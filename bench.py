"""Benchmark: single-stream decode throughput of the flagship model on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Metric: batch=1 greedy decode tokens/sec for a Llama-3.2-1B-shaped model with
Q40 weights at rest in HBM (int4+f16 scales, dequant-in-matmul Pallas kernel
— the same weight format the reference runs, src/nn/nn-quants.hpp:64-67) and
a 2048-token KV cache. Extras: effective weight-read bandwidth, MFU, and
kernel ablations (packed Q40 via XLA dequant, dense bf16) so the Pallas
kernel's contribution is in the artifact, not a commit message.

Resilience (round 1 shipped rc=1 with zero perf evidence when the axon
backend failed at init): the top-level process is a thin watchdog that runs
the real bench in a child with a timeout, retries TPU init failures, falls
back to a small CPU run when the TPU never comes up, and — if everything
fails — still emits a diagnostic JSON line and exits 0 so the failure mode
is recorded in BENCH_r{N}.json instead of a traceback.

Timing is honest under async dispatch: the whole generation loop runs
device-side (lax.scan with the sampled token fed back), completion is forced
by fetching the produced tokens, and the reported rate is the MARGINAL rate
between a short and a long run — constant dispatch/transfer overheads cancel.

vs_baseline: ratio against the reference's best published single-device
number — Llama 2 7B on 1x RPi 4B at 1312.50 ms/token = 0.762 tok/s
(report.pdf Fig. 3, BASELINE.md). Model sizes differ (1B vs 7B); the
per-chip north star (BASELINE.md: Llama-3.1-8B Q40, >=200 tok/s/chip) is
benched by the optional BENCH_8B=1 path on real hardware.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_SINGLE_DEVICE_TOK_S = 1000.0 / 1312.50  # report.pdf Fig. 3
METRIC = "llama32_1b_q40_decode_tok_s"

# bf16 peak TFLOP/s and HBM GB/s per chip by device kind (public specs)
_CHIP_SPECS = {
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5": (459e12, 2765e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
}


def _chip_spec(device_kind: str):
    for k, v in _CHIP_SPECS.items():
        if device_kind.lower().startswith(k.lower()):
            return v
    return None, None


# ---------------------------------------------------------------------------
# Child: the actual benchmark (runs under the watchdog).
# ---------------------------------------------------------------------------


def _tree_device_bytes(tree) -> int:
    import jax

    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def _bench_decode(config, params, n_short, n_long, reps=3, tag=""):
    """Marginal decode tok/s for one param set."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_llama_multiusers_tpu.models import init_kv_cache, llama_forward

    def make_generate(n_steps):
        @partial(jax.jit, donate_argnums=(1,))
        def generate(params, cache, first_token, start_pos):
            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = llama_forward(
                    config, params, tok[:, None], pos[:, None], cache
                )
                nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
                return (nxt, pos + 1, cache), nxt

            (_, _, cache), toks = jax.lax.scan(
                body, (first_token, start_pos, cache), None, length=n_steps
            )
            return toks, cache

        return generate

    first = jnp.zeros((1,), jnp.int32)
    pos0 = jnp.zeros((1,), jnp.int32)

    def timed(n_steps):
        gen = make_generate(n_steps)
        best = float("inf")
        for _ in range(reps + 1):  # first rep is compile+warmup
            cache = init_kv_cache(config, n_lanes=1, dtype=jnp.bfloat16)
            t0 = time.perf_counter()
            toks, cache = gen(params, cache, first, pos0)
            np.asarray(toks)  # forces completion (block_until_ready may not)
            dt = time.perf_counter() - t0
            best = min(best, dt)
        return best

    t_short = timed(n_short)
    t_long = timed(n_long)
    print(f"[bench] {tag}: short({n_short})={t_short:.3f}s long({n_long})={t_long:.3f}s",
          file=sys.stderr, flush=True)
    if t_long - t_short > 0.1 * t_long:
        return (n_long - n_short) / (t_long - t_short)
    # marginal signal below dispatch-overhead noise: conservative whole-run rate
    return n_long / t_long


def child_main() -> None:
    # CPU runs must strip the TPU PJRT plugin BEFORE backend discovery: this
    # box's sitecustomize registers one whose init dials a network tunnel,
    # and it blocks discovery even under JAX_PLATFORMS=cpu (see
    # utils/testing.force_cpu_mesh — the same reason round 1's bench hung)
    if os.environ.get("BENCH_FORCE_CPU") == "1" or os.environ.get("JAX_PLATFORMS") == "cpu":
        from distributed_llama_multiusers_tpu.utils.testing import force_cpu_mesh

        force_cpu_mesh(n_devices=1)

    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _flagship_config
    from distributed_llama_multiusers_tpu.models import params_from_random
    from distributed_llama_multiusers_tpu.models.loader import quantize_params
    from distributed_llama_multiusers_tpu.ops import linear

    dev = jax.devices()[0]
    platform = dev.platform
    device_kind = getattr(dev, "device_kind", platform)
    print(f"[bench] backend up: {platform} ({device_kind})", file=sys.stderr, flush=True)

    small = os.environ.get("GRAFT_SMALL") == "1" or platform != "tpu"
    config = _flagship_config(small=small)
    n_short, n_long = (4, 16) if small else (16, 128)

    # generate + quantize host-side; upload only the packed ~4.5-bit planes
    host_dense = params_from_random(config, seed=0, dtype=jnp.bfloat16, to_device=False)
    host_q = quantize_params(host_dense, to_device=False)
    params_q = jax.tree.map(jax.device_put, host_q)

    tok_s = _bench_decode(config, params_q, n_short, n_long, tag="packed+pallas")

    weight_bytes = _tree_device_bytes(params_q)
    peak_flops, peak_bw = _chip_spec(str(device_kind))
    n_param_flops = 2 * sum(
        x.size for x in jax.tree.leaves(host_dense)
    )  # 2*params matmul FLOPs/token (upper bound incl. embedding)

    result = {
        "metric": METRIC,
        "value": round(tok_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / REFERENCE_SINGLE_DEVICE_TOK_S, 2),
        "platform": platform,
        "device_kind": str(device_kind),
        "weight_read_gb_s": round(weight_bytes * tok_s / 1e9, 1),
        "mfu": round(n_param_flops * tok_s / peak_flops, 4) if peak_flops else None,
        "hbm_util": round(weight_bytes * tok_s / peak_bw, 3) if peak_bw else None,
        "baseline_note": "reference Llama-2-7B on 1x RPi 4B, 0.762 tok/s (report.pdf Fig.3)",
    }
    # bank the primary metric NOW: the watchdog parses the LAST stdout JSON
    # line, so if the ablations/8B extras below blow the child's time budget
    # or crash, this line still carries the measurement (round 1 failure mode)
    print(json.dumps(result), flush=True)

    # --- ablations: what the Pallas kernel buys over XLA dequant / dense ---
    if os.environ.get("BENCH_ABLATIONS", "1") == "1":
        linear.set_pallas_enabled(False)
        try:
            result["ablation_xla_dequant_tok_s"] = round(
                _bench_decode(config, params_q, n_short, n_long, tag="packed+xla-dequant"), 2
            )
        finally:
            linear.set_pallas_enabled(True)
        del params_q
        params_d = jax.tree.map(jax.device_put, host_dense)
        result["ablation_dense_bf16_tok_s"] = round(
            _bench_decode(config, params_d, n_short, n_long, tag="dense-bf16"), 2
        )
        del params_d

    # --- optional: the BASELINE north-star model (Llama-3.1-8B geometry) ---
    if os.environ.get("BENCH_8B") == "1" and platform == "tpu":
        from distributed_llama_multiusers_tpu.models.config import LlamaConfig

        cfg8 = LlamaConfig(
            dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
            vocab_size=128256, seq_len=2048, rope_theta=500000.0,
            rope_scaling_factor=8.0, rope_scaling_low_freq_factor=1.0,
            rope_scaling_high_freq_factor=4.0, rope_scaling_orig_max_seq_len=8192,
        )
        print("[bench] generating 8B random Q40 params (host)...", file=sys.stderr, flush=True)
        host8 = quantize_params(
            params_from_random(cfg8, seed=0, dtype=jnp.bfloat16, to_device=False),
            to_device=False,
        )
        params8 = jax.tree.map(jax.device_put, host8)
        del host8
        tok8 = _bench_decode(cfg8, params8, 8, 64, reps=2, tag="8b packed+pallas")
        result["llama31_8b_q40_decode_tok_s"] = round(tok8, 2)
        result["llama31_8b_northstar_frac"] = round(tok8 / 200.0, 3)

    print(json.dumps(result), flush=True)


# ---------------------------------------------------------------------------
# Parent: watchdog. Retries, CPU fallback, diagnostic JSON on total failure.
# ---------------------------------------------------------------------------


def _text(x) -> str:
    if isinstance(x, bytes):
        return x.decode(errors="replace")
    return x or ""


def _last_json_line(out: str):
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_child(env_extra: dict, timeout_s: float):
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired as e:
        # a timed-out child may still have banked its primary-metric line
        parsed = _last_json_line(_text(e.stdout))
        if parsed is not None:
            parsed["timed_out_in_extras"] = True
            return parsed, None
        return None, f"timeout after {timeout_s:.0f}s; stderr tail: {_text(e.stderr)[-300:]}"
    parsed = _last_json_line(proc.stdout)
    if parsed is not None:
        if proc.returncode != 0:
            # extras crashed after the primary line was banked: keep the
            # evidence AND the failure, instead of an unmarked success
            parsed["crashed_in_extras"] = _text(proc.stderr)[-300:]
        return parsed, None
    return None, f"rc={proc.returncode}; stderr tail: {_text(proc.stderr)[-400:]}"


def main() -> None:
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE", "2700"))
    errors = []

    # TPU attempts (the axon backend is flaky at init: round 1 died there)
    for attempt in range(2):
        budget = min(1500.0, deadline - time.monotonic())
        if budget < 120:
            break
        result, err = _run_child({}, budget)
        if result is not None:
            result["attempts"] = attempt + 1
            print(json.dumps(result))
            return
        errors.append(f"tpu[{attempt}]: {err}")
        print(f"[bench-watchdog] {errors[-1]}", file=sys.stderr, flush=True)
        if attempt < 1:  # no point sleeping after the final attempt
            time.sleep(20)

    # CPU fallback: degraded evidence beats no evidence
    budget = max(120.0, deadline - time.monotonic())
    result, err = _run_child(
        {"BENCH_FORCE_CPU": "1", "GRAFT_SMALL": "1", "BENCH_ABLATIONS": "0"}, budget
    )
    if result is not None:
        result["platform"] = "cpu-fallback"
        result["tpu_errors"] = errors
        print(json.dumps(result))
        return
    errors.append(f"cpu: {err}")

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": None,
                "unit": "tok/s",
                "vs_baseline": None,
                "error": "; ".join(errors)[-1200:],
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        child_main()
    else:
        main()
