#!/bin/bash
# One-shot hardware evidence ladder for the round-4 verdict's top items.
# Run the moment the TPU tunnel answers (scripts/tpu_watch.sh exits 0):
#   1. stage_probe  — DMA-only bandwidth floor for the slab layout
#   2. kernel_sweep — A/B the slab kernel's DLLAMA_* DMA-geometry knobs
#   3. bench.py     — full artifact: primary + serving + 8b north star +
#                     bf16 parity + ablations + in-bench sweep
# Everything is logged under scripts/hw_proof_<ts>/ so a dying tunnel
# still leaves partial evidence on disk.
set -u
DIR="$(cd "$(dirname "$0")" && pwd)"
REPO="$(dirname "$DIR")"
TS=$(date +%Y%m%d_%H%M%S)
OUT="$DIR/hw_proof_$TS"
mkdir -p "$OUT"
cd "$REPO"

echo "== stage_probe (DMA floor) ==" | tee "$OUT/status"
timeout "${PROBE_BUDGET_S:-420}" python scripts/stage_probe.py \
  > "$OUT/stage_probe.log" 2>&1
echo "stage_probe rc=$?" | tee -a "$OUT/status"

echo "== kernel_sweep ==" | tee -a "$OUT/status"
timeout "${SWEEP_BUDGET_S:-1500}" python scripts/kernel_sweep.py 280 \
  > "$OUT/kernel_sweep.log" 2>&1
echo "kernel_sweep rc=$?" | tee -a "$OUT/status"
grep -E "BEST|tok/s" "$OUT/kernel_sweep.log" | tail -8 | tee -a "$OUT/status"

# NOTE: deliberately NOT exporting the sweep winner's DLLAMA_* knobs into
# the bench environment — bench.py runs its own in-bench sweep, adopts a
# winner itself, and records `kernel_knobs` in the artifact, so the
# headline stays attributed to the geometry that produced it.
echo "== bench ==" | tee -a "$OUT/status"
timeout "${BENCH_BUDGET_S:-1400}" python bench.py \
  > "$OUT/bench.out" 2> "$OUT/bench.err"
echo "bench rc=$?" | tee -a "$OUT/status"
tail -1 "$OUT/bench.out" | tee -a "$OUT/status"
