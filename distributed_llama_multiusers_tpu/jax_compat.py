"""jax version compatibility for ``shard_map``.

jax >= 0.6 spells the replication-check kwarg ``check_vma``; older
versions spell it ``check_rep`` (and the oldest only export shard_map
from ``jax.experimental``). The kwarg is detected by signature, not by
import location — some versions export top-level ``jax.shard_map`` while
still spelling the kwarg ``check_rep``. Every shard_map call site in the
package imports from here so both spellings work.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # pre-top-level-export versions
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )
