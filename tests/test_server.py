"""HTTP API server tests — multi-user path (reference: src/dllama-api.cpp),
including true concurrent requests, which the fork's serialized accept loop
could not do."""

import json
import threading
import urllib.request

import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import ContinuousBatchingScheduler, InferenceEngine
from distributed_llama_multiusers_tpu.server import ApiServer
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer


@pytest.fixture(scope="module")
def server(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    engine = InferenceEngine(config, params, n_lanes=4, prefill_buckets=(16, 32))
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    api = ApiServer(sched, tok, model_name="tiny-test")
    httpd = api.serve(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()
    sched.stop()


def post(url, body, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_models_endpoint(server):
    with urllib.request.urlopen(server + "/v1/models", timeout=30) as r:
        body = json.loads(r.read())
    assert body["object"] == "list"
    assert body["data"][0]["id"] == "tiny-test"


def test_chat_completion(server):
    status, body = post(
        server + "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hello"}], "max_tokens": 6, "temperature": 0},
    )
    assert status == 200
    assert "generated_text" in body  # fork web-ui compat (web-ui/app.js:27-40)
    assert body["choices"][0]["message"]["content"] == body["generated_text"]
    assert body["usage"]["completion_tokens"] <= 6
    assert body["usage"]["prompt_tokens"] > 0


def test_concurrent_chat_completions(server):
    """4 simultaneous clients — all served through the shared batch."""
    results = {}
    errors = []

    def worker(i):
        try:
            results[i] = post(
                server + "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hello"}], "max_tokens": 5, "temperature": 0},
            )
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors
    assert len(results) == 4
    texts = {r[1]["generated_text"] for r in results.values()}
    assert len(texts) == 1  # same prompt, temp 0 -> identical outputs


def test_bad_request(server):
    req = urllib.request.Request(
        server + "/v1/chat/completions", data=b'{"messages": []}',
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_unknown_route(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(server + "/nope", timeout=30)
    assert e.value.code == 404


def test_streaming_sse(server):
    req = urllib.request.Request(
        server + "/v1/chat/completions",
        data=json.dumps(
            {"messages": [{"role": "user", "content": "hello"}], "max_tokens": 6,
             "temperature": 0, "stream": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    chunks = []
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        for line in r:
            line = line.decode().strip()
            if line.startswith("data: "):
                chunks.append(line[6:])
    assert chunks[-1] == "[DONE]"
    payloads = [json.loads(c) for c in chunks[:-1]]
    # truncated by max_tokens=6 -> accurate finish_reason
    assert payloads[-1]["choices"][0]["finish_reason"] in ("length", "stop")
    streamed = "".join(
        p["choices"][0]["delta"].get("content", "") for p in payloads
    )
    # must equal the non-streaming output for the same input
    _, full = post(
        server + "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hello"}], "max_tokens": 6, "temperature": 0},
    )
    assert streamed == full["generated_text"]


def test_cors_preflight(server):
    req = urllib.request.Request(server + "/v1/chat/completions", method="OPTIONS")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 204
        assert r.headers["Access-Control-Allow-Origin"] == "*"


def test_streaming_bad_request_gets_400(server):
    """Validation must happen before SSE headers commit."""
    req = urllib.request.Request(
        server + "/v1/chat/completions",
        data=json.dumps({"stream": True}).encode(),  # no messages
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_stats_endpoint(server):
    """GET /stats reports engine counters + lane occupancy (beyond reference
    parity: the reference has no metrics endpoint, SURVEY §5.5)."""
    # generate something first so counters are non-zero
    post(
        server + "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 3,
         "temperature": 0},
    )
    with urllib.request.urlopen(server + "/stats", timeout=30) as r:
        body = json.loads(r.read())
    assert body["decode_steps"] >= 1
    assert body["lanes_total"] >= 1
    assert 0 <= body["lanes_busy"] <= body["lanes_total"]
    assert "spec_tokens_per_lane_step" in body
    assert "spec_lane_steps" in body
    # dequant attribution (ops/dequant_select): every /stats payload names
    # the resolved dequant mode; under auto it adds per-site resolutions
    from distributed_llama_multiusers_tpu.ops.pallas_q40 import SELECTABLE_MODES

    assert body["dequant_mode"] in SELECTABLE_MODES


def test_text_completion(server):
    """/v1/completions (beyond parity): raw prompt, no chat template."""
    status, body = post(
        server + "/v1/completions",
        {"prompt": "hello world", "max_tokens": 6, "temperature": 0},
    )
    assert status == 200
    assert body["object"] == "text_completion"
    assert body["choices"][0]["text"] == body["generated_text"]
    assert body["usage"]["completion_tokens"] <= 6
    # 1-element list form is accepted; longer lists are a clean 400
    status2, body2 = post(
        server + "/v1/completions",
        {"prompt": ["hello world"], "max_tokens": 6, "temperature": 0},
    )
    assert status2 == 200 and body2["generated_text"] == body["generated_text"]
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as e3:
        post(server + "/v1/completions", {"prompt": ["a", "b"], "max_tokens": 4})
    assert e3.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e4:
        post(server + "/v1/completions", {"max_tokens": 4})
    assert e4.value.code == 400


def test_text_completion_streaming(server):
    import urllib.request

    req = urllib.request.Request(
        server + "/v1/completions",
        data=json.dumps(
            {"prompt": "hello world", "max_tokens": 6, "temperature": 0,
             "stream": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    chunks = []
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        for line in r:
            line = line.decode().strip()
            if line.startswith("data: "):
                chunks.append(line[6:])
    assert chunks[-1] == "[DONE]"
    payloads = [json.loads(c) for c in chunks[:-1]]
    assert payloads[-1]["choices"][0]["finish_reason"] in ("length", "stop")
    streamed = "".join(p["choices"][0]["text"] for p in payloads)
    _, full = post(
        server + "/v1/completions",
        {"prompt": "hello world", "max_tokens": 6, "temperature": 0},
    )
    assert streamed == full["generated_text"]
