"""Token sampler — greedy / temperature / top-p, with the reference's RNG.

Port of Sampler (src/tokenizer.cpp:382-510). The xorshift64* RNG and the
nucleus-sampling cutoff pre-filter are reproduced exactly so that seeded runs
are comparable with the reference; the softmax runs in float32.
"""

from __future__ import annotations

import numpy as np

_MASK64 = (1 << 64) - 1


def _random_u32(state: int) -> tuple[int, int]:
    """xorshift64* (src/tokenizer.cpp:25-31). Returns (value, new_state)."""
    state &= _MASK64
    state ^= state >> 12
    state ^= (state << 25) & _MASK64
    state ^= state >> 27
    state &= _MASK64
    return ((state * 0x2545F4914F6CDD1D) & _MASK64) >> 32, state


def _random_f32(state: int) -> tuple[float, int]:
    u, state = _random_u32(state)
    return (u >> 8) / 16777216.0, state


class Sampler:
    def __init__(self, vocab_size: int, temperature: float, topp: float, rng_seed: int):
        self.vocab_size = vocab_size
        self.temperature = float(temperature)
        self.topp = float(topp)
        self.rng_state = int(rng_seed) & _MASK64

    def set_temp(self, temperature: float) -> None:
        self.temperature = float(temperature)

    def set_seed(self, seed: int) -> None:
        self.rng_state = int(seed) & _MASK64

    def sample(self, logits: np.ndarray) -> int:
        logits = np.asarray(logits, dtype=np.float32)[: self.vocab_size]
        if self.temperature == 0.0:
            return int(np.argmax(logits))
        x = logits / self.temperature
        x = x - x.max()
        probs = np.exp(x, dtype=np.float32)
        probs /= probs.sum(dtype=np.float32)
        coin, self.rng_state = _random_f32(self.rng_state)
        if self.topp <= 0 or self.topp >= 1:
            return self._sample_mult(probs, coin)
        return self._sample_topp(probs, coin)

    @staticmethod
    def _sample_mult(probs: np.ndarray, coin: float) -> int:
        cdf = np.cumsum(probs, dtype=np.float64)
        idx = int(np.searchsorted(cdf, coin, side="right"))
        return min(idx, len(probs) - 1)

    def _sample_topp(self, probs: np.ndarray, coin: float) -> int:
        n = len(probs)
        # cutoff pre-filter (src/tokenizer.cpp:426-433)
        cutoff = (1.0 - self.topp) / (n - 1)
        idx = np.nonzero(probs >= cutoff)[0]
        if len(idx) == 0:
            # nothing passes the pre-filter (tiny topp over a near-uniform
            # distribution); the reference reads out of bounds here — fall
            # back to plain multinomial instead
            return self._sample_mult(probs, coin)
        order = idx[np.argsort(-probs[idx], kind="stable")]
        p = probs[order]
        csum = np.cumsum(p, dtype=np.float64)
        over = np.nonzero(csum > self.topp)[0]
        last = int(over[0]) if len(over) else len(order) - 1
        cumulative = float(csum[last])
        r = coin * cumulative
        pick = int(np.searchsorted(csum[: last + 1], r, side="right"))
        return int(order[min(pick, last)])
