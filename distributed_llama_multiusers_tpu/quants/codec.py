"""Block-quantization codecs, bit-exact with the reference formats.

Reference semantics (cited file:line are into /root/reference):

- Q40: 32-element blocks, one fp16 scale ``d = signed_absmax / -8`` and 16
  packed nibble bytes; encode is ``clip(trunc(x/d + 8.5), 0, 15)``
  (converter/writer.py:29-53, src/nn/nn-quants.cpp:193-227); decode is
  ``(nibble - 8) * d`` with the low nibbles holding elements [0,16) and the
  high nibbles elements [16,32) (src/nn/nn-quants.cpp:229-246).
- Q80: 32-element blocks, fp16 scale ``d = absmax / 127``, 32 int8 values
  ``round(x/d)`` (converter/writer.py:55-74, src/nn/nn-quants.cpp:154-172).
  NOTE: the reference converter rounds ties-to-even (np.round) while the
  C++ runtime quantizer rounds ties-away-from-zero (roundf); both are
  provided here via ``mode`` so each call site can match its counterpart.
- F16 scale conversion is IEEE half round-to-nearest-even, which numpy's
  float16 cast implements (matches src/nn/nn-quants.cpp:35-65).

All functions are vectorized numpy; these are the host-side codecs used by
the converter, the weight loader, and as the golden oracle for the on-device
JAX codecs in ``jax_codec.py``.
"""

from __future__ import annotations

import numpy as np

Q40_BLOCK_SIZE = 32
Q80_BLOCK_SIZE = 32
Q40_BLOCK_BYTES = 2 + Q40_BLOCK_SIZE // 2  # fp16 scale + 16 nibble bytes
Q80_BLOCK_BYTES = 2 + Q80_BLOCK_SIZE  # fp16 scale + 32 int8


class FloatType:
    """Tensor element-type ids used by the .m format (src/nn/nn-quants.hpp:56-62)."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3


_FLOAT_TYPE_NAMES = {
    FloatType.F32: "f32",
    FloatType.F16: "f16",
    FloatType.Q40: "q40",
    FloatType.Q80: "q80",
}


def float_type_name(float_type: int) -> str:
    return _FLOAT_TYPE_NAMES[float_type]


def tensor_bytes(float_type: int, n_elements: int) -> int:
    """On-disk byte size of a flat tensor (src/nn/nn-core.cpp getBytes)."""
    if float_type == FloatType.F32:
        return 4 * n_elements
    if float_type == FloatType.F16:
        return 2 * n_elements
    if float_type == FloatType.Q40:
        assert n_elements % Q40_BLOCK_SIZE == 0
        return (n_elements // Q40_BLOCK_SIZE) * Q40_BLOCK_BYTES
    if float_type == FloatType.Q80:
        assert n_elements % Q80_BLOCK_SIZE == 0
        return (n_elements // Q80_BLOCK_SIZE) * Q80_BLOCK_BYTES
    raise ValueError(f"unsupported float type {float_type}")


def _round_half_away(x: np.ndarray) -> np.ndarray:
    """C roundf: round half away from zero (vs np.round's ties-to-even)."""
    return np.sign(x) * np.floor(np.abs(x) + 0.5)


def quantize_q40(x: np.ndarray) -> np.ndarray:
    """Quantize float32 array (flat, multiple of 32) to packed Q40 bytes.

    Returns a uint8 array of shape [nBlocks, 18]: bytes 0:2 are the fp16
    scale (little-endian), bytes 2:18 the packed nibbles. Bit-exact with
    converter/writer.py:29-53 (the producer of .m files) which itself matches
    src/nn/nn-quants.cpp:193-227 for all inputs (both truncate toward zero
    after the +8.5 offset; values are always positive there).
    """
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    assert x.size % Q40_BLOCK_SIZE == 0, x.size
    groups = x.reshape(-1, Q40_BLOCK_SIZE)
    gmax = groups.max(axis=1)
    gmin = groups.min(axis=1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    deltas16 = deltas.astype(np.float16)
    ids = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = groups * ids[:, None] + 8.5
    q = np.clip(q, 0, 15).astype(np.int64)  # trunc toward zero; q >= 0
    half = Q40_BLOCK_SIZE // 2
    packed = (q[:, :half] & 0xF) | ((q[:, half:] & 0xF) << 4)
    out = np.empty((groups.shape[0], Q40_BLOCK_BYTES), dtype=np.uint8)
    out[:, 0:2] = deltas16.view(np.uint16).astype("<u2").view(np.uint8).reshape(-1, 2)
    out[:, 2:] = packed.astype(np.uint8)
    return out


def dequantize_q40(blocks: np.ndarray) -> np.ndarray:
    """Packed Q40 bytes [nBlocks, 18] -> float32 flat array.

    Matches src/nn/nn-quants.cpp:229-246: low nibbles are elements [0,16),
    high nibbles elements [16,32) of each block.
    """
    values, scales = q40_to_planar(blocks)
    return (values.astype(np.float32) * scales[:, None].astype(np.float32)).reshape(-1)


def q40_to_planar(blocks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Packed Q40 -> (int8 values [nBlocks, 32] centered at 0, f32 scales [nBlocks]).

    The planar layout feeds the on-device dequant-matmul path.
    """
    blocks = np.asarray(blocks, dtype=np.uint8).reshape(-1, Q40_BLOCK_BYTES)
    scales = blocks[:, 0:2].copy().view("<u2").view(np.float16).astype(np.float32).reshape(-1)
    qs = blocks[:, 2:]
    low = (qs & 0x0F).astype(np.int8) - 8
    high = (qs >> 4).astype(np.int8) - 8
    values = np.concatenate([low, high], axis=1)
    return values, scales


def quantize_q80(x: np.ndarray, mode: str = "runtime") -> np.ndarray:
    """Quantize float32 (flat, multiple of 32) to packed Q80 bytes [nBlocks, 34].

    mode="runtime" rounds half away from zero (src/nn/nn-quants.cpp:169
    roundf); mode="converter" rounds ties-to-even (converter/writer.py:67
    np.round). The two differ only on exact .5 scaled values.
    """
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    assert x.size % Q80_BLOCK_SIZE == 0
    groups = x.reshape(-1, Q80_BLOCK_SIZE)
    amax = np.abs(groups).max(axis=1)
    deltas = amax / 127.0
    deltas16 = deltas.astype(np.float16)
    ids = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    scaled = groups * ids[:, None]
    if mode == "runtime":
        q = _round_half_away(scaled)
    elif mode == "converter":
        q = np.round(scaled)
    else:
        raise ValueError(mode)
    q = q.astype(np.int8)
    out = np.empty((groups.shape[0], Q80_BLOCK_BYTES), dtype=np.uint8)
    out[:, 0:2] = deltas16.view(np.uint16).astype("<u2").view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q.view(np.uint8)
    return out


def dequantize_q80(blocks: np.ndarray) -> np.ndarray:
    """Packed Q80 bytes [nBlocks, 34] -> float32 flat (src/nn/nn-quants.cpp:175-191)."""
    values, scales = q80_to_planar(blocks)
    return (values.astype(np.float32) * scales[:, None].astype(np.float32)).reshape(-1)


def q80_to_planar(blocks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Packed Q80 -> (int8 values [nBlocks, 32], f32 scales [nBlocks])."""
    blocks = np.asarray(blocks, dtype=np.uint8).reshape(-1, Q80_BLOCK_BYTES)
    scales = blocks[:, 0:2].copy().view("<u2").view(np.float16).astype(np.float32).reshape(-1)
    values = blocks[:, 2:].copy().view(np.int8)
    return values, scales


def quantize_dequantize_q80(x: np.ndarray, mode: str = "runtime") -> np.ndarray:
    """Round-trip through Q80 — emulates the reference's activation-sync
    quantization (cast F32->Q80 before every TP sync, src/llm.cpp:150)."""
    return dequantize_q80(quantize_q80(x, mode=mode)).reshape(x.shape)
