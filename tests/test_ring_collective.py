"""Ring collectives (ops/ring_collective.py): parity vs the XLA
collectives they replace, on the virtual CPU mesh.

Gate classes (ISSUE 7 acceptance):
- f32 ring reduce-scatter / all-gather / all-reduce match
  lax.psum_scatter-style / all_gather / psum EXACTLY on integer-valued
  f32 (any summation order is exact there), and to fp tolerance on random
  values; odd AND even ring sizes.
- the Q80 wire matches the plain gather within the documented ~1e-2
  class, and matches the q80 qdq codec EXACTLY (same block rounding).
- DLLAMA_RING_SYNC=off (set_ring_sync(False)) restores the psum path:
  the partitioned Q40 matmul's col-sliced sync goes back to lax.psum
  bit-for-bit.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_llama_multiusers_tpu.jax_compat import shard_map
from distributed_llama_multiusers_tpu.ops import ring_collective as rc
from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
from distributed_llama_multiusers_tpu.quants.jax_codec import qdq_q80
from distributed_llama_multiusers_tpu.quants.packed import (
    PackedQ40,
    pack_q40_host,
    q40_matmul_xla,
)

pytestmark = pytest.mark.usefixtures("cpu_devices")


@pytest.fixture
def cpu_devices():
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-virtual-device CPU mesh (tests/conftest.py)")


def _partials(tp: int, width: int, seed: int = 0, exact: bool = True):
    """[tp, 2, width] per-device partial sums; integer-valued when exact
    (fp addition of small ints is exact in any order)."""
    rng = np.random.default_rng(seed)
    if exact:
        return rng.integers(-8, 8, (tp, 2, width)).astype(np.float32)
    return rng.standard_normal((tp, 2, width)).astype(np.float32)


def _run_local(fn, mesh, x, out_spec):
    """Feed each tp shard its own partial (leading axis sharded over tp)."""
    xs = jax.device_put(x, NamedSharding(mesh, P("tp", None, None)))
    return np.asarray(
        shard_map(
            fn, mesh=mesh, in_specs=(P("tp", None, None),),
            out_specs=out_spec, check_vma=False,
        )(xs)
    )


@pytest.mark.parametrize("tp", [2, 3, 4])
def test_ring_reduce_scatter_matches_sum(tp):
    """Even AND odd ring sizes: device r ends with exactly the reduced
    chunk r (integer values -> order-independent exact sums)."""
    mesh = make_mesh(MeshPlan(tp=tp))
    x = _partials(tp, 12 * tp)
    got = _run_local(
        lambda xl: rc.ring_reduce_scatter(xl[0], "tp", tp),
        mesh, x, P(None, "tp"),
    )
    assert np.array_equal(got, x.sum(axis=0))


@pytest.mark.parametrize("tp", [2, 3, 4])
def test_ring_all_reduce_matches_psum(tp):
    mesh = make_mesh(MeshPlan(tp=tp))
    x = _partials(tp, 8 * tp, seed=1)
    got = _run_local(
        lambda xl: rc.ring_all_reduce(xl[0], "tp", tp),
        mesh, x, P(None, None),
    )
    want = _run_local(
        lambda xl: jax.lax.psum(xl[0], "tp"), mesh, x, P(None, None)
    )
    assert np.array_equal(got, want)  # integer-valued: exact either way


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_ring_all_reduce_random_f32_tolerance():
    """Random f32: ring order vs XLA's reduction tree differ only in
    associativity — same f32 class."""
    tp = 4
    mesh = make_mesh(MeshPlan(tp=tp))
    x = _partials(tp, 32, seed=2, exact=False)
    got = _run_local(
        lambda xl: rc.ring_all_reduce(xl[0], "tp", tp),
        mesh, x, P(None, None),
    )
    want = x.sum(axis=0)
    assert np.allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("tp", [2, 3, 4])
def test_ring_all_gather_matches_all_gather(tp):
    """Gather moves bits: exact vs lax.all_gather, any ring size."""
    mesh = make_mesh(MeshPlan(tp=tp))
    x = _partials(tp, 6, seed=3, exact=False)
    got = _run_local(
        lambda xl: rc.ring_all_gather(xl[0], "tp", tp),
        mesh, x, P(None, None),
    )

    def ref(xl):
        g = jax.lax.all_gather(xl[0], "tp", axis=0)  # [tp, 2, 6]
        return jnp.concatenate([g[i] for i in range(tp)], axis=-1)

    want = _run_local(ref, mesh, x, P(None, None))
    assert np.array_equal(got, want)


def test_ring_all_gather_q80_wire_class():
    """The compressed wire: within the documented ~1e-2 class of the f32
    gather, and EXACTLY the q80 qdq codec's block rounding per chunk (the
    wire IS the codec — parity with q80_all_gather semantics)."""
    tp = 4
    mesh = make_mesh(MeshPlan(tp=tp))
    x = _partials(tp, 64, seed=4, exact=False)  # chunk 64 % 32 == 0
    got = _run_local(
        lambda xl: rc.ring_all_gather_q80(xl[0], "tp", tp),
        mesh, x, P(None, None),
    )
    exact = _run_local(
        lambda xl: rc.ring_all_gather(xl[0], "tp", tp),
        mesh, x, P(None, None),
    )
    scale = np.abs(exact).max()
    assert np.abs(got - exact).max() <= 2e-2 * scale
    # bit-for-bit the codec's rounding: chunk k == qdq_q80(device k's data)
    want = np.concatenate(
        [np.asarray(qdq_q80(jnp.asarray(x[i]), mode="converter")) for i in range(tp)],
        axis=-1,
    )
    assert np.array_equal(got, want)


def test_ring_all_reduce_fallback_indivisible():
    """A width the ring cannot chunk falls back to psum inside
    ring_all_reduce — callers may substitute unconditionally."""
    tp = 4
    mesh = make_mesh(MeshPlan(tp=tp))
    x = _partials(tp, 30, seed=5)  # 30 % 4 != 0
    got = _run_local(
        lambda xl: rc.ring_all_reduce(xl[0], "tp", tp),
        mesh, x, P(None, None),
    )
    assert np.array_equal(got, x.sum(axis=0))


# ---------------------------------------------------------------------------
# The fused form: ring_sync_matmul.
# ---------------------------------------------------------------------------


def _packed_weight(d_in, d_out, seed=0):
    rng = np.random.default_rng(seed)
    return PackedQ40(*map(
        jnp.asarray, pack_q40_host(
            rng.standard_normal((d_out, d_in)).astype(np.float32) * 0.1
        )
    ))


@pytest.mark.parametrize("tp", [2, 4])
def test_ring_sync_matmul_dense(tp):
    mesh = make_mesh(MeshPlan(tp=tp))
    rng = np.random.default_rng(6)
    x = rng.standard_normal((3, 32 * tp)).astype(np.float32)
    w = rng.standard_normal((32 * tp, 16 * tp)).astype(np.float32)
    got = np.asarray(rc.ring_sync_matmul(jnp.asarray(x), jnp.asarray(w), mesh))
    want = x @ w
    assert np.allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_ring_sync_matmul_packed_q40():
    """The serving form: col-sliced PackedQ40 planes, dequant-in-matmul
    per column chunk, ring-reduced — matches the unsharded Q40 matmul."""
    tp = 4
    mesh = make_mesh(MeshPlan(tp=tp))
    rng = np.random.default_rng(7)
    x = rng.standard_normal((3, 128)).astype(np.float32)
    w = _packed_weight(128, 128, seed=7)
    got = np.asarray(rc.ring_sync_matmul(jnp.asarray(x), w, mesh))
    want = np.asarray(q40_matmul_xla(jnp.asarray(x), w))
    scale = np.abs(want).max() + 1e-9
    assert np.abs(got - want).max() / scale < 1e-5


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_ring_sync_matmul_q80_wire():
    """Q80 wire engages on the gather half only: within the reference
    transport's ~1e-2 class of the f32-wire result."""
    tp = 4
    mesh = make_mesh(MeshPlan(tp=tp))
    rng = np.random.default_rng(8)
    x = rng.standard_normal((3, 128)).astype(np.float32)
    w = _packed_weight(128, 256, seed=8)  # chunk 64: whole Q80 blocks
    f32 = np.asarray(rc.ring_sync_matmul(jnp.asarray(x), w, mesh))
    q80 = np.asarray(rc.ring_sync_matmul(jnp.asarray(x), w, mesh, q80_wire=True))
    scale = np.abs(f32).max() + 1e-9
    assert np.abs(q80 - f32).max() / scale < 2e-2
    assert not np.array_equal(q80, f32)  # the wire really quantized


def test_ring_sync_matmul_rejects_indivisible():
    tp = 4
    mesh = make_mesh(MeshPlan(tp=tp))
    w = _packed_weight(128, 96, seed=9)  # 96 % 4 == 0 but 24 % 32 != 0
    x = jnp.zeros((2, 128), jnp.float32)
    with pytest.raises(ValueError, match="whole Q80 blocks"):
        rc.ring_sync_matmul(x, w, mesh, q80_wire=True)
    w2 = _packed_weight(128, 30 * 2, seed=9)  # 60 % 4 == 0 -> ok f32
    assert rc.ring_sync_supported(60, 4) and not rc.ring_sync_supported(60, 4, True)
    with pytest.raises(ValueError, match="divisible"):
        rc.ring_sync_matmul(x, _packed_weight(128, 90, seed=9), mesh)  # 90 % 4


# ---------------------------------------------------------------------------
# Escape hatch + engagement predicate.
# ---------------------------------------------------------------------------


def test_escape_hatch_restores_psum_path():
    """set_ring_sync(False): the partitioned Q40 matmul's col-sliced sync
    is lax.psum again — bit-for-bit the manual shard_map psum reference —
    and ring_sync_engages goes False everywhere."""
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.ops.pallas_q40 import (
        _q40_mm_impl,
        q40_matmul_partitioned,
    )

    tp = 4
    mesh = make_mesh(MeshPlan(tp=tp))
    rng = np.random.default_rng(10)
    x = rng.standard_normal((2, 128)).astype(np.float32)
    w = _packed_weight(128, 64, seed=10)

    # col-sliced layout: x last dim + packed plane rows sharded over tp.
    # interpret=True is the CPU convention for the partitioned kernel
    # (linear.matmul only routes here with pallas interpret on, as the
    # mesh tests do) — the escape-hatch contract is about the SYNC step.
    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(None, "tp")))
    wp = jax.device_put(w.packed, NamedSharding(mesh, P("tp", None)))
    ws = jax.device_put(w.scales, NamedSharding(mesh, P("tp", None)))
    shw = PackedQ40(wp, ws)

    def part_fn(a, b):
        # fresh jit per call: the ring flag is read at trace time, so a
        # shared cache would serve the first trace for both settings
        return jax.jit(
            lambda a_, b_: q40_matmul_partitioned(a_, b_, interpret=True)
        )(a, b)

    def manual_psum_ref():
        # EXACTLY the per-shard computation the partitioned path runs
        # (_q40_mm_impl), followed by a plain psum — the pre-ring lowering
        def inner(xl, pl_, sl):
            part = _q40_mm_impl(xl, pl_, sl, True, None)
            return jax.lax.psum(part, "tp")

        return np.asarray(shard_map(
            inner, mesh=mesh,
            in_specs=(P(None, "tp"), P("tp", None), P("tp", None)),
            out_specs=P(None, None), check_vma=False,
        )(xs, wp, ws))

    prev = rc.ring_sync_enabled()
    try:
        rc.set_ring_sync(False)
        assert not rc.ring_sync_engages(
            LlamaConfig(dim=64, hidden_dim=128, n_layers=1, n_heads=4,
                        n_kv_heads=4, vocab_size=64, seq_len=16),
            {"tp": 4},
        )
        off = np.asarray(part_fn(xs, shw))
        assert np.array_equal(off, manual_psum_ref())  # bit-for-bit psum
        rc.set_ring_sync(True)
        on = np.asarray(part_fn(xs, shw))
        # ring vs psum: same f32 class (exact at any tp for these magnitudes
        # is not guaranteed, but the class is)
        scale = np.abs(off).max() + 1e-9
        assert np.abs(on - off).max() / scale < 1e-5
    finally:
        rc.set_ring_sync(prev)


def test_ring_sync_engages_pure_tp_only():
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig

    cfg = LlamaConfig(dim=64, hidden_dim=128, n_layers=1, n_heads=4,
                      n_kv_heads=4, vocab_size=64, seq_len=16)
    prev = rc.ring_sync_enabled()
    try:
        rc.set_ring_sync(True)
        assert rc.ring_sync_engages(cfg, {"tp": 4})
        assert not rc.ring_sync_engages(cfg, {"tp": 1})
        assert not rc.ring_sync_engages(cfg, {"tp": 2, "sp": 2})
        assert not rc.ring_sync_engages(cfg, {"tp": 2, "dp": 2})
    finally:
        rc.set_ring_sync(prev)


def test_forward_ring_on_off_parity():
    """Pure-TP llama_forward: ring on vs off vs mesh-free all in the same
    f32 class (the serving-path integration, wo/w2 through the ring)."""
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
    )
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.parallel import (
        validate_mesh_for_config,
    )
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    config = LlamaConfig(dim=64, hidden_dim=128, n_layers=2, n_heads=8,
                         n_kv_heads=4, vocab_size=128, seq_len=32)
    plan = MeshPlan(tp=4)
    validate_mesh_for_config(config, plan)
    mesh = make_mesh(plan)
    params = params_from_random(config, seed=0, dtype=jnp.float32)
    sp = shard_params(params, mesh)
    rng = np.random.default_rng(11)
    toks = jnp.asarray(rng.integers(0, 128, (2, 8)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32)[None], (2, 8))

    def fwd(p, mesh_):
        logits, _ = jax.jit(
            lambda p_, t, q, c: llama_forward(config, p_, t, q, c, mesh=mesh_)
        )(p, toks, pos, init_kv_cache(config, 2))
        return np.asarray(logits)

    ref = fwd(params, None)
    prev = rc.ring_sync_enabled()
    try:
        rc.set_ring_sync(True)
        ring = fwd(sp, mesh)
        rc.set_ring_sync(False)
        psum = fwd(sp, mesh)
    finally:
        rc.set_ring_sync(prev)
    assert np.abs(ring - ref).max() < 1e-4
    assert np.abs(psum - ref).max() < 1e-4
    # greedy decisions identical: the serving stream-parity class
    assert np.array_equal(ring.argmax(-1), ref.argmax(-1))
