#!/usr/bin/env bash
# Local multi-process pod launcher — the analogue of the reference's
# examples/n-workers.sh local cluster harness (root + N-1 workers on one
# machine). Here the "cluster" is a jax.distributed pod: every process runs
# the same SPMD program over a global tp mesh, the root broadcasts a control
# packet per engine call and workers replay it (parallel/multihost.py).
#
# Usage:
#   examples/pod-launch.sh                 # 2-process pod, synthetic model
#   N=4 examples/pod-launch.sh             # 4-process pod
#   MODEL=llama.m TOK=llama.t examples/pod-launch.sh
#   MODE=api examples/pod-launch.sh        # root serves HTTP on $API_PORT
#
# Runs on CPU (one virtual device per process) so it works from a clean
# checkout with no TPU; on a real multi-host TPU pod, run the same commands
# on each host with --coordinator pointing at host 0.
set -euo pipefail
cd "$(dirname "$0")/.."

N=${N:-2}
PORT=${PORT:-$((20000 + RANDOM % 20000))}
API_PORT=${API_PORT:-8080}
MODE=${MODE:-inference}
WORKDIR=${WORKDIR:-/tmp/dllama-pod}
MODEL=${MODEL:-$WORKDIR/model.m}
TOK=${TOK:-$WORKDIR/tokenizer.t}
PROMPT=${PROMPT:-"hello world"}

mkdir -p "$WORKDIR"
if [ ! -f "$MODEL" ]; then
  echo "⭕ Writing synthetic model to $MODEL (set MODEL=/path/to/real.m to skip)"
  python - "$MODEL" "$TOK" <<'PY'
import sys
from distributed_llama_multiusers_tpu.formats.synthetic import (
    tiny_header, write_synthetic_model, write_synthetic_tokenizer,
)
h = tiny_header()
write_synthetic_model(sys.argv[1], h, seed=7)
write_synthetic_tokenizer(sys.argv[2], vocab_size=h.vocab_size)
PY
fi

# each process owns ONE virtual CPU device; the pod supplies N globally
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=1"

COMMON=(--coordinator "127.0.0.1:$PORT" --num-processes "$N"
        --model "$MODEL" --tokenizer "$TOK" --workers "tp$N")

WORKER_PIDS=()
cleanup() { kill "${WORKER_PIDS[@]}" 2>/dev/null || true; }
trap cleanup EXIT

for i in $(seq 1 $((N - 1))); do
  python -m distributed_llama_multiusers_tpu.app.dllama worker \
    "${COMMON[@]}" --process-id "$i" &
  WORKER_PIDS+=($!)
done

if [ "$MODE" = api ]; then
  # no exec: the EXIT trap must survive to reap the workers when the
  # server exits or is killed
  python -m distributed_llama_multiusers_tpu.app.dllama_api \
    "${COMMON[@]}" --process-id 0 --port "$API_PORT"
else
  python -m distributed_llama_multiusers_tpu.app.dllama inference \
    "${COMMON[@]}" --process-id 0 --prompt "$PROMPT" --steps "${STEPS:-16}"
fi

wait "${WORKER_PIDS[@]}"
echo "⭕ Pod exited cleanly"
