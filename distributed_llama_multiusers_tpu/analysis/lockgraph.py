"""Cross-file lock model: the shared substrate of the concurrency checks.

The four dlint v2 concurrency checks (``lock-order``, ``lock-blocking``,
``lock-atomicity``, ``pod-broadcast``) all need the same facts: which
attributes in the package ARE locks, which class owns each one, which
Condition is just a view of which lock, and which locks each
function/method acquires. This module collects them once per analyzer run
into a :class:`LockModel` stored on the shared ``Project``.

Lock identity is **class-qualified**: the node for ``self._lock`` inside
``QosQueue.__init__`` is ``"QosQueue._lock"``, so the three ``_m_lock``
instances in ``telemetry/metrics.py`` are three distinct nodes. Module-
level locks qualify by module stem (``native/__init__.py``'s ``_lock`` is
``"native._lock"``). These names are also the runtime witness's vocabulary
(``lockcheck.make_lock("QosQueue._lock")``): the collect pass recognizes
``make_lock`` declaration sites, reads the literal, and reports a
mismatch between the literal and the class-qualified attribute as a
finding — the static graph and the runtime witness cannot drift apart
silently.

A ``threading.Condition(self._lock)`` built over a known lock is an
**alias**: entering the condition IS entering the lock, so acquisitions
through either spelling resolve to one canonical node (exactly the
``("_lock", "_not_empty")`` equivalence the guarded-by declarations
already encode).

The **lock-order graph** has an edge A→B for every "A held while
acquiring B" site, including ONE level of intra-package calls: a
``with self._lock:`` body calling a method that itself takes a known
lock contributes an edge through that call. Edges carry their site and a
``waived`` flag (``# dlint: ok[lock-order] reason`` on the acquisition
line) so intentional nesting is both suppressed and documented in place.

Resolution is name-based like the rest of dlint (no type inference):
an attribute access resolves to the declaring class when the access sits
inside that class, then to a same-module declaration, then to a unique
project-wide declaration; ambiguous names resolve to nothing. Keep lock
attribute names distinctive — the shipped ones are.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .core import Finding, SourceFile, nearest, parse_waivers, walk_with_ancestors

LOCK_CTOR_NAMES = {"Lock", "RLock"}
COND_CTOR_NAMES = {"Condition"}
MAKE_LOCK_NAME = "make_lock"

# built-in fallback so standalone scans (CLI --graph, the runtime witness
# seed) can parse waivers without importing the registry (which imports
# the checkers, which import this module)
_FALLBACK_VALID_CHECKS = {
    "guarded-by", "host-sync", "pipeline-sync", "clock", "condvar",
    "sharding-axis", "lock-order", "lock-blocking", "lock-atomicity",
    "pod-broadcast",
}


@dataclass(frozen=True)
class LockDecl:
    """One declared lock (or condition alias) in the analyzed set."""

    qual: str  # class-qualified id, e.g. "QosQueue._lock"
    attr: str  # the attribute/name use sites spell, e.g. "_lock"
    owner: str  # class name, or module stem for module-level locks
    path: str  # display path of the declaring file
    line: int

    @property
    def site(self) -> str:
        return f"{self.path}:{self.line}"


@dataclass(frozen=True)
class Edge:
    """One 'a held while acquiring b' site in the lock-order graph."""

    a: str
    b: str
    path: str
    line: int
    via: str | None  # callee name for one-level call edges, None for direct
    waived: bool

    @property
    def site(self) -> str:
        return f"{self.path}:{self.line}"


@dataclass
class FuncInfo:
    """Per-function facts for the one-level call expansion."""

    key: tuple[str, str, str]  # (module display, owner class or "", name)
    acquires: set[str] = field(default_factory=set)  # direct, canonical
    blocking: list[tuple[int, str]] = field(default_factory=list)


def module_stem(path: Path) -> str:
    """Module-level locks qualify by the module's import name component:
    ``native/__init__.py`` -> ``native``, ``telemetry/logs.py`` -> ``logs``."""
    if path.stem == "__init__":
        return path.parent.name
    return path.stem


def _call_name(value: ast.AST) -> tuple[str, ast.Call] | None:
    """(final callee component, call node) for a Call expression, else None."""
    if not isinstance(value, ast.Call):
        return None
    func = value.func
    if isinstance(func, ast.Attribute):
        return func.attr, value
    if isinstance(func, ast.Name):
        return func.id, value
    return None


def _unwrap_factory(value: ast.AST) -> ast.AST:
    """``field(default_factory=X)`` declares whatever X builds; a lambda
    factory declares its body. Anything else passes through unchanged."""
    named = _call_name(value)
    if named is not None and named[0] == "field":
        for kw in named[1].keywords:
            if kw.arg == "default_factory":
                value = kw.value
                break
    if isinstance(value, ast.Lambda):
        return value.body
    return value


def classify_ctor(value: ast.AST):
    """Classify a declaration RHS: ``("lock", None)`` for Lock/RLock
    constructions, ``("cond", arg)`` for Condition(arg) (arg may be None:
    a bare Condition owns its own lock), ``("named", literal)`` for
    ``make_lock("Owner.attr")`` witness-wrapped declarations, else None."""
    value = _unwrap_factory(value)
    named = _call_name(value)
    if named is None:
        # bare `threading.Lock` (no call) as a default_factory
        if isinstance(value, ast.Attribute) and value.attr in LOCK_CTOR_NAMES:
            return ("lock", None)
        if isinstance(value, ast.Name) and value.id in LOCK_CTOR_NAMES:
            return ("lock", None)
        return None
    name, call = named
    if name in LOCK_CTOR_NAMES:
        return ("lock", None)
    if name in COND_CTOR_NAMES:
        return ("cond", call.args[0] if call.args else None)
    if name == MAKE_LOCK_NAME:
        if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
            call.args[0].value, str
        ):
            return ("named", call.args[0].value)
        return ("named", None)  # malformed: non-literal witness name
    return None


def _decl_targets(node: ast.AST) -> list[tuple[str, ast.AST]]:
    """(attr-or-name, value) pairs a statement declares. ``self.X = ...``
    yields X; plain ``X = ...`` yields X (class body or module level)."""
    if isinstance(node, ast.Assign):
        targets, value = node.targets, node.value
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        targets, value = [node.target], node.value
    else:
        return []
    out = []
    for tgt in targets:
        if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self":
            out.append((tgt.attr, value))
        elif isinstance(tgt, ast.Name):
            out.append((tgt.id, value))
    return out


# -- blocking-construct vocabulary (shared with lock_blocking_check) ---------

# names shared with builtin containers/strings never resolve through the
# unique-project-wide fallback: `self._reg_metrics.get(...)` is dict.get,
# not MetricsRegistry.get, and name-based matching cannot tell — so it
# declines (self-calls and bare module calls stay precise)
AMBIENT_METHOD_NAMES = frozenset(
    dir(dict)) | frozenset(dir(list)) | frozenset(dir(set)) \
    | frozenset(dir(str)) | frozenset(dir(tuple)) | frozenset(dir(bytes))

SYNC_METHODS = {"item", "tolist", "block_until_ready", "all_logits",
                "lane_logits", "device_get"}
SYNC_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
              "jax.device_get"}
SOCKET_METHODS = {"sendall", "recv", "accept", "connect"}
SUBPROCESS_FUNCS = {"subprocess.run", "subprocess.check_call",
                    "subprocess.check_output", "subprocess.Popen"}
BROADCAST_NAMES = {"broadcast_one_to_all", "_bcast"}


def classify_blocking_call(node: ast.Call) -> tuple[str, str] | None:
    """``(kind, description)`` when the call is a blocking construct, else
    None. Kinds: ``"wait"`` needs held-lock context to judge (a
    Condition.wait on the HELD lock is the one legitimate
    blocking-under-lock); everything else blocks unconditionally."""
    func = node.func
    last = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None
    )
    if last is None:
        return None
    spelled = ast.unparse(func)
    if last in SYNC_METHODS or spelled in SYNC_FUNCS:
        return "sync", f"device->host sync '{spelled}(...)'"
    if last in ("wait", "wait_for") and isinstance(func, ast.Attribute):
        return "wait", f"'{spelled}(...)'"
    if last == "result" and isinstance(func, ast.Attribute):
        return "future", f"future '{spelled}(...)'"
    if last in SOCKET_METHODS and isinstance(func, ast.Attribute):
        return "io", f"socket/stream '{spelled}(...)'"
    if last == "urlopen":
        return "io", f"HTTP '{spelled}(...)'"
    if last == "print" and isinstance(func, ast.Name):
        return "io", "stream write 'print(...)'"
    if last == "sleep":
        return "sleep", f"'{spelled}(...)'"
    if spelled in SUBPROCESS_FUNCS:
        return "subprocess", f"subprocess '{spelled}(...)'"
    if last in BROADCAST_NAMES or (
        last.startswith("send_") and isinstance(func, ast.Attribute)
    ):
        return "broadcast", f"collective/packet send '{spelled}(...)'"
    if last == "join" and isinstance(func, ast.Attribute) and not (
        # str.join / os.path.join, same carve-out as the condvar check
        isinstance(func.value, ast.Constant)
        or ast.unparse(func.value).endswith("path")
    ):
        return "join", f"thread join '{spelled}(...)'"
    if _observer_name(last):
        return "observer", f"observer/hook call '{spelled}(...)'"
    return None


def _observer_name(name: str) -> bool:
    """The documented observer/hook vocabulary: ``on_*`` (underscore
    prefixes stripped) plus ``*observer*``/``*callback*``/``*hook*`` —
    for Name and Attribute callees alike, so renaming ``_on_pop_wait``
    to ``_wait_observer`` cannot silently retire the rule."""
    return (
        name.lstrip("_").startswith("on_")
        or "observer" in name
        or "callback" in name
        or "hook" in name
    )


def walk_excluding_nested_defs(root: ast.AST):
    """``ast.walk`` over ``root`` skipping the bodies of nested
    functions/lambdas — they run on their own call stacks, so lexical
    facts about ``root`` (held locks, reachable raises/returns, blocking
    constructs) do not apply to them. Shared by every check that scopes
    to one function."""
    skip: set[int] = set()
    for d in ast.walk(root):
        if isinstance(
            d, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ) and d is not root:
            for inner in ast.walk(d):
                skip.add(id(inner))
    for node in ast.walk(root):
        if id(node) not in skip:
            yield node


def iter_blocking(root: ast.AST):
    """Yield ``(call_node, kind, description)`` for every blocking
    construct directly inside ``root`` (nested defs excluded)."""
    for node in walk_excluding_nested_defs(root):
        if not isinstance(node, ast.Call):
            continue
        hit = classify_blocking_call(node)
        if hit is not None:
            yield node, hit[0], hit[1]


# -- the model ----------------------------------------------------------------


class LockModel:
    def __init__(self):
        self.decls: dict[str, LockDecl] = {}
        self.by_attr: dict[str, set[str]] = {}
        self.alias: dict[str, str] = {}  # condition qual -> lock qual
        self._alias_pending: list[tuple[str, str, str]] = []  # qual, owner, target attr
        self.funcs: dict[tuple[str, str, str], FuncInfo] = {}
        self.methods_by_name: dict[str, list[tuple[str, str, str]]] = {}
        self.edges: list[Edge] = []
        self.findings: list[Finding] = []
        self._files: list[SourceFile] = []
        self._resolved = False
        self._edges_built = False

    # -- phase 1: per-file declaration scan (Analyzer collect) ---------------

    def add_file(self, sf: SourceFile) -> None:
        self._files.append(sf)
        stem = module_stem(sf.path)
        for node, ancestors in walk_with_ancestors(sf.tree):
            pairs = _decl_targets(node)
            if not pairs:
                continue
            cls = nearest(ancestors, ast.ClassDef)
            owner = cls.name if cls is not None else stem
            if cls is None and nearest(
                ancestors, ast.FunctionDef, ast.AsyncFunctionDef
            ) is not None:
                continue  # lock local to a function: not shared state
            for attr, value in pairs:
                kind = classify_ctor(value)
                if kind is None:
                    continue
                qual = f"{owner}.{attr}"
                if kind[0] == "named":
                    literal = kind[1]
                    if literal is None:
                        self.findings.append(Finding(
                            "lock-order", sf.display, node.lineno,
                            f"make_lock declaration for '{qual}' needs a "
                            "string-literal witness name",
                        ))
                    elif literal != qual:
                        self.findings.append(Finding(
                            "lock-order", sf.display, node.lineno,
                            f"witness lock name {literal!r} does not match its "
                            f"class-qualified declaration '{qual}' — the "
                            "runtime witness and the static graph would track "
                            "different locks",
                        ))
                    self._declare(qual, attr, owner, sf, node.lineno)
                elif kind[0] == "lock":
                    self._declare(qual, attr, owner, sf, node.lineno)
                elif kind[0] == "cond":
                    arg = kind[1]
                    target = None
                    if isinstance(arg, ast.Attribute) and isinstance(
                        arg.value, ast.Name
                    ) and arg.value.id == "self":
                        target = arg.attr
                    elif isinstance(arg, ast.Name):
                        target = arg.id
                    self._declare(qual, attr, owner, sf, node.lineno)
                    if target is not None:
                        # resolve once every declaration has been seen
                        self._alias_pending.append((qual, owner, target))

    def _declare(self, qual, attr, owner, sf: SourceFile, line: int) -> None:
        if qual not in self.decls:
            self.decls[qual] = LockDecl(qual, attr, owner, sf.display, line)
            self.by_attr.setdefault(attr, set()).add(qual)

    # -- phase 2: cross-file resolution (idempotent; any check may call) -----

    def ensure_semantics(self) -> None:
        if self._resolved:
            return
        self._resolved = True
        for qual, owner, target in self._alias_pending:
            target_qual = f"{owner}.{target}"
            if target_qual in self.decls:
                self.alias[qual] = target_qual
        for sf in self._files:
            stem = module_stem(sf.path)
            for node, ancestors in walk_with_ancestors(sf.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                cls = nearest(ancestors, ast.ClassDef)
                owner = cls.name if cls is not None else ""
                key = (sf.display, owner, node.name)
                info = self.funcs.setdefault(key, FuncInfo(key))
                self.methods_by_name.setdefault(node.name, []).append(key)
                for inner, inner_anc in walk_with_ancestors(node):
                    if isinstance(inner, (ast.With, ast.AsyncWith)):
                        for item in inner.items:
                            qual = self.resolve(
                                item.context_expr, cls.name if cls else None,
                                stem,
                            )
                            if qual is not None:
                                info.acquires.add(qual)
                for call, kind, descr in iter_blocking(node):
                    if kind != "wait":  # wait needs held-set context
                        info.blocking.append((call.lineno, descr))

    def canonical(self, qual: str) -> str:
        seen = set()
        while qual in self.alias and qual not in seen:
            seen.add(qual)
            qual = self.alias[qual]
        return qual

    def resolve(self, expr: ast.AST, class_ctx: str | None,
                stem: str) -> str | None:
        """Canonical lock qual for an acquisition expression (``self._lock``,
        ``self.engine.stats.lock``, module-level ``_lock``), or None."""
        if isinstance(expr, ast.Call):  # e.g. `with self._get_lock():` — opaque
            return None
        if isinstance(expr, ast.Name):
            # a bare name only ever denotes a module-level lock of THIS
            # module; a function-local `lock = threading.Lock()` (skipped
            # at declaration time) must not fall through to the unique
            # fallback and mis-bind to an unrelated class's lock
            if f"{stem}.{expr.id}" in self.by_attr.get(expr.id, ()):
                return self.canonical(f"{stem}.{expr.id}")
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        candidates = self.by_attr.get(attr)
        if not candidates:
            return None
        if class_ctx is not None and f"{class_ctx}.{attr}" in candidates:
            return self.canonical(f"{class_ctx}.{attr}")
        if f"{stem}.{attr}" in candidates:
            return self.canonical(f"{stem}.{attr}")
        if len(candidates) == 1:
            return self.canonical(next(iter(candidates)))
        return None  # ambiguous: name-based matching declines to guess

    def held_at(self, ancestors, class_ctx: str | None,
                stem: str) -> list[tuple[str, int]]:
        """(canonical qual, with-line) for every known lock held at a node,
        innermost first, stopping at the first def/lambda boundary (a
        closure body runs after the enclosing with released its lock)."""
        held: list[tuple[str, int]] = []
        for a in reversed(list(ancestors)):
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    qual = self.resolve(item.context_expr, class_ctx, stem)
                    if qual is not None:
                        held.append((qual, a.lineno))
            elif isinstance(
                a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                break
        return held

    # -- phase 3: the lock-order graph (lock-order finalize) -----------------

    def _edge_waived(self, sf: SourceFile, line: int) -> bool:
        w = sf.waivers.get(line)
        if w is not None and w.covers("lock-order"):
            return True
        prev = sf.waivers.get(line - 1)
        return prev is not None and prev.standalone and prev.covers("lock-order")

    def build_edges(self) -> None:
        if self._edges_built:
            return
        self._edges_built = True
        self.ensure_semantics()
        for sf in self._files:
            stem = module_stem(sf.path)
            for node, ancestors in walk_with_ancestors(sf.tree):
                cls = nearest(ancestors, ast.ClassDef)
                class_ctx = cls.name if cls is not None else None
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    quals = [
                        q for item in node.items
                        if (q := self.resolve(item.context_expr, class_ctx, stem))
                        is not None
                    ]
                    if not quals:
                        continue
                    held = self.held_at(ancestors, class_ctx, stem)
                    waived = self._edge_waived(sf, node.lineno)
                    for i, b in enumerate(quals):
                        for a, _ in held:
                            self.edges.append(Edge(
                                a, b, sf.display, node.lineno, None, waived
                            ))
                        # `with a, b:` acquires left-to-right: ordered too
                        for a in quals[:i]:
                            self.edges.append(Edge(
                                a, b, sf.display, node.lineno, None, waived
                            ))
                elif isinstance(node, ast.Call):
                    held = self.held_at(ancestors, class_ctx, stem)
                    if not held:
                        continue
                    info = self._resolve_callee(node, sf, class_ctx)
                    if info is None or not info.acquires:
                        continue
                    waived = self._edge_waived(sf, node.lineno)
                    callee = ast.unparse(node.func)
                    for b in info.acquires:
                        for a, _ in held:
                            self.edges.append(Edge(
                                a, b, sf.display, node.lineno, callee, waived
                            ))

    def _resolve_callee(self, node: ast.Call, sf: SourceFile,
                        class_ctx: str | None) -> FuncInfo | None:
        """One level of intra-package call resolution, name-based: `self.m()`
        binds in the enclosing class, bare `f()` in the module, `x.m()`
        only when the method name is unique project-wide."""
        func = node.func
        if isinstance(func, ast.Attribute):
            name = func.attr
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                if class_ctx is not None:
                    return self.funcs.get((sf.display, class_ctx, name))
                return None
            if name in AMBIENT_METHOD_NAMES:
                return None  # dict.get/list.pop/... masquerade as methods
            keys = self.methods_by_name.get(name, [])
            if len(keys) == 1:
                return self.funcs.get(keys[0])
            return None
        if isinstance(func, ast.Name):
            return self.funcs.get((sf.display, "", func.id))
        return None

    def order_edges(self, include_waived: bool = False) -> list[Edge]:
        self.build_edges()
        out = [e for e in self.edges if include_waived or not e.waived]
        # one representative per (a, b): deterministic, earliest site
        best: dict[tuple[str, str], Edge] = {}
        for e in sorted(out, key=lambda e: (e.a, e.b, e.path, e.line)):
            best.setdefault((e.a, e.b), e)
        return list(best.values())

    def cycles(self) -> list[list[Edge]]:
        """Cycles in the non-waived order graph, each as its edge list
        (self-edges are length-1 cycles: re-acquiring a non-reentrant
        lock deadlocks without any second lock involved)."""
        edges = self.order_edges()
        adj: dict[str, list[Edge]] = {}
        for e in edges:
            adj.setdefault(e.a, []).append(e)
        out: list[list[Edge]] = []
        for e in edges:
            if e.a == e.b:
                out.append([e])
        # DFS from each node, smallest-first for determinism; report a
        # cycle only when it closes on the root so each cycle is found
        # exactly once (at its lexicographically smallest node)
        for root in sorted(adj):
            stack: list[tuple[str, list[Edge]]] = [(root, [])]
            seen_paths = set()
            while stack:
                node, path = stack.pop()
                for e in sorted(
                    adj.get(node, []), key=lambda e: e.b, reverse=True
                ):
                    if e.a == e.b:
                        continue
                    if e.b == root:
                        key = tuple(x.b for x in path) + (e.b,)
                        if key not in seen_paths:
                            seen_paths.add(key)
                            out.append(path + [e])
                    elif e.b > root and all(p.b != e.b for p in path):
                        stack.append((e.b, path + [e]))
        return out

    def dot(self) -> str:
        """The computed lock-order graph in DOT, for reviewer eyeballs
        (``dlint --graph``). Waived edges render dashed: intentional
        nesting stays visible without tripping the cycle check."""
        self.build_edges()
        lines = ["digraph dlint_lock_order {"]
        lines.append('  rankdir=LR; node [shape=box, fontname="monospace"];')
        for qual in sorted(self.decls):
            canon = self.canonical(qual)
            if canon != qual:
                continue  # aliases collapse into their canonical lock
            aliases = sorted(
                q for q, target in self.alias.items() if target == qual
            )
            label = qual if not aliases else f"{qual}\\n(= {', '.join(aliases)})"
            lines.append(f'  "{qual}" [label="{label}"];')
        for e in self.order_edges(include_waived=True):
            style = ', style=dashed' if e.waived else ""
            lines.append(
                f'  "{e.a}" -> "{e.b}" [label="{e.site}"{style}];'
            )
        lines.append("}")
        return "\n".join(lines)


# -- standalone entry points (CLI --graph, runtime witness seed) -------------


def scan_paths(paths, valid_checks: set[str] | None = None) -> LockModel:
    """Build a LockModel outside an Analyzer run: parse ``paths`` (files or
    directories), scan declarations, and leave the model ready for
    ``order_edges()``/``dot()``. Parse failures are skipped — the full
    analyzer reports those."""
    from .core import iter_py_files

    if valid_checks is None:
        valid_checks = set(_FALLBACK_VALID_CHECKS)
    model = LockModel()
    for p in iter_py_files(paths):
        try:
            text = p.read_text(encoding="utf-8")
            tree = ast.parse(text, filename=str(p))
        except (OSError, SyntaxError, ValueError):
            continue
        sf = SourceFile(
            path=p, display=p.as_posix(), text=text, tree=tree
        )
        sf.waivers, _ = parse_waivers(text, valid_checks, sf.display)
        model.add_file(sf)
    return model


def package_lock_graph(include_waived: bool = False):
    """(a, b, site) tuples of the package's statically computed lock-order
    edges — the runtime witness's seed. Waived edges are excluded by
    default: a waiver documents intentional nesting, and the witness must
    not fire on the order the waiver just sanctioned."""
    package_root = Path(__file__).resolve().parent.parent
    model = scan_paths([package_root])
    return [
        (e.a, e.b, e.site) for e in model.order_edges(include_waived)
    ]
