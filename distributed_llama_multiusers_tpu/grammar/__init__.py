"""Grammar-constrained decoding: on-device structured output.

A request's ``response_format`` — ``{"type": "json_object"}`` or
``{"type": "json_schema", "json_schema": {...}}`` — compiles into a
token-level DFA over the real tokenizer vocab (automaton.py: a byte-level
JSON character machine, walked by every vocab piece), whose per-state
legal-token sets become a packed device mask table and whose transitions
become a compact sparse edge table (slab.py). The engine gathers the
current state's mask inside every compiled step family, applies ``-inf``
before the existing exact top-p sort, and computes the next state ON
DEVICE so the automaton state rides the pipelined carry exactly like the
position carry — constrained lanes coexist with unconstrained ones at
``pipeline_flushes == 0``.

Host mirror (``GrammarAutomaton.next_state`` / ``filter_prefix``) serves
draft pre-filtering, deterministic journal replay, and fleet migration;
the device tables are the enforcement path.
"""

from .automaton import (
    GrammarAutomaton,
    GrammarError,
    canonical_key,
    compile_automaton,
    validate_response_format,
)
from .slab import GrammarSlab, GrammarSlabFull, SlabHandle

__all__ = [
    "GrammarAutomaton",
    "GrammarError",
    "GrammarSlab",
    "GrammarSlabFull",
    "SlabHandle",
    "canonical_key",
    "compile_automaton",
    "validate_response_format",
]
