"""jit-stability / donation-discipline / warmup-coverage: compile
stability as a machine-checked invariant.

The serving loop's contract is *one compiled program per (family,
bucket), compiled only at warmup* — a mid-serving XLA recompile stalls
every lane for seconds exactly when they are hot, and (PR 11's lesson)
the two ways to lose it are silent: a device-pytree leaf rebuilt with a
different sharding/aval recompiles every warmed program on the next
dispatch, and a step family the warmup loop missed compiles on its
first live dispatch. All three checks consume the surface model
``jitmodel.extract_jit_model`` builds (the ``protocol_check`` pattern);
the runtime twin is ``analysis/jitcheck.py`` (``DLLAMA_JITCHECK=1``).

- ``jit-stability`` — inside an engine method (scope:
  ``runtime/engine.py``), storing a bare ``jnp.asarray`` /
  ``jnp.array`` result (or a sharding-less ``jax.device_put``) into
  ``self`` state is a finding: device-pytree leaves must be built by
  the ONE sanctioned sharding-preserving constructor
  (``InferenceEngine._replace_leaf`` — ``make_array_from_callback`` /
  ``device_put`` with the captured ``NamedSharding``), so a leaf
  replacement can never change the compiled programs' input aval.
- ``donation-discipline`` — every ``donate_argnums`` call site must
  rebind the donated operand from the call's own results
  (``..., self.cache = self._fn(self.params, self.cache, ...)``);
  reading a donated value after the call (use-after-donate) or storing
  it into other host-side state before the call (the alias outlives the
  donation) is a finding.
- ``warmup-coverage`` — the set of dispatchable step families (every
  ``self.*_fn``-style jit binding: decode/pipelined/fused/spec
  families, ``_copy_page_fn``, ``_copy_lane_fn``, ``_sample_one``, the
  ``decode_multi`` factory) is cross-checked against what
  ``warmup_engine`` actually warms: a family reachable from a dispatch
  method but absent from warmup fails lint (the PR 11 COW-compile
  class), as does a bucketed family warmed outside the
  ``prefill_buckets`` loop, and a family no dispatcher can reach (dead
  compiled surface — the ``device_topk`` class).
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, Project, SourceFile
from .jitmodel import extract_jit_model
from .lockgraph import walk_excluding_nested_defs

ENGINE_SCOPE = ("runtime/engine.py",)
# jit-stability additionally covers the dequant selection table: its rules
# and resolution caches are read at trace time, so a device array stored
# into table state would become a captured constant with a changeable aval
# (the same recompile class as an engine leaf swap). Warmup coverage stays
# engine-only — the table has no compiled families of its own.
JIT_STABILITY_SCOPE = ENGINE_SCOPE + ("ops/dequant_select.py",)
# donation sites exist beyond the engine (the trainer's fused step); the
# jit surface the issue scopes is engine + model + ops + grammar slab
DONATION_SCOPE = (
    "runtime/engine.py", "models/llama.py", "grammar/slab.py",
    "training/trainer.py",
)
DONATION_DIRS = ("/ops/",)

# THE sanctioned leaf constructor: the one place a host mirror may
# become a device leaf. dlint whitelists exactly this name; everything
# else (the table leaf, the grammar-slab upload) must route through it.
SANCTIONED_LEAF_FNS = ("_replace_leaf",)

_BARE_LEAF_CALLS = {
    "jnp.asarray", "jnp.array", "jax.numpy.asarray", "jax.numpy.array",
}


def _spelled(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _self_target_attr(node: ast.AST) -> str | None:
    """``self.x`` / ``self.x[...]`` assignment target -> ``x``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class JitStabilityChecker(Checker):
    name = "jit-stability"
    description = (
        "device-pytree leaves stored into engine state must come from "
        "the sanctioned sharding-preserving constructor (_replace_leaf), "
        "never a bare jnp.asarray/jnp.array — a changed leaf aval forces "
        "an XLA recompile of every warmed program mid-serving"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*JIT_STABILITY_SCOPE):
            return
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__" or fn.name in SANCTIONED_LEAF_FNS:
                    # __init__ builds the initial pytree (the avals every
                    # program is compiled against); the sanctioned
                    # constructor is the whitelist itself
                    continue
                yield from self._check_method(sf, fn)

    def _check_method(self, sf: SourceFile, fn):
        for node in walk_excluding_nested_defs(fn):
            if not isinstance(node, ast.Assign):
                continue
            stored = [a for a in
                      (_self_target_attr(t) for t in node.targets)
                      if a is not None]
            if not stored:
                continue
            for sub in ast.walk(node.value):
                if not isinstance(sub, ast.Call):
                    continue
                spelled = _spelled(sub.func)
                if spelled in _BARE_LEAF_CALLS:
                    yield Finding(
                        self.name, sf.display, sub.lineno,
                        f"engine state 'self.{stored[0]}' rebuilt with "
                        f"bare {spelled}(...) — on a mesh the new leaf "
                        "drops the captured NamedSharding, the compiled "
                        "programs' input aval changes, and every warmed "
                        "family recompiles on the next dispatch (the PR 11 "
                        "per-admission-recompile class); build the leaf "
                        "with the sanctioned _replace_leaf constructor",
                    )
                elif spelled == "jax.device_put" and len(sub.args) < 2 \
                        and not any(kw.arg in ("device", "sharding")
                                    for kw in sub.keywords):
                    yield Finding(
                        self.name, sf.display, sub.lineno,
                        f"engine state 'self.{stored[0]}' rebuilt with "
                        "jax.device_put(...) without an explicit sharding "
                        "— the default placement is single-device, which "
                        "changes the leaf aval on a mesh; pass the "
                        "captured NamedSharding (or use _replace_leaf)",
                    )


class DonationDisciplineChecker(Checker):
    name = "donation-discipline"
    description = (
        "donate_argnums call sites rebind the donated operand from the "
        "call's results; reading a donated value after the call, or "
        "aliasing it into host state before it, touches a freed buffer"
    )

    def _in_scope(self, sf: SourceFile) -> bool:
        if sf.endswith(*DONATION_SCOPE):
            return True
        p = sf.path.as_posix()
        return any(d in p for d in DONATION_DIRS) and p.endswith(".py")

    def check(self, sf: SourceFile, project: Project):
        if not self._in_scope(sf):
            return
        model = extract_jit_model(sf.tree, sf.display)
        if not model.families:
            return
        for d in model.dispatchers.values():
            for use in d.donate_calls:
                if use.escape_line is not None:
                    # escapes even when the call rebinds: the pre-call
                    # alias still points at the freed buffer
                    yield Finding(
                        self.name, sf.display, use.escape_line,
                        f"donated pytree escapes into host-side state: "
                        f"'{use.spelling}' is stored here and then "
                        f"donated to {use.family} at line {use.line} — "
                        "the stored alias refers to a freed device "
                        "buffer after the call",
                    )
                if use.rebound:
                    continue
                if use.later_read_line is not None:
                    yield Finding(
                        self.name, sf.display, use.later_read_line,
                        f"use-after-donate: '{use.spelling}' was donated "
                        f"to {use.family} at line {use.line} "
                        "(donate_argnums) and is read again here — the "
                        "buffer was freed into the call's workspace; "
                        "rebind it from the call's results "
                        f"(`..., {use.spelling} = ...{use.family}(...)`)",
                    )


class WarmupCoverageChecker(Checker):
    name = "warmup-coverage"
    description = (
        "every dispatchable compiled step family is warmed by "
        "warmup_engine (bucketed families per prefill bucket) — a "
        "family missing from warmup compiles mid-serving on its first "
        "live dispatch (the PR 11 COW-compile class)"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*ENGINE_SCOPE):
            return
        model = extract_jit_model(sf.tree, sf.display)
        if not model.families:
            return
        if not model.has_warmup:
            yield Finding(
                self.name, sf.display, 1,
                f"{len(model.families)} compiled step families but no "
                "warmup_engine function — every family compiles "
                "mid-serving on its first dispatch",
            )
            return

        # several attrs can bind one site (the decode_multi factory and
        # its per-horizon dict): group by site so one warmed alias
        # covers the family
        groups: dict[int, list[str]] = {}
        for attr, site in model.families.items():
            groups.setdefault(id(site), []).append(attr)

        warmed_fams = model.warmed_families()
        for _, attrs in sorted(groups.items(),
                               key=lambda kv: model.family_lines[kv[1][0]]):
            attrs.sort(key=lambda a: model.family_lines[a])
            head = attrs[0]
            line = model.family_lines[head]
            dispatchers = sorted(
                d.name for d in model.dispatchers.values()
                if any(a in d.families for a in attrs)
            )
            if not dispatchers:
                yield Finding(
                    self.name, sf.display, line,
                    f"compiled family '{head}' is dispatched by no engine "
                    "method — dead device-program surface (compile cost "
                    "and warmup time for a program nothing can run); "
                    "delete it or wire a dispatcher",
                )
                continue
            if not any(a in warmed_fams for a in attrs):
                yield Finding(
                    self.name, sf.display, line,
                    f"compiled family '{head}' (dispatched by "
                    f"{', '.join(dispatchers)}) is never warmed by "
                    "warmup_engine — its first live dispatch pays the "
                    "XLA compile mid-serving; warm it (the PR 11 "
                    "COW-compile class)",
                )

        # bucketed dispatchers compile one program per prefill bucket:
        # warming one bucket leaves the others to compile mid-serving
        for method, call in sorted(model.warmed.items()):
            d = model.dispatchers.get(method)
            if d is not None and d.bucketed and d.families \
                    and not call.in_bucket_loop:
                yield Finding(
                    self.name, sf.display, call.line,
                    f"bucketed dispatcher '{method}' is warmed outside "
                    "the `for ... in engine.prefill_buckets` loop — only "
                    "one bucket's program compiles at warmup; the other "
                    "buckets compile on their first live admission",
                )
