"""Numpy oracle: the reference engine's math, step for step.

This plays the role the CPU backend plays in the reference's own test
strategy (SURVEY.md §7 stage 2): an independent, easily-auditable
implementation used to check the XLA path for token parity. It follows the
single-node graph of src/llm.cpp:126-438 literally, including the lossy
activation casts:

    embedding -> per layer [ rms -> Q80 cast -> q/k/v matmul -> rope ->
    kv append -> attention -> Q80 cast -> wo matmul -> Q80 cast (ZQ) ->
    residual add ] [ rms -> Q80 cast -> w1/w3 -> silu*mul -> Q80 cast ->
    w2 -> Q80 cast (ZQ) -> residual add ] -> final rms -> wcls

Weights come in as dequantized f32 (the Q40 noise is already baked in by the
file codecs). Everything is float32.
"""

from __future__ import annotations

import numpy as np

from ..formats.model_file import HiddenAct
from ..quants.codec import quantize_dequantize_q80
from .config import LlamaConfig


def _qdq80(x: np.ndarray) -> np.ndarray:
    return quantize_dequantize_q80(x, mode="runtime").astype(np.float32)


class OracleLlama:
    """Single-stream (batch=1) stateful decoder with a KV cache."""

    def __init__(self, config: LlamaConfig, weights: dict, emulate_q80: bool = True):
        """``weights``: dict with f32 numpy arrays in .m orientation
        ([d_out, d_in] matmuls): embedding [vocab, dim], per-layer lists
        wq,wk,wv,wo,w1,w2,w3,rms_att,rms_ffn, plus rms_final, wcls."""
        self.c = config
        self.w = weights
        self.emulate_q80 = emulate_q80
        S = config.seq_len
        self.k_cache = np.zeros((config.n_layers, S, config.n_kv_heads, config.head_size), np.float32)
        self.v_cache = np.zeros_like(self.k_cache)
        from ..ops.rope import build_rope_cache

        self.cos, self.sin = build_rope_cache(
            S,
            config.head_size,
            config.rope_theta,
            config.rope_scaling_factor,
            config.rope_scaling_low_freq_factor,
            config.rope_scaling_high_freq_factor,
            config.rope_scaling_orig_max_seq_len,
        )

    def reset(self):
        self.k_cache[:] = 0
        self.v_cache[:] = 0

    def _rms(self, x: np.ndarray, w: np.ndarray) -> np.ndarray:
        inv = 1.0 / np.sqrt(np.mean(x.astype(np.float32) ** 2) + self.c.norm_epsilon)
        return (x * inv * w).astype(np.float32)

    def _act(self, g: np.ndarray) -> np.ndarray:
        if self.c.hidden_act == HiddenAct.SILU:
            return g / (1.0 + np.exp(-g))
        return 0.5 * g * (1.0 + np.tanh(0.797884560802865 * g * (1.0 + 0.044715 * g * g)))

    def _rope(self, x: np.ndarray, pos: int) -> np.ndarray:
        # x: [n_heads_x, head_size], interleaved pairs
        h, d = x.shape
        c = self.cos[pos]
        s = self.sin[pos]
        x = x.reshape(h, d // 2, 2).copy()
        x0 = x[:, :, 0].copy()
        x1 = x[:, :, 1].copy()
        x[:, :, 0] = x0 * c - x1 * s
        x[:, :, 1] = x0 * s + x1 * c
        return x.reshape(h, d)

    def forward(self, token: int, pos: int) -> np.ndarray:
        """One decode step; returns logits [vocab] float32."""
        c = self.c
        qdq = _qdq80 if self.emulate_q80 else (lambda v: v)
        n_kv, hd, group = c.n_kv_heads, c.head_size, c.n_heads // c.n_kv_heads

        x = self.w["embedding"][token].astype(np.float32).copy()
        for l in range(c.n_layers):
            y = self._rms(x, self.w["rms_att"][l])
            yq = qdq(y)
            bq = self.w["bq"][l] if "bq" in self.w else 0.0
            bk = self.w["bk"][l] if "bk" in self.w else 0.0
            bv = self.w["bv"][l] if "bv" in self.w else 0.0
            q = (self.w["wq"][l] @ yq + bq).reshape(c.n_heads, hd)
            k = (self.w["wk"][l] @ yq + bk).reshape(n_kv, hd)
            v = (self.w["wv"][l] @ yq + bv).reshape(n_kv, hd)
            q = self._rope(q, pos)
            k = self._rope(k, pos)
            self.k_cache[l, pos] = k
            self.v_cache[l, pos] = v

            # attention over 0..pos (nn-cpu-ops.cpp:749-784)
            att_out = np.empty((c.n_heads, hd), np.float32)
            for h in range(c.n_heads):
                kv_h = h // group
                keys = self.k_cache[l, : pos + 1, kv_h]  # [pos+1, hd]
                vals = self.v_cache[l, : pos + 1, kv_h]
                scores = keys @ q[h] / np.sqrt(np.float32(hd))
                scores = scores - scores.max()
                e = np.exp(scores)
                p = e / e.sum()
                att_out[h] = p @ vals
            att_flat = att_out.reshape(-1)
            out = self.w["wo"][l] @ qdq(att_flat)
            x = x + qdq(out)

            y = self._rms(x, self.w["rms_ffn"][l])
            yq = qdq(y)
            if c.n_experts > 0:
                # top-k routing, softmax over selected logits (Mixtral
                # semantics; the reference never executes MoE — SURVEY.md §2.4)
                gate = self.w["moe_gate"][l] @ y  # router reads unquantized y
                top = np.argsort(-gate)[: c.n_active_experts]
                ew = np.exp(gate[top] - gate[top].max())
                ew = ew / ew.sum()
                d = np.zeros_like(x)
                for e, we in zip(top, ew):
                    g = self.w["w1"][l][e] @ yq
                    u = self.w["w3"][l][e] @ yq
                    g = self._act(g)
                    d = d + we * (self.w["w2"][l][e] @ qdq(g * u))
            else:
                g = self.w["w1"][l] @ yq
                u = self.w["w3"][l] @ yq
                g = self._act(g)
                d = self.w["w2"][l] @ qdq(g * u)
            x = x + qdq(d)

        y = self._rms(x, self.w["rms_final"])
        return (self.w["wcls"] @ qdq(y)).astype(np.float32)

    def generate_greedy(self, prompt_tokens: list[int], n_steps: int) -> list[int]:
        """Prefill the prompt token-by-token then greedy-decode n_steps."""
        self.reset()
        logits = None
        for i, t in enumerate(prompt_tokens):
            logits = self.forward(t, i)
        out = []
        pos = len(prompt_tokens)
        cur = int(np.argmax(logits))
        for _ in range(n_steps):
            out.append(cur)
            logits = self.forward(cur, pos)
            pos += 1
            cur = int(np.argmax(logits))
        return out


def oracle_weights_from_m(path: str, header) -> dict:
    """Load .m tensors as dequantized f32 in file orientation."""
    from .loader import read_m_tensors

    return read_m_tensors(path, header)
