"""Disaggregated prefill tests (disagg/ — ISSUE 16).

Three layers, mirroring tests/test_fleet.py's shape:

- **kvtransfer units** — bundle export/import round trip over the REAL
  :class:`KVPagePool` (fleet-independent): integrity hashes verify
  before any mutation, adoption is refcount-correct (reused prefixes
  bump refs, only fresh pages import payloads), a COW-born block
  survives transfer, and exhaustion/corruption shed typed WITHOUT
  partial adoption.
- **replica surfaces** — the role field on /load, the
  ``GET /admin/kvpages/<id>`` export and ``POST /admin/kvimport``
  adopt endpoints with their typed refusals (404/400/409/422).
- **THE pin** — a long-classified request routed to a prefill-role
  replica hands its KV pages + session to a decode replica mid-stream,
  and the client stream is byte-identical to the single-replica run;
  every hand-off failure (no decode target, prefill death mid-transfer)
  degrades to a typed fallback, never a hung stream.

MockAsyncEngine in ``content_keyed + paged`` mode is the determinism
class under test: its page payloads are content-canonical (sha256 of
the tree-node key), so two replicas that committed the same prefix
export identical bytes and the integrity machinery is exercised for
real, not vacuously.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.error
import urllib.request

import pytest

from distributed_llama_multiusers_tpu.disagg import (
    HandoffAborted,
    KVTransferError,
    adopt_bundle,
    classify_prompt,
    decode_bundle,
    export_bundle,
    page_hash,
    prompt_chars,
)
from distributed_llama_multiusers_tpu.fleet import FleetRouter
from distributed_llama_multiusers_tpu.runtime.kvpool import PoolExhausted
from distributed_llama_multiusers_tpu.runtime.scheduler import (
    ContinuousBatchingScheduler,
)
from distributed_llama_multiusers_tpu.serving import StreamRegistry
from distributed_llama_multiusers_tpu.server import ApiServer
from distributed_llama_multiusers_tpu.tokenizer import TemplateType
from distributed_llama_multiusers_tpu.utils import faults
from distributed_llama_multiusers_tpu.utils.testing import (
    CharStreamTokenizer,
    MockAsyncEngine,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# kvtransfer units: bundle round trip over the real pool
# ---------------------------------------------------------------------------


def _paged_engine(pool_pages=32, max_parked=8, page_size=4, seq_len=64,
                  n_lanes=2):
    """A paged mock: the REAL KVPagePool bookkeeping, device half mocked
    content-canonically (export/import are genuine round trips)."""
    return MockAsyncEngine(
        n_lanes=n_lanes, content_keyed=True, paged=True,
        kv_page_size=page_size, kv_pool_pages=pool_pages,
        kv_max_parked=max_parked, seq_len=seq_len,
    )


def _commit_chain(engine, lane, tokens):
    """Admit + commit + park one session's chain on ``engine``."""
    engine.paged_admit(lane, tokens, reserve_tokens=len(tokens))
    engine.paged_commit(lane, tokens)
    engine.paged_finish(lane, park=True)


def test_bundle_export_import_round_trip():
    """THE unit pin: export a committed chain off pool A, adopt into
    pool B — pages + hashes verify, only fresh pages import, the
    adopted prefix is visible to B's admission (refcount-shared), and
    re-export off B reproduces the bundle byte-for-byte."""
    a = _paged_engine()
    tokens = list(range(2, 26))  # 24 tokens = 6 full blocks of 4
    _commit_chain(a, 0, tokens)

    bundle = export_bundle(a.kvpool, a, tokens)
    assert bundle["v"] == 1 and bundle["page_size"] == 4
    assert bundle["n_tokens"] == 24 and len(bundle["blocks"]) == 6
    for blk in bundle["blocks"]:
        payload = base64.b64decode(blk["p"])
        assert blk["h"] == page_hash(4, blk["t"], payload)

    b = _paged_engine()
    receipt = adopt_bundle(b.kvpool, b, bundle)
    assert receipt == {"pages": 6, "fresh": 6, "reused": 0}
    assert b.pages_imported == 6
    stats = b.kvpool.stats()
    assert stats["pool_adopts"] == 1
    assert stats["pool_adopted_pages_fresh"] == 6
    # the chain is registered: B's tree resolves every block in order
    assert len(b.kvpool.chain_pages(tokens)) == 6
    # round-trip fidelity: B re-exports the identical bundle
    assert export_bundle(b.kvpool, b, tokens) == bundle

    # idempotent re-adoption: all reused, zero new imports (reused
    # pages' bytes may be live read targets — skipping them is the rule)
    receipt2 = adopt_bundle(b.kvpool, b, bundle)
    assert receipt2 == {"pages": 6, "fresh": 0, "reused": 6}
    assert b.pages_imported == 6

    # refcount-correct adoption: a real admission on B shares the whole
    # adopted prefix copy-free (start = 24 of 25 prompt tokens)
    start = b.paged_admit(0, tokens + [50], reserve_tokens=26,
                          min_share_tokens=4)
    assert start == 24


def test_cow_block_survives_transfer():
    """A block born through the pool's copy-on-write path (divergence
    inside a shared block) exports and adopts like any committed block,
    and adoption dedups against the shared prefix it branched from."""
    a = _paged_engine()
    base = list(range(2, 26))  # 6 blocks
    _commit_chain(a, 0, base)
    # session 2 shares 5 full blocks + 2 tokens of block 6, then
    # diverges: admit serves the partial block copy-on-write
    forked = base[:22] + [91, 92]
    start, _blocks, copies, _sw = a.kvpool.admit(
        1, forked, reserve_tokens=len(forked) + 1, min_share_tokens=4
    )
    assert copies, "expected a COW copy at the divergent block"
    assert start == 22  # 5 shared blocks + 2 COW-served tokens
    a.kvpool.commit(1, forked)
    a.kvpool.finish(1, park=True)

    bundle = export_bundle(a.kvpool, a, forked)
    assert len(bundle["blocks"]) == 6  # the COW block is committed too

    b = _paged_engine()
    assert adopt_bundle(b.kvpool, b, bundle) \
        == {"pages": 6, "fresh": 6, "reused": 0}
    # adopting the ORIGINAL chain now moves only the divergent tail:
    # the 5 shared blocks dedup against the forked chain's prefix
    bundle_base = export_bundle(a.kvpool, a, base)
    assert adopt_bundle(b.kvpool, b, bundle_base) \
        == {"pages": 6, "fresh": 1, "reused": 5}


def test_integrity_failure_adopts_nothing():
    """A corrupted payload (or a payload attached to the wrong block)
    dies typed BEFORE any pool mutation — never a partial adoption."""
    a = _paged_engine()
    tokens = list(range(2, 26))
    _commit_chain(a, 0, tokens)
    bundle = export_bundle(a.kvpool, a, tokens)

    # flipped payload bytes on block 1
    evil = json.loads(json.dumps(bundle))
    evil["blocks"][1]["p"] = base64.b64encode(b"\x00" * 64).decode()
    b = _paged_engine()
    free_before = b.kvpool.pages_free()
    with pytest.raises(KVTransferError) as e:
        adopt_bundle(b.kvpool, b, evil)
    assert e.value.reason == "integrity"
    assert b.kvpool.pages_free() == free_before
    assert b.kvpool.stats()["pool_adopts"] == 0
    assert b.pages_imported == 0
    assert b.kvpool.chain_pages(tokens) == []

    # payload intact but re-attached to the WRONG block: the tokens are
    # part of the hash framing, so the mix-up is caught too
    swapped = json.loads(json.dumps(bundle))
    swapped["blocks"][0]["t"], swapped["blocks"][1]["t"] = \
        swapped["blocks"][1]["t"], swapped["blocks"][0]["t"]
    with pytest.raises(KVTransferError) as e:
        adopt_bundle(b.kvpool, b, swapped)
    assert e.value.reason == "integrity"


def test_bundle_geometry_and_shape_rejections():
    a = _paged_engine()
    tokens = list(range(2, 26))
    _commit_chain(a, 0, tokens)
    bundle = export_bundle(a.kvpool, a, tokens)
    b = _paged_engine()

    with pytest.raises(KVTransferError) as e:
        decode_bundle(b.kvpool, {**bundle, "v": 2})
    assert e.value.reason == "bundle_version"

    with pytest.raises(KVTransferError) as e:
        decode_bundle(b.kvpool, {**bundle, "page_size": 8})
    assert e.value.reason == "page_size_mismatch"

    short_payload = b"x" * 8
    partial = {**bundle, "blocks": [{
        "t": [1, 2, 3],
        "p": base64.b64encode(short_payload).decode(),
        "h": page_hash(4, [1, 2, 3], short_payload),
    }]}
    with pytest.raises(KVTransferError) as e:
        decode_bundle(b.kvpool, partial)
    assert e.value.reason == "partial_block"

    with pytest.raises(KVTransferError) as e:
        decode_bundle(b.kvpool, {**bundle, "blocks": [{"t": [1, 2, 3, 4]}]})
    assert e.value.reason == "malformed_block"

    # empty chain: a valid no-op, not an error (prompt under one block)
    assert adopt_bundle(b.kvpool, b, {**bundle, "blocks": []}) \
        == {"pages": 0, "fresh": 0, "reused": 0}
    assert b.kvpool.stats()["pool_adopts"] == 0


def test_adopt_exhausted_pool_sheds_without_mutation():
    """Adoption against a pool whose pages are pinned by LIVE lanes
    raises the typed PoolExhausted with the pool exactly as it was —
    the importing replica's 429 shed, never garbage state."""
    b = _paged_engine(pool_pages=32)
    # two live lanes pin 30 of 32 pages (not parked: nothing evictable)
    b.paged_admit(0, list(range(100, 156)), reserve_tokens=57)
    b.paged_admit(1, list(range(200, 256)), reserve_tokens=57)
    assert b.kvpool.pages_free() < 6

    a = _paged_engine()
    foreign = list(range(2, 26))
    _commit_chain(a, 0, foreign)
    bundle = export_bundle(a.kvpool, a, foreign)

    free_before = b.kvpool.pages_free()
    with pytest.raises(PoolExhausted):
        adopt_bundle(b.kvpool, b, bundle)
    assert b.kvpool.pages_free() == free_before
    assert b.kvpool.chain_pages(foreign) == []
    assert b.pages_imported == 0

    # a parkless pool cannot pin the adopted chain: typed refusal
    parkless = _paged_engine(max_parked=0)
    with pytest.raises(ValueError):
        adopt_bundle(parkless.kvpool, parkless, bundle)


# ---------------------------------------------------------------------------
# prompt-length classification
# ---------------------------------------------------------------------------


def test_prompt_chars_both_api_shapes():
    assert prompt_chars({"prompt": "abcd"}) == 4
    assert prompt_chars({"prompt": ["ab", "cd", 7]}) == 4
    assert prompt_chars({"messages": [
        {"role": "system", "content": "abc"},
        {"role": "user", "content": "de"},
        {"role": "user", "content": None},
    ]}) == 5
    assert prompt_chars({}) == 0


def test_classify_prompt_threshold_and_disable():
    assert classify_prompt({"prompt": "x" * 99}, 100) == "short"
    assert classify_prompt({"prompt": "x" * 100}, 100) == "long"
    # non-positive threshold disables disagg routing entirely
    assert classify_prompt({"prompt": "x" * 10_000}, 0) == "short"
    assert classify_prompt({"prompt": "x" * 10_000}, -1) == "short"


# ---------------------------------------------------------------------------
# replica surfaces: role on /load, kvpages export, kvimport adopt
# ---------------------------------------------------------------------------


class _Tok(CharStreamTokenizer):
    def decode(self, token):
        return f"[{token}]"


def _paged_replica(rid, role="mixed", grace_s=30.0, paged=True):
    engine = MockAsyncEngine(
        n_lanes=2, max_chunk=8, content_keyed=True, step_s=0.004,
        paged=paged, kv_page_size=16, kv_pool_pages=128, kv_max_parked=32,
    )
    sched = ContinuousBatchingScheduler(
        engine, _Tok(64, max_chars=96),
        speculative=False, prefix_min_tokens=16, multi_step=0,
    )
    sched.start()
    registry = StreamRegistry(grace_s=grace_s) if grace_s else None
    api = ApiServer(sched, _Tok(64, max_chars=96), model_name="disagg",
                    template_type=TemplateType.LLAMA2, resume=registry,
                    replica_id=rid, role=role)
    httpd = api.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return {"api": api, "engine": engine, "sched": sched,
            "registry": registry, "httpd": httpd,
            "base": f"127.0.0.1:{httpd.server_address[1]}", "rid": rid}


def _stop_replica(r):
    try:
        r["httpd"].shutdown()
    finally:
        if r["registry"] is not None:
            r["registry"].close()
        try:
            r["sched"].stop()
        except RuntimeError:
            pass


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def _post_json(url, body, timeout=20):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _open_stream(base, body, timeout=60):
    req = urllib.request.Request(
        f"http://{base}/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = urllib.request.urlopen(req, timeout=timeout)
    rid = int(resp.headers["X-DLlama-Request"])
    # read to the first delta: admission (and the prompt's page
    # commits) are proven before the caller exports anything
    for line in resp:
        line = line.decode().strip()
        if line.startswith("data: ") and line != "data: [DONE]":
            break
    return resp, rid


def _drain(resp):
    for line in resp:
        pass
    resp.close()


def test_run_device_op_executes_on_loop_thread_and_relays_errors():
    """The donation-race fix (found by a live real-engine drive): page
    export/import must run on the batching-loop thread at its step
    boundary — the pipelined chain donates the cache pytree, so an
    admin-thread touch of ``engine.cache`` mid-chain hits a deleted
    buffer. Pins: (a) ops posted from another thread execute ON the
    loop thread, (b) exceptions re-raise to the caller with their
    original type, (c) a stopped loop runs ops inline (tests, drained
    servers), never hangs the caller."""
    engine = _paged_engine()
    sched = ContinuousBatchingScheduler(
        engine, CharStreamTokenizer(64), speculative=False, multi_step=0,
    )
    # (c) loop not running: inline on the calling thread
    here = threading.current_thread()
    assert sched.run_device_op(threading.current_thread) is here
    sched.start()
    try:
        # (a) posted from this (non-loop) thread, executed on the loop
        ran_on = sched.run_device_op(threading.current_thread)
        assert ran_on is sched._thread
        assert ran_on is not here

        # (b) original exception type crosses back to the caller
        class _Boom(RuntimeError):
            pass

        def _raise():
            raise _Boom("device op failed")

        with pytest.raises(_Boom, match="device op failed"):
            sched.run_device_op(_raise)
        # the loop survived the op's exception
        assert sched.run_device_op(lambda: 7) == 7
    finally:
        sched.stop()
    # (c) again after stop: inline, no hang
    assert sched.run_device_op(threading.current_thread) is here


def test_role_advertised_on_load_scrape():
    p = _paged_replica("pf", role="prefill")
    m = _paged_replica("mx")
    try:
        assert _get_json(f"http://{p['base']}/load")["role"] == "prefill"
        assert _get_json(f"http://{m['base']}/load")["role"] == "mixed"
    finally:
        _stop_replica(p)
        _stop_replica(m)


def test_kvpages_export_surface():
    r = _paged_replica("exp")
    try:
        prompt = "kv page export surface " * 4  # 92 chars -> 5 full pages
        resp, rid = _open_stream(
            r["base"], {"prompt": prompt, "max_tokens": 24, "stream": True}
        )
        bundle = _get_json(f"http://{r['base']}/admin/kvpages/{rid}")
        assert bundle["v"] == 1 and bundle["page_size"] == 16
        assert len(bundle["blocks"]) >= 5
        for blk in bundle["blocks"]:
            assert blk["h"] == page_hash(
                16, blk["t"], base64.b64decode(blk["p"])
            )
        _drain(resp)
        # unknown session: 404; non-numeric id: 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://{r['base']}/admin/kvpages/424242", timeout=10
            )
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://{r['base']}/admin/kvpages/nope", timeout=10
            )
        assert e.value.code == 400
    finally:
        _stop_replica(r)


def test_kvimport_surface_and_typed_refusals():
    src = _paged_replica("isrc")
    dst = _paged_replica("idst")
    flat = _paged_replica("iflat", paged=False)
    try:
        prompt = "kv import surface round trip " * 3  # 87 chars
        resp, rid = _open_stream(
            src["base"], {"prompt": prompt, "max_tokens": 24, "stream": True}
        )
        bundle = _get_json(f"http://{src['base']}/admin/kvpages/{rid}")
        _drain(resp)
        status, receipt = _post_json(
            f"http://{dst['base']}/admin/kvimport", bundle
        )
        assert status == 200
        assert receipt["pages"] >= 5 and receipt["fresh"] == receipt["pages"]
        assert receipt["replica"] == "idst"
        assert dst["engine"].pages_imported == receipt["pages"]

        # corrupted in flight: typed 422, destination pool untouched
        evil = json.loads(json.dumps(bundle))
        evil["blocks"][0]["p"] = base64.b64encode(b"\x11" * 64).decode()
        adopts_before = dst["engine"].pool_stats()["pool_adopts"]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_json(f"http://{dst['base']}/admin/kvimport", evil)
        assert e.value.code == 422
        assert json.loads(e.value.read())["reason"] == "integrity"
        assert dst["engine"].pool_stats()["pool_adopts"] == adopts_before

        # a contiguous-cache replica cannot adopt pages: clear 409
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_json(f"http://{flat['base']}/admin/kvimport", bundle)
        assert e.value.code == 409
    finally:
        for r in (src, dst, flat):
            _stop_replica(r)


# ---------------------------------------------------------------------------
# router: THE disagg pin + typed fallbacks
# ---------------------------------------------------------------------------


def _router(replicas, **kw):
    router = FleetRouter(
        {r["rid"]: r["base"] for r in replicas},
        scrape_interval_s=kw.pop("scrape_interval_s", 0.1),
        **kw,
    ).start()
    httpd = router.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    router.scrape_once()
    return router, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _stream_via_router(rbase, body, timeout=120):
    req = urllib.request.Request(
        rbase + "/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    texts, ids, term = [], [], None
    cur_id = None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        served = resp.headers.get("X-DLlama-Replica")
        for line in resp:
            line = line.decode().strip()
            if line.startswith("id: "):
                cur_id = int(line[4:])
                continue
            if not line.startswith("data: "):
                continue
            if line == "data: [DONE]":
                break
            p = json.loads(line[6:])
            if "error" in p:
                term = p
                continue
            ch = p.get("choices", [{}])[0]
            if ch.get("finish_reason") is None:
                texts.append(ch.get("text", ""))
                if cur_id is not None:
                    ids.append(cur_id)
                cur_id = None
            else:
                term = p
    return "".join(texts), term, served, ids


def _oracle_text(body):
    """The single-replica reference stream off a STANDALONE replica
    (content_keyed: byte-identical wherever the prompt runs)."""
    r = _paged_replica("oracle")
    try:
        req = urllib.request.Request(
            f"http://{r['base']}/v1/completions",
            data=json.dumps({**body, "stream": False}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())["generated_text"]
    finally:
        _stop_replica(r)


LONG_BODY = {"prompt": "disagg hand off pin prompt " * 10,  # 270 chars
             "max_tokens": 24, "stream": True}


def test_disagg_handoff_mid_stream_byte_identical():
    """THE pin (acceptance criterion): a long-classified request routed
    to the prefill-role replica hands off — pages adopted fresh on the
    decode replica, session injected, stream reattached — and the
    client sees the single-replica bytes with gapless SSE ids."""
    ref = _oracle_text(LONG_BODY)
    p = _paged_replica("p0", role="prefill")
    d = _paged_replica("d0", role="decode")
    router, rhttpd, rbase = _router([p, d], long_prompt_chars=120)
    try:
        text, term, served, ids = _stream_via_router(rbase, LONG_BODY)
        assert served == "p0"  # long -> the prefill-role replica
        assert text == ref
        assert term is not None and "error" not in term
        assert term["choices"][0]["finish_reason"] == "length"
        assert ids == list(range(1, len(ids) + 1))
        assert router.disagg_handoffs_ok == 1
        assert router.disagg_fallbacks == 0
        assert router.disagg_pages_fresh >= 1
        # the decode replica genuinely adopted + imported the pages
        assert d["engine"].pool_stats()["pool_adopts"] >= 1
        assert d["engine"].pages_imported >= 1
        assert "dllama_router_disagg_handoffs_total" \
            in router.handle_metrics()
        stats = router.handle_stats()
        assert stats["router_disagg_handoffs_ok"] == 1
        assert stats["router_long_prompt_chars"] == 120
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(p)
        _stop_replica(d)


def test_no_decode_target_falls_back_monolithic():
    """A fleet with ONLY the prefill replica: the hand-off has nowhere
    to go, so it falls back typed and the original stream finishes
    byte-identical — the monolithic path, never a hang."""
    ref = _oracle_text(LONG_BODY)
    p = _paged_replica("solo", role="prefill")
    router, rhttpd, rbase = _router([p], long_prompt_chars=120)
    try:
        text, term, served, _ = _stream_via_router(rbase, LONG_BODY)
        assert served == "solo"
        assert text == ref
        assert term["choices"][0]["finish_reason"] == "length"
        assert router.disagg_handoffs_ok == 0
        assert router.disagg_fallbacks == 1
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(p)


def test_prefill_death_mid_transfer_migrates_not_hangs(monkeypatch):
    """The nastiest failure mode: the prefill replica DIES in the
    middle of the transfer. The hand-off aborts typed (fallback), the
    resumed source stream breaks, and the normal migration path moves
    the session to the decode replica off the cached ticket — the
    client still sees the single-replica bytes, never a hung stream."""
    import distributed_llama_multiusers_tpu.fleet.router as router_mod

    ref = _oracle_text(LONG_BODY)
    p = _paged_replica("dies", role="prefill")
    d = _paged_replica("lives", role="decode")

    def deadly_hand_off(*args, **kw):
        # the source replica dies mid-transfer (scheduler force-cancel
        # + accept loop down, the orderly-death shape). stop() comes
        # FIRST and synchronously: the in-flight lanes must be
        # cancelled before the fallback resumes the source stream, so
        # the pump deterministically takes the migrate branch instead
        # of racing the short remaining generation to a natural finish
        # (httpd.shutdown() can block up to its serve-loop poll
        # interval, longer than the whole stream)
        p["sched"].stop()
        p["httpd"].shutdown()
        p["httpd"].server_close()
        raise HandoffAborted("src_died", "injected: source died mid-transfer")

    monkeypatch.setattr(router_mod, "hand_off", deadly_hand_off)
    router, rhttpd, rbase = _router([p, d], long_prompt_chars=120)
    try:
        text, term, served, ids = _stream_via_router(rbase, LONG_BODY)
        assert served == "dies"
        assert text == ref
        assert term is not None and "error" not in term
        assert term["choices"][0]["finish_reason"] == "length"
        assert ids == list(range(1, len(ids) + 1))
        assert router.disagg_fallbacks == 1
        assert router.disagg_handoffs_ok == 0
        assert router.migrations_ok == 1  # the rescue: ticket migration
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(d)
