"""Inference engine: compiled decode/prefill steps over a lane-based KV cache.

This is the TPU-native replacement for the reference executor + forward loop
(src/nn/nn-executor.cpp:134-187, src/app.cpp:179-231): instead of a
spin-barrier thread pool stepping a flat op list and shipping control packets
to workers, there are two compiled XLA programs —

- ``decode``: one token for every lane at its own position (the whole
  continuous batch advances in a single device step), and
- ``prefill``: a bucketed prompt chunk for ONE lane (dynamic-sliced out of
  the lane axis so other lanes' caches are untouched) — full prompt
  processing, fixing reference defect (a).

Shapes are bucketed (prompt chunks padded up to fixed sizes) so XLA compiles
a handful of programs once, replacing the reference's dynamic ``batchSize``
argument (nn-executor.cpp:171). All per-lane state (positions, sampling,
stream decode) lives with the scheduler; the engine is stateless apart from
the device-resident cache it threads through.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..models.config import LlamaConfig
from ..models.llama import KVCache, LlamaParams, init_kv_cache, llama_forward

DEFAULT_PREFILL_BUCKETS = (16, 64, 256, 1024)


@dataclass
class EngineStats:
    """Per-call timing + transfer counters — the analogue of the reference's
    per-step-type totalTime[] and socket byte counters (SURVEY.md §5.1)."""

    prefill_s: float = 0.0
    decode_s: float = 0.0
    prefill_tokens: int = 0
    decode_steps: int = 0
    host_bytes_in: int = 0  # device->host logits traffic

    def reset(self) -> "EngineStats":
        snap = EngineStats(**self.__dict__)
        self.prefill_s = self.decode_s = 0.0
        self.prefill_tokens = self.decode_steps = self.host_bytes_in = 0
        return snap


class InferenceEngine:
    def __init__(
        self,
        config: LlamaConfig,
        params: LlamaParams,
        n_lanes: int = 8,
        prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
        cache_dtype=jnp.float32,
        emulate_q80_activations: bool = False,
        mesh=None,
        replicate_outputs: bool = False,
    ):
        self.config = config
        self.params = params
        self.n_lanes = n_lanes
        self.mesh = mesh
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= config.seq_len
        ) or (min(16, config.seq_len),)
        self.cache = init_kv_cache(config, n_lanes, dtype=cache_dtype)
        self.stats = EngineStats()

        cfg = config
        q80 = emulate_q80_activations

        sp_mesh = mesh

        if replicate_outputs and mesh is not None:
            # multi-host: logits/greedy must come back fully replicated, or
            # no process can fetch them (a cross-host-sharded jax.Array is
            # not locally convertible; the reference instead gathers logits
            # to its root over TCP, SYNC_NODE_SLICES_EXCEPT_ROOT)
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(mesh, PartitionSpec())
            replicate = lambda x: jax.lax.with_sharding_constraint(x, rep)
        else:
            replicate = lambda x: x

        @partial(jax.jit, donate_argnums=(1,))
        def _decode(params, cache, tokens, positions):
            # tokens/positions: [n_lanes] -> [n_lanes, 1]
            logits, cache = llama_forward(
                cfg, params, tokens[:, None], positions[:, None], cache,
                emulate_q80_activations=q80, mesh=sp_mesh,
            )
            step = logits[:, 0, :]
            return (
                replicate(step),
                replicate(jnp.argmax(step, axis=-1).astype(jnp.int32)),
                cache,
            )

        @partial(jax.jit, donate_argnums=(1,))
        def _prefill(params, cache, lane, tokens, start_pos, n_tokens):
            # tokens: [bucket] int32, first n_tokens real; lane, start_pos,
            # n_tokens traced scalars (one compile per bucket size only).
            bucket = tokens.shape[0]
            # slice this lane's cache to batch-of-1
            k_lane = jax.lax.dynamic_slice_in_dim(cache.k, lane, 1, axis=1)
            v_lane = jax.lax.dynamic_slice_in_dim(cache.v, lane, 1, axis=1)
            positions = start_pos + jnp.arange(bucket, dtype=jnp.int32)
            # padded tail tokens write at positions >= start_pos + n_tokens,
            # which later real writes overwrite before they become readable
            # (mask s <= pos), so no masking is needed
            logits, lane_cache = llama_forward(
                cfg,
                params,
                tokens[None, :],
                positions[None, :],
                KVCache(k=k_lane, v=v_lane),
                emulate_q80_activations=q80,
                mesh=sp_mesh,
            )
            k = jax.lax.dynamic_update_slice_in_dim(cache.k, lane_cache.k, lane, axis=1)
            v = jax.lax.dynamic_update_slice_in_dim(cache.v, lane_cache.v, lane, axis=1)
            last = jax.lax.dynamic_index_in_dim(logits[0], n_tokens - 1, axis=0, keepdims=False)
            return (
                replicate(last),
                replicate(jnp.argmax(last).astype(jnp.int32)),
                KVCache(k=k, v=v),
            )

        self._decode_fn = _decode
        self._prefill_fn = _prefill

    # -- public API ---------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def prefill(self, lane: int, tokens: list[int], start_pos: int = 0):
        """Process a full prompt on one lane in bucketed chunks. Returns
        (last_logits np[vocab], greedy_token int, total_positions)."""
        if not tokens:
            raise ValueError("prefill needs at least one token (empty prompt)")
        if start_pos + len(tokens) > self.config.seq_len:
            raise ValueError(
                f"prompt of {len(tokens)} tokens at pos {start_pos} exceeds "
                f"seq_len {self.config.seq_len}"
            )
        t0 = time.perf_counter()
        pos = start_pos
        remaining = list(tokens)
        last = greedy = None
        while remaining:
            chunk_max = self.prefill_buckets[-1]
            chunk = remaining[:chunk_max]
            remaining = remaining[len(chunk) :]
            bucket = self.bucket_for(len(chunk))
            padded = np.zeros(bucket, np.int32)
            padded[: len(chunk)] = chunk
            last, greedy, self.cache = self._prefill_fn(
                self.params,
                self.cache,
                jnp.int32(lane),
                jnp.asarray(padded),
                jnp.int32(pos),
                jnp.int32(len(chunk)),
            )
            pos += len(chunk)
        jax.block_until_ready(last)
        self.stats.prefill_s += time.perf_counter() - t0
        self.stats.prefill_tokens += len(tokens)
        return last, int(greedy), pos

    def decode(self, tokens: np.ndarray, positions: np.ndarray):
        """One decode step for all lanes. tokens/positions: int32 [n_lanes]
        (idle lanes: any in-range position; their writes are never readable).
        Returns (logits device-array [n_lanes, vocab], greedy np[n_lanes])."""
        t0 = time.perf_counter()
        logits, greedy, self.cache = self._decode_fn(
            self.params,
            self.cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
        )
        greedy_np = np.asarray(greedy)
        self.stats.decode_s += time.perf_counter() - t0
        self.stats.decode_steps += 1
        return logits, greedy_np

    def lane_logits(self, logits, lane: int) -> np.ndarray:
        """Transfer one lane's logits to host (counted, for sampling)."""
        out = np.asarray(logits[lane])
        self.stats.host_bytes_in += out.nbytes
        return out

    def all_logits(self, logits) -> np.ndarray:
        """Single batched device->host transfer of all lanes' logits."""
        out = np.asarray(logits)
        self.stats.host_bytes_in += out.nbytes
        return out

    def reset_lane(self, lane: int) -> None:
        """Nothing to clear on device: a fresh request's prefill rewrites the
        lane's cache from position 0, and reads are masked to s <= pos."""
