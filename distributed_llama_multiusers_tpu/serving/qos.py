"""Bounded admission + per-user fair scheduling for the serving queue.

Replaces the bare FIFO ``RequestQueue`` (runtime/scheduler.py — itself the
mirror of the fork's src/Request.hpp:39-64) on the serving path. Production
continuous-batching servers (Orca-style iteration-level scheduling, vLLM's
scheduler) all pair the batching loop with an admission/QoS layer; this is
that layer. Three properties the FIFO lacks:

- **bounded admission** — at most ``capacity`` queued requests; overflow
  raises the typed :class:`AdmissionRejected` (the HTTP layer maps it to
  429 + ``Retry-After``) instead of growing an unbounded backlog that melts
  the server under overload.
- **priority classes** — strict ``HIGH > NORMAL > LOW`` between classes: a
  lower class pops only when every higher class is empty. Priority orders
  *service*, not admission: at capacity ``push`` sheds regardless of class
  (no eviction), so a full LOW backlog does lock HIGH out until it drains —
  pair ``capacity`` with queue timeouts (deadlines.py) to bound that window.
  Sustained HIGH floods can starve LOW by design.
- **deficit round robin** keyed by ``user_id`` within a class (Shreedhar &
  Varghese, SIGCOMM '95): each user in the rotation earns ``quantum`` cost
  credit per visit and a request pops only when its user's credit covers its
  cost (``max_tokens``), so one user's burst of large requests cannot starve
  other users' small ones. Unserved credit accumulates (a big request
  eventually goes); post-service carryover is capped at one quantum so a
  cheap-request user cannot bank unbounded credit while backlogged.

Thread-safe. The interface is a superset of ``RequestQueue``
(push/pop/empty/drain), so the scheduler takes either; requests are
duck-typed (``user_id`` / ``priority`` / ``max_tokens`` / ``submitted_at``
attributes, all optional).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from enum import IntEnum
from typing import Callable

from ..lockcheck import make_lock


class Priority(IntEnum):
    """Strict admission classes; lower value pops first."""

    HIGH = 0
    NORMAL = 1
    LOW = 2

    @staticmethod
    def parse(value) -> "Priority":
        """Accept ``"high"/"normal"/"low"`` (HTTP bodies) or the int value."""
        if isinstance(value, Priority):
            return value
        if isinstance(value, str):
            try:
                return Priority[value.strip().upper()]
            except KeyError:
                raise ValueError(
                    f"unknown priority {value!r} (expected high, normal, or low)"
                ) from None
        try:
            return Priority(int(value))
        except (TypeError, ValueError):
            raise ValueError(
                f"unknown priority {value!r} (expected high, normal, or low)"
            ) from None


class AdmissionRejected(RuntimeError):
    """Typed load-shed signal: the request never entered the queue.

    ``reason`` is ``"queue_full"`` (bounded admission, HTTP 429),
    ``"draining"`` (graceful shutdown in progress, HTTP 503),
    ``"breaker_open"`` (the engine circuit breaker is shedding while the
    engine is unhealthy, HTTP 503 — serving/breaker.py), or
    ``"pool_exhausted"`` (the paged KV pool is pinned by active lanes,
    HTTP 429 — runtime/kvpool.py); all carry a ``retry_after_s`` hint
    for the ``Retry-After`` header."""

    def __init__(
        self,
        reason: str,
        capacity: int = 0,
        queue_depth: int = 0,
        retry_after_s: float = 1.0,
    ):
        self.reason = reason
        self.capacity = capacity
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s
        self.http_status = (
            503 if reason in ("draining", "breaker_open") else 429
        )
        if reason == "draining":
            msg = "server is draining; not admitting new requests"
        elif reason == "breaker_open":
            msg = (
                "engine circuit breaker open (repeated engine failures); "
                f"retry in ~{retry_after_s:.0f}s"
            )
        elif reason == "pool_exhausted":
            msg = (
                "kv page pool exhausted (pinned by active requests; "
                "see --kv-pool-pages/--kv-max-parked); "
                f"retry in ~{retry_after_s:.0f}s"
            )
        else:
            msg = (
                f"queue full ({queue_depth}/{capacity} waiting); "
                f"retry in ~{retry_after_s:.0f}s"
            )
        super().__init__(msg)


def _default_cost(req) -> float:
    """DRR cost of a request: its token demand (the decode-lane time it will
    hold), never below one so zero/absent max_tokens still consumes credit."""
    return float(max(1, getattr(req, "max_tokens", 1) or 1))


def page_cost(page_size: int) -> Callable[[object], float]:
    """DRR cost in KV PAGES for paged engines (runtime/kvpool.py): the
    pages this request's admission will reserve — prompt + max_tokens
    (+1 for the boundary token's KV write), rounded up to page granularity.

    On the contiguous layout every admission costs one identical lane, so
    token demand (decode time) is the only axis users can differ on. The
    paged pool makes HBM itself the contended resource — a 10-page
    admission displaces ten times the parked sessions a 1-page one does —
    so fair share must charge what admission actually takes from the
    pool, or one user's long-context requests would evict every other
    user's parked prefixes at the same DRR price as a one-liner.

    Cost is evaluated at POP time on queued requests, before
    tokenization: ``n_prompt_tokens`` is used when a recovery replay or
    an earlier pass already resolved it, otherwise the prompt's token
    count is estimated at ~4 chars/token (the usual BPE density; the
    estimate only orders the DRR rotation, admission itself charges the
    exact reservation)."""
    page_size = max(1, int(page_size))

    def _cost(req) -> float:
        prompt = int(getattr(req, "n_prompt_tokens", 0) or 0)
        if prompt <= 0:
            prompt = len(getattr(req, "prompt", "") or "") // 4 + 1
        tokens = prompt + int(max(1, getattr(req, "max_tokens", 1) or 1)) + 1
        return float(max(1, -(-tokens // page_size)))

    return _cost


def jittered_retry_after(seconds: float, key: int,
                         spread: float = 0.2) -> float:
    """``seconds`` with deterministic ±``spread`` jitter, floored at 1s.

    Every shed path (breaker open, queue full, stalled-503) hands clients
    a Retry-After; when a replica trips, it sheds a BURST of clients with
    the SAME hint, and their synchronized retries land as a thundering
    herd on the exact second the replica reopens — re-tripping it. The
    jitter de-synchronizes the herd. Deterministic by design (a pure
    splitmix64 hash of ``key`` — use the request id; the same finalizer
    the fault plan's Bernoulli trigger uses): the same shed decision
    always renders the same header, so tests and log correlation stay
    exact, while distinct requests spread across the ±20% band."""
    from ..utils.faults import _mix64

    u = (_mix64(int(key) * 0x9E3779B97F4A7C15) >> 11) / float(1 << 53)
    return max(1.0, float(seconds) * (1.0 - spread + 2.0 * spread * u))


class QosQueue:
    """Priority + deficit-round-robin request queue with bounded admission.

    ``capacity`` 0 means unbounded (library default — the serving entry point
    passes ``--max-queue``). ``quantum`` is the per-visit credit in cost
    units (tokens); it sets the interleave grain — a user must wait roughly
    ``cost/quantum`` rotation visits before a request that large pops.
    """

    # dlint guarded-by declaration (analysis/lock_check.py): all queue and
    # counter state may only be touched holding `_lock` — directly or via
    # the `_not_empty` Condition built over it (entering either IS holding
    # the lock) — or inside __init__ / *_locked methods. Machine-checked
    # by `make lint`.
    _dlint_guarded_by = {
        ("_lock", "_not_empty"): (
            "_levels", "_deficit", "_depth", "_admitted", "_popped",
            "_rejected", "_removed", "_wait_s_total", "_recent_waits",
            "_max_depth",
        ),
    }

    def __init__(
        self,
        capacity: int = 0,
        quantum: float = 128.0,
        cost: Callable[[object], float] | None = None,
    ):
        if quantum <= 0:
            raise ValueError("quantum must be positive")
        self.capacity = max(0, int(capacity))
        self.quantum = float(quantum)
        self._cost = cost or _default_cost
        # built via make_lock so the runtime lock-order witness
        # (DLLAMA_LOCKCHECK=1, lockcheck.py) can wrap it; the literal must
        # match the class-qualified name — dlint's lock-order collect
        # cross-checks it
        self._lock = make_lock("QosQueue._lock")
        self._not_empty = threading.Condition(self._lock)
        # priority -> (user_id -> FIFO of that user's requests); the
        # OrderedDict order IS the DRR rotation for that class
        self._levels: dict[int, OrderedDict[str, deque]] = {}
        self._deficit: dict[tuple[int, str], float] = {}
        self._depth = 0
        # counters (exposed via stats(), surfaced on /stats)
        self._admitted = 0
        self._popped = 0
        self._rejected: dict[str, int] = {"queue_full": 0, "draining": 0}
        self._removed = 0  # taken out by remove_if/drain, never popped
        self._wait_s_total = 0.0
        self._recent_waits: deque[float] = deque(maxlen=64)
        self._max_depth = 0
        # optional pop-time wait observer (telemetry queue-wait histogram);
        # set once before serving via set_wait_observer, invoked OUTSIDE
        # the queue lock — not in _dlint_guarded_by by design
        self._on_pop_wait: Callable[[float], None] | None = None

    # -- RequestQueue-compatible surface ------------------------------------

    def push(self, request) -> None:
        """Admit or shed: raises :class:`AdmissionRejected` at capacity —
        the caller (HTTP layer) turns that into a 429, so overload degrades
        into fast rejections instead of unbounded queueing."""
        with self._not_empty:
            if self.capacity and self._depth >= self.capacity:
                self._rejected["queue_full"] += 1
                raise AdmissionRejected(
                    "queue_full",
                    capacity=self.capacity,
                    queue_depth=self._depth,
                    retry_after_s=self._retry_after_locked(),
                )
            if getattr(request, "submitted_at", None) is None:
                request.submitted_at = time.monotonic()
            prio = int(getattr(request, "priority", Priority.NORMAL))
            user = str(getattr(request, "user_id", "") or "")
            level = self._levels.setdefault(prio, OrderedDict())
            dq = level.get(user)
            if dq is None:
                level[user] = dq = deque()
            dq.append(request)
            self._depth += 1
            self._admitted += 1
            self._max_depth = max(self._max_depth, self._depth)
            self._not_empty.notify()

    def pop(self, timeout: float | None = None):
        """Next request by (priority, per-user DRR); ``None`` on timeout.
        ``timeout=None`` blocks until a request arrives (Queue semantics);
        the scheduler's idle loop parks here instead of spinning."""
        wait = None
        with self._not_empty:
            if self._depth == 0 and timeout is not None:
                deadline = time.monotonic() + timeout
                while self._depth == 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._not_empty.wait(remaining)
            while self._depth == 0:
                self._not_empty.wait()
            req = self._pop_drr_locked()
            self._depth -= 1
            self._popped += 1
            t0 = getattr(req, "submitted_at", None)
            if t0 is not None:
                wait = max(0.0, time.monotonic() - t0)
                self._wait_s_total += wait
                self._recent_waits.append(wait)
        # observer runs OUTSIDE the queue lock: a histogram bump must never
        # extend the critical section every submit()/pop() contends on
        observer = self._on_pop_wait
        if wait is not None and observer is not None:
            observer(wait)
        return req

    def set_wait_observer(self, observer: Callable[[float], None] | None) -> None:
        """Install a callback invoked with each popped request's queue
        wait (seconds) — the telemetry queue-wait histogram feed, so the
        histogram's count reconciles with ``queue_popped`` exactly. Call
        before serving starts; the callback runs on the scheduler thread,
        outside the queue lock, and must not touch the queue."""
        self._on_pop_wait = observer

    def empty(self) -> bool:
        """Advisory emptiness (racy by nature, same contract as the FIFO)."""
        # dlint: ok[guarded-by] advisory racy read by documented contract; one int load under the GIL
        return self._depth == 0

    def drain(self) -> list:
        """Remove and return everything queued (shutdown path). Drained
        requests count as removed so the stats reconciliation (admitted =
        popped + removed + depth) survives a stop()/start() cycle."""
        with self._not_empty:
            out = []
            for level in self._levels.values():
                for dq in level.values():
                    out.extend(dq)
            self._levels.clear()
            self._deficit.clear()
            self._depth = 0
            self._removed += len(out)
            return out

    # -- QoS surface ---------------------------------------------------------

    def depth(self) -> int:
        # dlint: ok[guarded-by] advisory racy read by documented contract; one int load under the GIL
        return self._depth

    def remove_if(self, predicate) -> list:
        """Remove and return every queued request matching ``predicate`` —
        the scheduler's deadline sweep, so queue-wait timeouts fire even
        while all lanes stay saturated and nothing is being popped."""
        out = []
        with self._not_empty:
            for prio in list(self._levels):
                level = self._levels[prio]
                for user in list(level):
                    matched = []
                    kept = deque()
                    for r in level[user]:  # evaluate predicate exactly once
                        (matched if predicate(r) else kept).append(r)
                    if not matched:
                        continue  # common case: leave the deque untouched
                    out.extend(matched)
                    if kept:
                        level[user] = kept
                    else:
                        del level[user]
                        self._deficit.pop((prio, user), None)
                if not level:
                    del self._levels[prio]
            self._depth -= len(out)
            self._removed += len(out)
        return out

    def note_rejection(self, reason: str) -> None:
        """Count a rejection decided outside the queue (e.g. the scheduler
        shedding submissions during drain) so /stats sees all shed load."""
        with self._lock:
            self._rejected[reason] = self._rejected.get(reason, 0) + 1

    def stats(self) -> dict:
        """Point-in-time counter snapshot (single lock hold)."""
        with self._lock:
            avg = self._wait_s_total / self._popped if self._popped else 0.0
            return {
                "queue_depth": self._depth,
                "queue_capacity": self.capacity,
                "queue_admitted": self._admitted,
                "queue_popped": self._popped,
                "queue_rejected_full": self._rejected.get("queue_full", 0),
                "queue_rejected_draining": self._rejected.get("draining", 0),
                "queue_rejected_breaker": self._rejected.get("breaker_open", 0),
                # admitted = popped + removed + depth always reconciles
                "queue_removed": self._removed,
                "queue_wait_s_total": round(self._wait_s_total, 6),
                "queue_wait_avg_s": round(avg, 6),
                "queue_max_depth": self._max_depth,
            }

    # -- internals -----------------------------------------------------------

    def _retry_after_locked(self) -> float:
        # two congestion signals, floored at 1s: the average queue wait over
        # the last few dozen pops (a lifetime average would let one past
        # overload era inflate the hint forever), and the age of the oldest
        # request still waiting — during full saturation nothing pops, so
        # the pop-time average alone would tell clients to hammer a stuck
        # server with ~1s retries. Each per-user deque is FIFO, so only the
        # fronts need checking (O(waiting users), only paid on rejection).
        hint = 1.0
        if self._recent_waits:
            hint = max(hint, sum(self._recent_waits) / len(self._recent_waits))
        now = time.monotonic()
        for level in self._levels.values():
            for dq in level.values():
                t0 = getattr(dq[0], "submitted_at", None)
                if t0 is not None:
                    hint = max(hint, now - t0)
        return hint

    def _pop_drr_locked(self):
        for prio in sorted(self._levels):
            level = self._levels[prio]
            while level:
                # one full rotation: visit each user once, crediting a quantum
                min_rounds = None
                for user in list(level):
                    dq = level[user]
                    key = (prio, user)
                    cost = self._cost(dq[0])
                    credit = self._deficit.get(key, 0.0) + self.quantum
                    level.move_to_end(user)  # visited: back of the rotation
                    if credit >= cost:
                        req = dq.popleft()
                        if dq:
                            # cap carryover at one quantum: a backlogged
                            # cheap-request user must not bank unbounded
                            # credit (see module doc)
                            self._deficit[key] = min(credit - cost, self.quantum)
                        else:
                            del level[user]
                            self._deficit.pop(key, None)
                            if not level:
                                del self._levels[prio]
                        return req
                    # not enough credit yet: bank it; rounds = how many more
                    # full rotations until this user's head request pops
                    self._deficit[key] = credit
                    rounds = int(-(-(cost - credit) // self.quantum))
                    if min_rounds is None or rounds < min_rounds:
                        min_rounds = rounds
                # nobody could afford its head request this rotation: advance
                # the rotation clock arithmetically — every user earns one
                # quantum per silent rotation, so handing out min_rounds-1
                # quanta at once and letting the next real rotation add the
                # last one yields deficits identical to spinning, in O(users)
                # instead of O(cost/quantum) iterations under the queue lock
                # (one request with a huge max_tokens must not stall every
                # push/pop/stats caller while credit trickles in)
                if min_rounds > 1:
                    for user in level:
                        self._deficit[(prio, user)] += (min_rounds - 1) * self.quantum
        raise RuntimeError("pop on empty queue (caller must hold depth > 0)")
