"""Deterministic fault injection for the serving path (chaos harness).

Five PRs of async/pod machinery (pipelined decode, fused admissions,
control-plane replay, ring sync) had never been exercised under failure:
the only way an engine exception ever reached the scheduler was a real
XLA error on real hardware, which no CPU test can schedule. This module
makes failure a first-class, SEEDED input: a :class:`FaultPlan` names
injection points and fires at deterministic arrival indices, so a chaos
test can assert "the 5th dispatch raises" and replay the exact same
schedule every run — same spec, same seed, same faults.

Injection points (the names are the vocabulary; hooks are one function
call at each site, zero work when no plan is armed):

    engine.dispatch   — decode/decode_multi/decode_spec/prefill_chunk/
                        decode_pipelined/decode_prefill_fused entry
    engine.consume    — pipeline_consume (the lagged blocking readback)
    engine.transfer   — all_logits / lane_logits (host transfers)
    plane.broadcast   — ControlPlane._send (root->worker packet out)
    plane.recv        — ControlPlane.recv (worker packet in)
    journal.write     — RequestJournal writer-thread batch write (crash
                        durability: a failed journal write is counted and
                        contained, never fatal to serving)
    recovery.replay   — RecoveryCoordinator per-entry re-admission
                        (deterministic replay after a crash)

Spec grammar (``DLLAMA_FAULTS`` env var, or :func:`arm` directly)::

    spec    := clause (';' clause)*
    clause  := point ':' trigger (':' option)*
    trigger := '@' N ['+' M]          fire at the Nth arrival (1-based),
                                      then every M arrivals after
             | 'p=' F ',seed=' S      Bernoulli(F) per arrival, decided by
                                      a pure hash of (seed, arrival) — the
                                      schedule is a function of the seed
    option  := 'n=' K                 at most K fires (default unlimited)
             | 'kind=raise'           raise InjectedFault (default)
             | 'kind=hang'            block the calling thread instead —
                                      the blackholed-step simulator the
                                      watchdog exists for
             | 'hang=' SECONDS        hang duration (default 30; the hang
                                      aborts early on disarm())

Examples::

    DLLAMA_FAULTS="engine.dispatch:@5:n=1"         one fault, 5th dispatch
    DLLAMA_FAULTS="engine.consume:p=0.02,seed=7"   seeded 2% consume faults
    DLLAMA_FAULTS="engine.consume:@8:n=1:kind=hang:hang=5"  one 5s blackhole

Armed state is process-global (the engine hot paths can't thread a plan
through every call); ``fire()`` on an unarmed process is one global read.
"""

from __future__ import annotations

import threading
import time

from ..lockcheck import make_lock

POINTS = (
    "engine.dispatch",
    "engine.consume",
    "engine.transfer",
    "plane.broadcast",
    "plane.recv",
    "journal.write",
    "recovery.replay",
)


class InjectedFault(RuntimeError):
    """A scheduled fault firing. Deliberately NOT a ValueError: the
    scheduler's failure classifier treats it as engine-scoped (the class
    of failure the containment layer exists for), matching the real
    errors it stands in for (XLA RESOURCE_EXHAUSTED, transfer errors)."""

    def __init__(self, point: str, arrival: int):
        self.point = point
        self.arrival = arrival
        super().__init__(
            f"injected fault at {point} (arrival {arrival})"
        )


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a pure, platform-stable hash — the Bernoulli
    trigger's decision for arrival i is mix(seed ^ i), so a schedule is a
    function of (seed, arrival index) and nothing else."""
    x &= 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


class FaultClause:
    """One parsed clause: a point, a deterministic trigger, and limits."""

    def __init__(self, point: str, at: int = 0, every: int = 0,
                 prob: float = 0.0, seed: int = 0, limit: int = 0,
                 kind: str = "raise", hang_s: float = 30.0):
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (expected one of {POINTS})"
            )
        if kind not in ("raise", "hang"):
            raise ValueError(f"unknown fault kind {kind!r}")
        if at <= 0 and prob <= 0.0:
            raise ValueError(
                f"clause for {point} needs a trigger (@N or p=F,seed=S)"
            )
        self.point = point
        self.at = at
        self.every = every
        self.prob = prob
        self.seed = seed
        self.limit = limit
        self.kind = kind
        self.hang_s = hang_s

    def decides(self, arrival: int, fired: int) -> bool:
        """Pure decision for the ``arrival``-th (1-based) event at this
        clause's point, given ``fired`` prior fires — no state, so the
        whole schedule is enumerable up front (see FaultPlan.schedule)."""
        if self.limit and fired >= self.limit:
            return False
        if self.at > 0:
            if arrival == self.at:
                return True
            return (
                self.every > 0
                and arrival > self.at
                and (arrival - self.at) % self.every == 0
            )
        # Bernoulli(prob) via the top 53 bits of the hash
        draw = _mix64(self.seed ^ (0x9E3779B97F4A7C15 * arrival)) >> 11
        return draw / float(1 << 53) < self.prob

    @staticmethod
    def parse(text: str) -> "FaultClause":
        parts = [p.strip() for p in text.split(":") if p.strip()]
        if len(parts) < 2:
            raise ValueError(f"fault clause {text!r} needs point:trigger")
        point = parts[0]
        kw: dict = {}
        trigger = parts[1]
        if trigger.startswith("@"):
            body = trigger[1:]
            if "+" in body:
                at, every = body.split("+", 1)
                kw["at"], kw["every"] = int(at), int(every)
            else:
                kw["at"] = int(body)
        else:
            for item in trigger.split(","):
                k, _, v = item.partition("=")
                if k == "p":
                    kw["prob"] = float(v)
                elif k == "seed":
                    kw["seed"] = int(v)
                else:
                    raise ValueError(f"bad trigger term {item!r} in {text!r}")
        for opt in parts[2:]:
            k, _, v = opt.partition("=")
            if k == "n":
                kw["limit"] = int(v)
            elif k == "kind":
                kw["kind"] = v
            elif k == "hang":
                kw["hang_s"] = float(v)
            else:
                raise ValueError(f"bad option {opt!r} in fault clause {text!r}")
        return FaultClause(point, **kw)


class FaultPlan:
    """A parsed, armed-able set of clauses with per-point arrival counters.

    Counters are the only mutable state; decisions are pure functions of
    (clause, arrival index), so ``schedule()`` can enumerate exactly which
    arrivals will fire — the determinism contract the chaos tests pin."""

    # dlint guarded-by declaration (analysis/lock_check.py): the arrival
    # counters and per-clause fire counts only move under _lock (fire()
    # is called from the scheduler loop thread AND pod worker threads).
    _dlint_guarded_by = {
        ("_lock",): ("_arrivals", "_fired", "_log"),
    }

    def __init__(self, clauses: list[FaultClause]):
        self.clauses = list(clauses)
        self._lock = make_lock("FaultPlan._lock")
        self._arrivals: dict[str, int] = {}
        self._fired: list[int] = [0] * len(self.clauses)
        self._log: list[tuple[str, int]] = []  # (point, arrival) fired

    @staticmethod
    def parse(spec: str) -> "FaultPlan":
        clauses = [
            FaultClause.parse(c) for c in spec.split(";") if c.strip()
        ]
        if not clauses:
            raise ValueError(f"empty fault spec {spec!r}")
        return FaultPlan(clauses)

    def schedule(self, point: str, horizon: int) -> list[int]:
        """The arrival indices in [1, horizon] that will fire at ``point``
        — computed without touching the live counters, so two plans parsed
        from the same spec report identical schedules (the determinism
        gate)."""
        out = []
        fired = [0] * len(self.clauses)
        for arrival in range(1, horizon + 1):
            for i, c in enumerate(self.clauses):
                if c.point != point:
                    continue
                if c.decides(arrival, fired[i]):
                    fired[i] += 1
                    out.append(arrival)
                    break
        return out

    def fired_log(self) -> list[tuple[str, int]]:
        with self._lock:
            return list(self._log)

    def fire(self, point: str) -> None:
        """One arrival at ``point``: count it, and act when a clause
        decides — raise :class:`InjectedFault` (kind=raise) or block the
        calling thread (kind=hang, the blackholed-step simulator; aborts
        early on :func:`disarm`). The decision happens under the lock;
        the action happens outside it."""
        act: FaultClause | None = None
        arrival = 0
        with self._lock:
            arrival = self._arrivals.get(point, 0) + 1
            self._arrivals[point] = arrival
            for i, c in enumerate(self.clauses):
                if c.point != point:
                    continue
                if c.decides(arrival, self._fired[i]):
                    self._fired[i] += 1
                    self._log.append((point, arrival))
                    act = c
                    break
        if act is None:
            return
        if act.kind == "hang":
            deadline = time.monotonic() + act.hang_s
            # interruptible blackhole: disarm() releases hung threads so
            # a chaos test never leaks a sleeping loop thread past its
            # assertions
            while time.monotonic() < deadline and _armed() is self:
                _ABORT.wait(0.05)
            return
        raise InjectedFault(point, arrival)


# -- process-global arming ----------------------------------------------------

_PLAN: FaultPlan | None = None
_ABORT = threading.Event()


def _armed() -> FaultPlan | None:
    return _PLAN


def armed() -> bool:
    return _PLAN is not None


def arm(plan_or_spec) -> FaultPlan:
    """Arm a plan process-wide (a spec string parses first). Re-arming
    replaces the previous plan and releases any of its hung threads."""
    global _PLAN
    plan = (
        FaultPlan.parse(plan_or_spec)
        if isinstance(plan_or_spec, str)
        else plan_or_spec
    )
    _ABORT.set()
    _ABORT.clear()
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None
    _ABORT.set()  # release kind=hang blackholes
    _ABORT.clear()


def maybe_arm_from_env() -> FaultPlan | None:
    """Arm from ``DLLAMA_FAULTS`` when set and nothing is armed yet —
    called by scheduler.start() so `DLLAMA_FAULTS=... dllama-api ...`
    just works. Idempotent: an explicitly armed plan is never replaced."""
    import os

    if _PLAN is not None:
        return _PLAN
    spec = os.environ.get("DLLAMA_FAULTS")
    if not spec:
        return None
    return arm(spec)


def fire(point: str) -> None:
    """Hook call placed at each injection point: one global read when
    unarmed (the zero-overhead contract), the plan's decision otherwise."""
    plan = _PLAN
    if plan is None:
        return
    plan.fire(point)
