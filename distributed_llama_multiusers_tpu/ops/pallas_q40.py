"""Pallas TPU kernel: y = x @ dequant(W) for Q40-packed weights.

The TPU analogue of the reference's dequant-in-matmul kernels
(matmul_Q80_Q40_F32, src/nn/nn-cpu-ops.cpp:222-440, and the Vulkan shader
src/nn/vulkan/matmul-forward-q80-q40-f32.comp): weights stay int4+f16-scale
in HBM (~4.5 bits/element) and are expanded to f32 tile-by-tile in VMEM,
never materializing the dense weight in HBM. Decode-time matmuls are
HBM-bandwidth-bound, so reading 4.5 bits instead of 16 (bf16) per element is
the main single-chip throughput lever.

Layout (quants/packed.py): block-local nibble halves — each 32-input quant
block is 16 consecutive packed rows (low nibble = block inputs [0,16), high
nibble = [16,32)) + 1 scale row, so a chunk of whole blocks covers the same
contiguous input range in `packed`, `scales`, and `x`.

Kernel formulation (round-3 kernel-lab "v1", landed round 4): TWO dots —
the low/high nibble planes each multiply a pre-split half of x, so the
kernel never concatenates/relayouts the dequantized tile — and the -8
nibble offset is folded into one small correction dot against per-block x
sums instead of a per-weight subtract. Per packed byte the VPU does one
shift+mask+scale-mul, the rest is MXU work.

Block layout (round-4 rework, driven by stage_probe.py measurements on a
real v5e): blocks span the FULL output width (or a wide 512-multiple tile
for very wide matmuls), so each DMA fetches one contiguous multi-hundred-KB
slab instead of the 512-BYTE strided rows of the old (chunk, 512) blocks —
which measured at 47 GB/s of the chip's 819 GB/s on pure reads. Dequant
happens in 512-lane sub-tiles INSIDE the kernel to bound VMEM transients.
Grid: (m tiles, d_out wide-tiles, d_in chunks); the d_in axis accumulates
into an f32 VMEM scratch.

On TPU the dot runs in bf16 by default: BOTH the dequantized weight planes
and the x operand are cast to bf16 (``w_dtype`` is the dot's compute
dtype), trading the MXU's multi-pass f32 emulation (~3x slower, ~f32
accuracy) for single-pass bf16. That rounds activations to 8 mantissa
bits — the same precision class as the reference's own Q80 activation
casts (8-bit, src/llm.cpp:232-239). Interpret mode (CPU tests) defaults
to exact f32; ``set_pallas_w_dtype(jnp.float32)`` restores multi-pass f32
on TPU.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.6 spells pltpu.CompilerParams "TPUCompilerParams" (same kwargs)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from ..quants.packed import (
    PALLAS_SUB as SUB_TILE,
    PackedQ40,
    pallas_sub_tiles as _sub_tiles,
    pallas_wide_tile as _pick_w,
)

import os as _os

# tuning knobs, env-overridable for hardware sweeps (scripts/kernel_sweep.py)
SINGLE_SLAB_BYTES = int(
    _os.environ.get("DLLAMA_SINGLE_SLAB", 1 << 20)
)  # planes up to this: one DMA, no k axis
TARGET_BLOCK_BYTES = int(
    _os.environ.get("DLLAMA_TARGET_BLOCK", 1 << 20)
)  # k-chunk size target (DMA/compute overlap)

# Dequant arithmetic variant for the bf16 dot path (round-5 finding: the
# kernel is VPU-bound on the per-weight dequant chain — hbm_util ~0.26 on
# BOTH the 1B and the 8B, i.e. a per-byte cost with DMA hiding under it):
#   v4         f32 dequant (nib->f32, f32 scale mul) then bf16 cast
#   bf16chain  nib int->bf16 direct, one bf16 scale mul (no f32 round-trip)
#   repeat     bf16chain + jnp.repeat scale broadcast (no reshape dance)
#   u8chain    nibble masks on NATIVE 8-bit lanes (before any widening
#              relayout), int8->bf16 cast, bf16 scale mul — targets the
#              uint8->int32 expansion cost the other chains all pay
#   blockdot   per-quant-block MXU dots on RAW bf16 nibbles; the scale (and
#              the folded -8 offset) hit each block's [m, t] OUTPUT — the
#              per-weight VPU chain shrinks to mask + cast (~2 ops), with
#              the post-scale costing m/32 ops/weight (so decode-shaped m
#              only: m > 32 falls back to bf16chain)
#   i8blockdot blockdot with int8 MXU dots on Q80-QUANTIZED activations
#              (the reference's own activation format: per-block int8 +
#              f32 scale, src/llm.cpp:232-239): raw int8 nibbles feed the
#              MXU with NO per-weight cast or mul — the only chain with a
#              path to the DMA roofline. Numerics = reference Q80xQ40
#              class (activations quantized), bounded by the mode parity
#              test; same m cap as blockdot.
# Exact-f32 dots (w_dtype=f32: parity gate, interpret tests) always use the
# v4 f32 chain regardless of this knob.
DEQUANT_MODES = ("v4", "bf16chain", "repeat", "u8chain", "blockdot",
                 "i8blockdot")
# "auto" is selectable but not a kernel mode: it resolves per (d_in, d_out,
# m-class) from the persisted selection table (ops/dequant_select.py) inside
# q40_matmul_pallas, at trace time, so every family still compiles once.
SELECTABLE_MODES = DEQUANT_MODES + ("auto",)


def _env_dequant_default() -> str:
    """DLLAMA_DEQUANT, validated at READ time. A typo'd value must fail
    loudly here: the slab kernel's mode= else-branch would otherwise
    silently run the v4 chain under the wrong name."""
    mode = _os.environ.get("DLLAMA_DEQUANT", "v4")
    if mode not in SELECTABLE_MODES:
        raise ValueError(
            f"DLLAMA_DEQUANT={mode!r} is not a known dequant mode; "
            f"one of {SELECTABLE_MODES}"
        )
    return mode


DEQUANT_MODE = _env_dequant_default()
BLOCKDOT_MAX_M = 32  # above this, the post-scale FMA outweighs the savings

# Trace-time counters (host side: these python bodies run only while jax
# traces a NEW program, so steady-state jit-cache hits add nothing). They
# are the operand-sharing and compile-churn witnesses: `shared_builds` /
# `shared_consumes` pin that one Q80Acts build feeds every matmul sharing
# its input (llama_forward: wq/wk/wv = 1 build, w1/w3 = 1 build per step),
# and `impl_traces` holding still across repeated calls is the
# no-recompile signal tests assert across the BLOCKDOT_MAX_M boundary.
TRACE_STATS = {
    "acts_builds": 0,      # make_q80_acts executions (any caller)
    "shared_builds": 0,    # ... with shared=True (the models/llama.py hoist)
    "shared_consumes": 0,  # q40_matmul_pallas calls fed a prebuilt Q80Acts
    "impl_traces": 0,      # kernel-body traces (one per compiled family)
}


def reset_trace_stats() -> None:
    for k in TRACE_STATS:
        TRACE_STATS[k] = 0

# The one shared DMA-geometry sweep table: (single-slab ceiling, k-chunk
# target) in bytes, keyed by a stable name. scripts/kernel_sweep.py runs
# all of them; bench.py's in-bench sweep runs the non-default entries in
# this order under its remaining deadline. Ordered best-candidates-first
# (round-4 stage_probe pointed at larger contiguous DMAs).
SWEEP_COMBOS = {
    "slab1M_blk1M": (1 << 20, 1 << 20),  # the compiled-in default above
    "slab2M_blk2M": (2 << 20, 2 << 20),
    "slab4M_blk2M": (4 << 20, 2 << 20),
    "slab4M_blk4M": (4 << 20, 4 << 20),
    # whole-plane single DMA for every 1B plane (w1/w3 are 8 MB packed):
    # trades k-loop double-buffer overlap for zero chunking overhead.
    # (A 512k combo was dropped: blocks under 1 MB cannot tile the
    # 8192-wide FFN planes at all — rows would have to be <128 — so it
    # silently measured the XLA fallback, not the kernel.)
    "slab8M_blk8M": (8 << 20, 8 << 20),
}
DEFAULT_COMBO = "slab1M_blk1M"
M_TILE = 256
ROW_ALIGN = 8  # x rows padded to this multiple


def _f16_bits_to_f32(h: jnp.ndarray) -> jnp.ndarray:
    """Exact f16 -> f32 from int16 bit patterns (Mosaic has no f16 type).

    Exact for all finite f16 values, which the Q40 encoder guarantees.
    Normals: rebias the exponent into f32 position. Denormals: mant * 2^-24
    as a float product — no denormal f32 intermediates, so flush-to-zero
    hardware (XLA:CPU, TPU) cannot corrupt them."""
    h32 = h.astype(jnp.int32) & 0xFFFF
    exp = (h32 >> 10) & 0x1F
    mant = h32 & 0x3FF
    normal = jax.lax.bitcast_convert_type(
        ((exp + 112) << 23) | (mant << 13), jnp.float32
    )
    denorm = mant.astype(jnp.float32) * jnp.float32(5.9604644775390625e-08)  # 2^-24
    mag = jnp.where(exp == 0, denorm, normal)
    return jnp.where(h32 >> 15 != 0, -mag, mag)


# A packed block (plus Mosaic's double buffer, the dequant transients, and
# the [m_tile, w_tile] f32 accumulator) must fit VMEM; blocks above this
# mean the shape has no supported tiling and callers take the XLA fallback.
MAX_BLOCK_BYTES = 4 << 20


def _pick_rows(half: int, w: int) -> int | None:
    """Packed rows per reduction step, or None when no VMEM-safe tiling
    exists. Small planes: the whole extent (one contiguous DMA). Larger:
    the biggest 128-multiple divisor of `half` whose slab is
    ~TARGET_BLOCK_BYTES, so Mosaic double-buffers multi-hundred-KB
    contiguous fetches."""
    if half * w <= SINGLE_SLAB_BYTES:
        return half
    best = None
    for rows in range(128, half + 1, 128):
        if half % rows == 0 and rows * w <= TARGET_BLOCK_BYTES:
            best = rows
    if best is None and half * w <= MAX_BLOCK_BYTES:
        return half  # e.g. half with no 128-multiple divisor, modest plane
    return best


def _plan_blocks(d_in: int, d_out: int) -> tuple[int, int] | None:
    """(w_tile, rows) for the slab kernel, or None when the shape has no
    supported VMEM-safe tiling (callers use q40_matmul_xla)."""
    if d_in % 32 != 0:
        return None
    w_tile = _pick_w(d_out)
    if w_tile is None:
        return None
    rows = _pick_rows(d_in // 2, w_tile)
    if rows is None:
        return None
    return w_tile, rows


def _acc_epilogue(part, off, t, k, n_k, out_ref, acc_ref):
    """Shared k-axis accumulation for one sub-tile's partial sum: direct
    write when the reduction has one chunk, else init/accumulate into the
    f32 VMEM scratch (finalized by ``_final_writeback``)."""
    if n_k == 1:
        out_ref[:, off:off + t] = part.astype(out_ref.dtype)
    else:
        @pl.when(k == 0)
        def _(part=part, off=off, t=t):
            acc_ref[:, off:off + t] = part

        @pl.when(k > 0)
        def _(part=part, off=off, t=t):
            acc_ref[:, off:off + t] = acc_ref[:, off:off + t] + part


def _final_writeback(k, n_k, out_ref, acc_ref):
    if n_k > 1:
        @pl.when(k == n_k - 1)
        def _():
            out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def set_dequant_mode(mode: str | None) -> None:
    """Select the bf16-path dequant variant (None -> env/default; "auto" ->
    per-site table resolution, ops/dequant_select.py). The mode is a static
    argument of the jitted matmul, so switching retraces — resolve before
    warmup_engine, never mid-serving."""
    global DEQUANT_MODE
    if mode is not None and mode not in SELECTABLE_MODES:
        raise ValueError(
            f"unknown dequant mode {mode!r}; one of {SELECTABLE_MODES}"
        )
    DEQUANT_MODE = mode or _env_dequant_default()


def _q40_slab_kernel(x_lo_ref, x_hi_ref, bsum_t_ref, packed_ref, scales_ref,
                     out_ref, acc_ref, *, w_dtype, sub_tiles, n_k, mode):
    """One (m tile, d_out wide-tile, d_in chunk) step — two-dot formulation
    over a contiguous weight slab:

    - NO nibble concat: the low/high nibble planes each feed their own MXU
      dot against a matching pre-split half of x, so the dequantized tile
      never needs the [n_blk, 32, tile] relayout of the round-1 kernel.
    - NO per-weight -8 subtract: folded into one small correction dot,
      8 * (per-block x sums) @ scales, subtracted from the partial sum.
    - Dequant walks the slab in `sub_tiles`-lane slices to bound the VMEM
      transient (the slab itself can be megabytes wide).

    x_lo/x_hi: [mt, rows] (block-interleaved halves of x's columns for this
    d_in chunk). bsum_t: [rows/16, mt] f32 per-quant-block x sums,
    transposed so the lane dim is m. packed: [rows, W] uint8 slab. scales:
    [rows/16, W] int16 (f16 bits). acc: [mt, W] f32 scratch (elided when
    n_k == 1: the block writes out_ref directly)."""
    rows, _ = packed_ref.shape
    n_blk = rows // 16
    k = pl.program_id(2)
    x_lo = x_lo_ref[...].astype(w_dtype)
    x_hi = x_hi_ref[...].astype(w_dtype)
    bsum_t = bsum_t_ref[...]

    off = 0
    for t in sub_tiles:
        s = _f16_bits_to_f32(scales_ref[:, off:off + t])  # [n_blk, t] f32
        if mode == "u8chain":
            # mask on native 8-bit lanes BEFORE any widening: the other
            # chains pay a uint8->int32 expansion relayout up front
            p8 = packed_ref[:, off:off + t]
            s3 = s.astype(jnp.bfloat16)[:, None, :]
            lo8 = (p8 & jnp.uint8(0x0F)).astype(jnp.int8)
            hi8 = (p8 >> jnp.uint8(4)).astype(jnp.int8)
            w_lo = (lo8.astype(jnp.bfloat16).reshape(n_blk, 16, t) * s3)
            w_hi = (hi8.astype(jnp.bfloat16).reshape(n_blk, 16, t) * s3)
            w_lo = w_lo.reshape(rows, t)
            w_hi = w_hi.reshape(rows, t)
        elif mode == "bf16chain":
            # dequant stays in bf16: nibbles (0..15, exact in bf16) cast
            # once, scales rounded to bf16 once per block (amortized /32),
            # ONE bf16 mul per weight — drops the f32 round-trip + downcast
            p = packed_ref[:, off:off + t].astype(jnp.int32)
            s3 = s.astype(jnp.bfloat16)[:, None, :]
            w_lo = ((p & 0x0F).astype(jnp.bfloat16).reshape(n_blk, 16, t) * s3)
            w_hi = ((p >> 4).astype(jnp.bfloat16).reshape(n_blk, 16, t) * s3)
            w_lo = w_lo.reshape(rows, t)
            w_hi = w_hi.reshape(rows, t)
        elif mode == "repeat":
            # bf16 chain with the scale broadcast as an explicit row repeat
            # (each block's scale row 16x consecutive) instead of the
            # reshape->broadcast->reshape dance — a relayout-cost A/B
            p = packed_ref[:, off:off + t].astype(jnp.int32)
            s_rep = jnp.repeat(s.astype(jnp.bfloat16), 16, axis=0)
            w_lo = (p & 0x0F).astype(jnp.bfloat16) * s_rep
            w_hi = (p >> 4).astype(jnp.bfloat16) * s_rep
        else:  # v4: f32 dequant, cast to the dot dtype at the end
            p = packed_ref[:, off:off + t].astype(jnp.int32)
            s3 = s[:, None, :]
            w_lo = ((p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, t) * s3)
            w_hi = ((p >> 4).astype(jnp.float32).reshape(n_blk, 16, t) * s3)
            w_lo = w_lo.reshape(rows, t).astype(w_dtype)
            w_hi = w_hi.reshape(rows, t).astype(w_dtype)

        # folded -8 offset: 8 * bsum_b @ s == sum_i x_i * 8 * s_block(i)
        corr = jax.lax.dot_general(
            bsum_t, s, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        part = (
            jnp.dot(x_lo, w_lo, preferred_element_type=jnp.float32)
            + jnp.dot(x_hi, w_hi, preferred_element_type=jnp.float32)
            - 8.0 * corr
        )
        _acc_epilogue(part, off, t, k, n_k, out_ref, acc_ref)
        off += t
    _final_writeback(k, n_k, out_ref, acc_ref)


def _q40_blockdot_kernel(xlt_ref, xht_ref, bsum_t_ref, packed_ref, scales_ref,
                        out_ref, acc_ref, *, sub_tiles, n_k):
    """blockdot mode: one (m tile, d_out wide-tile, d_in chunk) step where
    the MXU does the dequant scaling implicitly. Per quant block b, two
    small dots contract the RAW bf16 nibbles against the matching 16-row
    x slices (x arrives TRANSPOSED [rows, m] so the slices are sublane
    ranges, not sub-128 lane slices); the block's scale and the folded -8
    offset then hit the [m, t] block output once:

        y += (x_lo_b @ nib_lo_b + x_hi_b @ nib_hi_b - 8*bsum_b) * s_b

    Per-weight VPU work = mask + int->bf16 cast (~2 ops vs ~4.5 for the
    f32 chain); the post-scale FMA costs m/32 ops per weight, which is why
    callers cap m (BLOCKDOT_MAX_M). MXU pays n_blk small 16-deep dots per
    sub-tile — idle capacity at decode shapes (mfu ~0.002)."""
    rows, _ = packed_ref.shape
    n_blk = rows // 16
    k = pl.program_id(2)
    bs = bsum_t_ref[...]  # [n_blk, m_tile] f32
    xl = xlt_ref[...].astype(jnp.bfloat16)  # cast ONCE, slice per block
    xh = xht_ref[...].astype(jnp.bfloat16)
    dn = (((0,), (0,)), ((), ()))
    off = 0
    for t in sub_tiles:
        p = packed_ref[:, off:off + t].astype(jnp.int32)
        s = _f16_bits_to_f32(scales_ref[:, off:off + t])  # [n_blk, t]
        nib_lo = (p & 0x0F).astype(jnp.bfloat16)
        nib_hi = (p >> 4).astype(jnp.bfloat16)
        part = None
        for b in range(n_blk):
            lo = jax.lax.dot_general(
                xl[16 * b:16 * (b + 1), :],
                nib_lo[16 * b:16 * (b + 1), :], dn,
                preferred_element_type=jnp.float32,
            )
            hi = jax.lax.dot_general(
                xh[16 * b:16 * (b + 1), :],
                nib_hi[16 * b:16 * (b + 1), :], dn,
                preferred_element_type=jnp.float32,
            )
            contrib = (lo + hi - 8.0 * bs[b, :, None]) * s[b][None, :]
            part = contrib if part is None else part + contrib
        _acc_epilogue(part, off, t, k, n_k, out_ref, acc_ref)
        off += t
    _final_writeback(k, n_k, out_ref, acc_ref)


def _q40_i8blockdot_kernel(xlt_ref, xht_ref, aux_ref, packed_ref, scales_ref,
                           out_ref, acc_ref, *, sub_tiles, n_k):
    """i8blockdot mode: per-block int8 MXU dots on Q80-quantized
    activations. The raw int8 nibbles are the dot operand — the only
    per-weight VPU work is the 8-bit-lane mask. Per block b:

        y += s_b * (sx_b * (xq_lo_b @ nib_lo_b + xq_hi_b @ nib_hi_b)
                    - 8 * bsum_b)

    with sx the per-(lane, block) activation scale and bsum the EXACT f32
    per-block x sums (the folded -8 offset stays exact; only the nibble
    dot itself carries activation-quantization error — the reference's
    Q80xQ40 numerics, src/llm.cpp:232-239). aux interleaves bsum/sx on
    the sublane axis: aux[2b] = bsum[b], aux[2b+1] = sx[b]."""
    rows, _ = packed_ref.shape
    n_blk = rows // 16
    k = pl.program_id(2)
    m_t = xlt_ref.shape[1]
    aux = aux_ref[...].reshape(n_blk, 2, m_t)
    bs = aux[:, 0, :]  # [n_blk, m_tile] f32
    sx = aux[:, 1, :]
    xl = xlt_ref[...]  # [rows, m_tile] int8
    xh = xht_ref[...]
    dn = (((0,), (0,)), ((), ()))
    off = 0
    for t in sub_tiles:
        p8 = packed_ref[:, off:off + t]
        nib_lo = (p8 & jnp.uint8(0x0F)).astype(jnp.int8)
        nib_hi = (p8 >> jnp.uint8(4)).astype(jnp.int8)
        s = _f16_bits_to_f32(scales_ref[:, off:off + t])  # [n_blk, t]
        part = None
        for b in range(n_blk):
            lo = jax.lax.dot_general(
                xl[16 * b:16 * (b + 1), :],
                nib_lo[16 * b:16 * (b + 1), :], dn,
                preferred_element_type=jnp.int32,
            )
            hi = jax.lax.dot_general(
                xh[16 * b:16 * (b + 1), :],
                nib_hi[16 * b:16 * (b + 1), :], dn,
                preferred_element_type=jnp.int32,
            )
            d = (lo + hi).astype(jnp.float32)
            contrib = (sx[b][:, None] * d - 8.0 * bs[b][:, None]) * s[b][None, :]
            part = contrib if part is None else part + contrib
        _acc_epilogue(part, off, t, k, n_k, out_ref, acc_ref)
        off += t
    _final_writeback(k, n_k, out_ref, acc_ref)


def pallas_supports(w: PackedQ40) -> bool:
    """True when the slab kernel handles these shapes; otherwise callers
    take the q40_matmul_xla fallback (ops/linear.py). d_in must cover whole
    quant blocks; d_out must give a valid wide tile (the loader pads wcls
    to a multiple of 8192 so vocab-width matmuls qualify); the fitted
    blocks must be VMEM-safe."""
    if w.packed.ndim != 2:
        return False
    return _plan_blocks(w.d_in, w.d_out) is not None


def _resolve_w_dtype(w_dtype, interpret: bool):
    """None -> exact f32 in interpret mode (CPU parity tests), bf16 on TPU.
    w_dtype is the dot's COMPUTE dtype: the dequantized planes and the x
    operand are both cast to it (bf16 = single-pass MXU; f32 = slower
    multi-pass emulation with ~f32 accuracy)."""
    if w_dtype is not None:
        return w_dtype
    return jnp.float32 if interpret else jnp.bfloat16


def _m_geometry(m: int) -> tuple[int, int]:
    """(m_pad, m_tile): x rows padded to ROW_ALIGN, tiled at M_TILE."""
    m_pad = max(ROW_ALIGN, ((m + ROW_ALIGN - 1) // ROW_ALIGN) * ROW_ALIGN)
    m_tile = min(M_TILE, m_pad)
    if m_pad % m_tile != 0:
        m_pad = ((m_pad + m_tile - 1) // m_tile) * m_tile
    return m_pad, m_tile


class Q80Acts(NamedTuple):
    """Shared activation operands for the Q40 matmul: built ONCE per
    distinct input and consumed by every matmul sharing it — llama_forward's
    wq/wk/wv share one normed x and w1/w3 another, so the per-step
    activation-quant + relayout VPU work drops to one build per site
    instead of one per call.

    Every kernel layout is materialized eagerly — the f32 nibble halves
    (slab chains), their transposes (blockdot), the Q80 per-block int8
    quantization with interleaved bsum/sx aux (i8blockdot) — because under
    jit the layouts the resolved mode does not touch are dead code XLA
    eliminates per compiled program. `x` keeps the ORIGINAL [..., d_in]
    input: it is the shape/dtype source and the operand for the XLA
    fallback when a consumer's weight has no supported tiling."""

    x: jnp.ndarray        # original input, [..., d_in]
    x_lo: jnp.ndarray     # [m_pad, half] f32 block-local low-nibble half
    x_hi: jnp.ndarray     # [m_pad, half] f32 high half
    x_lo_t: jnp.ndarray   # [half, m_pad] f32 (blockdot: block rows on sublanes)
    x_hi_t: jnp.ndarray
    bsum_t: jnp.ndarray   # [n_blk, m_pad] f32 per-block sums (folded -8)
    xq_lo_t: jnp.ndarray  # [half, m_pad] int8 Q80-quantized halves
    xq_hi_t: jnp.ndarray
    aux_t: jnp.ndarray    # [2*n_blk, m_pad] f32; aux[2b]=bsum[b], aux[2b+1]=sx[b]

    @property
    def d_in(self) -> int:
        return self.x.shape[-1]

    @property
    def m(self) -> int:
        m = 1
        for s in self.x.shape[:-1]:
            m *= s
        return m


def make_q80_acts(x: jnp.ndarray, shared: bool = False) -> Q80Acts:
    """Build the Q40-matmul activation operand bundle for `x` (idempotent
    on an existing bundle). O(m*d_in) VPU work, negligible next to the
    weight read — but when one input feeds several matmuls the per-call
    prep (f32 cast + pad, nibble split, transposes, Q80 quantization +
    aux interleave) used to be traced into EVERY call; hoisting it here
    runs it once per distinct input. bsum stays TRANSPOSED [n_blk, m] so
    its lane dim is m — Pallas lane-dim blocks must be multiples of 128
    or the full extent, and m tiles are either all of m_pad or 256-wide."""
    if isinstance(x, Q80Acts):
        return x
    d_in = x.shape[-1]
    if d_in % 32 != 0:
        raise ValueError(f"d_in={d_in} must cover whole 32-wide quant blocks")
    TRACE_STATS["acts_builds"] += 1
    if shared:
        TRACE_STATS["shared_builds"] += 1
    half = d_in // 2
    n_blk = d_in // 32
    m = 1
    for s in x.shape[:-1]:
        m *= s
    xf = x.reshape(m, d_in).astype(jnp.float32)
    m_pad, _ = _m_geometry(m)
    if m_pad != m:
        xf = jnp.pad(xf, ((0, m_pad - m), (0, 0)))

    xb = xf.reshape(m_pad, n_blk, 2, 16)
    x_lo = xb[:, :, 0, :].reshape(m_pad, half)
    x_hi = xb[:, :, 1, :].reshape(m_pad, half)

    xq3 = xf.reshape(m_pad, n_blk, 32)
    bsum = xq3.sum(axis=2)  # EXACT f32 sums: the folded -8 stays exact
    sx = jnp.maximum(jnp.abs(xq3).max(axis=2), 1e-8) / 127.0
    xq = jnp.clip(jnp.round(xq3 / sx[:, :, None]), -127, 127).astype(jnp.int8)

    return Q80Acts(
        x=x,
        x_lo=x_lo,
        x_hi=x_hi,
        x_lo_t=x_lo.T,
        x_hi_t=x_hi.T,
        bsum_t=bsum.T,
        xq_lo_t=xq[:, :, :16].reshape(m_pad, half).T,
        xq_hi_t=xq[:, :, 16:].reshape(m_pad, half).T,
        aux_t=jnp.stack([bsum, sx], axis=2).reshape(m_pad, n_blk * 2).T,
    )


def q40_matmul_pallas(x, w: PackedQ40, interpret: bool = False,
                      w_dtype=None) -> jnp.ndarray:
    """y = x @ dequant(w). x: [..., d_in] array OR a prebuilt ``Q80Acts``
    bundle (operand sharing across matmuls); returns [..., d_out] in the
    input's dtype.

    ``w_dtype``: the dot's compute dtype — applied to the dequantized
    weight planes AND the x operand. None (the default) resolves to exact
    f32 under interpret and bf16 on TPU — see ``_resolve_w_dtype``.
    Explicit f32 on TPU restores multi-pass f32 MXU semantics (slower,
    more mantissa); explicit bf16 under interpret is the ablation/test
    knob. The bf16 path's dequant arithmetic variant comes from
    ``DEQUANT_MODE`` (env DLLAMA_DEQUANT / set_dequant_mode), resolved
    here so switching modes retraces; "auto" resolves per (d_in, d_out,
    m-class) from the persisted selection table (ops/dequant_select.py),
    deterministically at trace time, so a warmed family never re-resolves.
    Exact-f32 dots always use the v4 f32 chain; blockdot's post-scale FMA
    scales with m, so large-m calls (prefill/training) fall back to
    bf16chain."""
    w_dtype_r = _resolve_w_dtype(w_dtype, interpret)
    acts = x if isinstance(x, Q80Acts) else None
    xr = acts.x if acts is not None else x
    m = 1
    for s_ in xr.shape[:-1]:
        m *= s_
    mode = DEQUANT_MODE if w_dtype_r == jnp.bfloat16 else "v4"
    if mode == "auto":
        from .dequant_select import resolve_mode

        mode = resolve_mode(w.d_in, w.d_out, m)
    if mode in ("blockdot", "i8blockdot") and m > BLOCKDOT_MAX_M:
        mode = "bf16chain"
    if acts is not None:
        TRACE_STATS["shared_consumes"] += 1
        return _q40_matmul_acts_impl(acts, w, interpret, w_dtype_r, mode)
    return _q40_matmul_pallas_impl(x, w, interpret, w_dtype_r, mode)


@partial(jax.jit, static_argnames=("interpret", "w_dtype", "mode"))
def _q40_matmul_pallas_impl(x: jnp.ndarray, w: PackedQ40, interpret, w_dtype,
                            mode) -> jnp.ndarray:
    """Raw-x entry: builds the operand bundle inside the same trace (XLA
    DCEs the layouts `mode` does not touch), then runs the kernel."""
    return _q40_matmul_core(make_q80_acts(x), w, interpret, w_dtype, mode)


@partial(jax.jit, static_argnames=("interpret", "w_dtype", "mode"))
def _q40_matmul_acts_impl(acts: Q80Acts, w: PackedQ40, interpret, w_dtype,
                          mode) -> jnp.ndarray:
    """Prebuilt-operand entry. Q80Acts is a NamedTuple pytree, so inside an
    outer trace the bundle stays symbolic and one build feeds every
    consumer without re-tracing the prep."""
    return _q40_matmul_core(acts, w, interpret, w_dtype, mode)


def _q40_matmul_core(acts: Q80Acts, w: PackedQ40, interpret, w_dtype,
                     mode) -> jnp.ndarray:
    TRACE_STATS["impl_traces"] += 1
    if w.packed.ndim != 2:
        raise ValueError(f"expected 2D packed weight, got {w.packed.shape}")
    d_in, d_out = w.d_in, w.d_out
    half = d_in // 2
    if acts.d_in != d_in:
        raise ValueError(f"operand d_in {acts.d_in} != weight d_in {d_in}")
    plan = _plan_blocks(d_in, d_out)
    if plan is None:
        raise ValueError(
            f"shape ({d_in}, {d_out}) unsupported; use q40_matmul_xla"
        )
    w_tile, rows = plan
    sub = _sub_tiles(w_tile)
    n_k = half // rows

    lead = acts.x.shape[:-1]
    m = acts.m
    m_pad = acts.x_lo.shape[0]
    m_tile = min(M_TILE, m_pad)

    grid = (m_pad // m_tile, d_out // w_tile, n_k)

    scale_bits = jax.lax.bitcast_convert_type(w.scales, jnp.int16)

    aux_spec = pl.BlockSpec((rows // 16, m_tile), lambda i, j, k: (k, i))
    if mode == "blockdot":
        # x TRANSPOSED [rows, m]: the kernel slices 16-row (one quant
        # block) ranges, which must land on the sublane axis — sub-128
        # lane slices would relayout
        xa, xb_ = acts.x_lo_t, acts.x_hi_t
        aux = acts.bsum_t
        x_spec = pl.BlockSpec((rows, m_tile), lambda i, j, k: (k, i))
        kernel = partial(_q40_blockdot_kernel, sub_tiles=sub, n_k=n_k)
    elif mode == "i8blockdot":
        # Q80-quantized activations from the bundle; x TRANSPOSED like
        # blockdot; bsum (EXACT f32 sums) and the activation scales
        # interleave on the sublane axis
        xa, xb_ = acts.xq_lo_t, acts.xq_hi_t
        aux = acts.aux_t
        aux_spec = pl.BlockSpec(
            ((rows // 16) * 2, m_tile), lambda i, j, k: (k, i)
        )
        x_spec = pl.BlockSpec((rows, m_tile), lambda i, j, k: (k, i))
        kernel = partial(_q40_i8blockdot_kernel, sub_tiles=sub, n_k=n_k)
    else:
        xa, xb_ = acts.x_lo, acts.x_hi
        aux = acts.bsum_t
        x_spec = pl.BlockSpec((m_tile, rows), lambda i, j, k: (i, k))
        kernel = partial(_q40_slab_kernel, w_dtype=w_dtype, sub_tiles=sub,
                         n_k=n_k, mode=mode)

    out_dtype = acts.x.dtype
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            x_spec,
            x_spec,
            aux_spec,
            pl.BlockSpec((rows, w_tile), lambda i, j, k: (k, j)),
            pl.BlockSpec((rows // 16, w_tile), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((m_tile, w_tile), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, d_out), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((m_tile, w_tile if n_k > 1 else SUB_TILE), jnp.float32)
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad * d_in * d_out,
            bytes_accessed=d_in * d_out // 2 + (d_in // 32) * d_out * 2
            + m_pad * d_in * 4 + m_pad * d_out * out_dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(xa, xb_, aux, w.packed, scale_bits)

    return out[:m].reshape(*lead, d_out)


# ---------------------------------------------------------------------------
# GSPMD integration: a partitioning rule for the kernel.
#
# Pallas calls are opaque to the SPMD partitioner, so without this a sharded
# forward would have to fall back to XLA dequant (round 1 disabled the kernel
# under any mesh). custom_partitioning teaches XLA to treat the quantized
# matmul like a dot: row-sliced weights (d_out sharded, reference
# sliceRowMatmul src/nn/nn-core.cpp:207-217) run the kernel per shard with a
# sharded output; col-sliced weights (d_in sharded, sliceColMatmul
# :219-230) run it per shard and psum the partial sums — the collective the
# reference realizes as its quantized TCP all-gather + merge_add.
# ---------------------------------------------------------------------------

from jax.experimental.custom_partitioning import custom_partitioning  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def _q40_mm_impl(x, packed, scales, interpret, w_dtype):
    """Single-shard implementation: Pallas when the (local) shapes fit,
    XLA dequant otherwise. Runs unmodified on 1 device; partitioned, each
    shard re-evaluates `pallas_supports` on its local shapes."""
    from ..quants.packed import q40_matmul_xla

    w = PackedQ40(packed=packed, scales=scales)
    if pallas_supports(w):
        return q40_matmul_pallas(x, w, interpret=interpret, w_dtype=w_dtype)
    return q40_matmul_xla(x, w)


def _pad_spec(sharding, rank):
    spec = tuple(sharding.spec) if sharding.spec is not None else ()
    return spec + (None,) * (rank - len(spec))


def _spec_axes(entry):
    if entry is None:
        return set()
    return set(entry) if isinstance(entry, tuple) else {entry}


def _plan(mesh, arg_shapes):
    """(x_spec, packed_spec, scales_spec, out_spec, k_spec) — the canonical
    sharding layout nearest to what the operands arrived with."""
    x_s, p_s, _ = (a.sharding for a in arg_shapes)
    x_rank = len(arg_shapes[0].shape)
    x_spec = _pad_spec(x_s, x_rank)
    p_spec = _pad_spec(p_s, 2)

    k_spec = p_spec[0] if p_spec[0] is not None else x_spec[-1]
    n_spec = p_spec[1]
    if _spec_axes(k_spec) & _spec_axes(n_spec):
        k_spec = None  # conflicting proposal: replicate the contraction
    used = _spec_axes(k_spec) | _spec_axes(n_spec)
    lead = tuple(s if not (_spec_axes(s) & used) else None for s in x_spec[:-1])

    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    return (
        ns(*lead, k_spec),
        ns(k_spec, n_spec),
        ns(k_spec, n_spec),
        ns(*lead, n_spec),
        k_spec,
    )


def _q40_mm_infer_sharding(interpret, w_dtype, mesh, arg_shapes, result_shape):
    del interpret, w_dtype, result_shape
    return _plan(mesh, arg_shapes)[3]


def _q40_mm_partition(interpret, w_dtype, mesh, arg_shapes, result_shape):
    del result_shape
    x_sh, p_sh, s_sh, out_sh, k_spec = _plan(mesh, arg_shapes)

    def lower(x, packed, scales):
        y = _q40_mm_impl(x, packed, scales, interpret, w_dtype)
        if k_spec is not None:
            y = _contraction_sync(y, k_spec, mesh)
        return y

    return mesh, lower, out_sh, (x_sh, p_sh, s_sh)


def _contraction_sync(y, k_spec, mesh):
    """The col-sliced partial-sum sync: a ring all-reduce (n-1 chunk-sized
    hops XLA overlaps with the surrounding compute — ops/ring_collective.py)
    when the ring engages, else the plain psum. DLLAMA_RING_SYNC=off (or
    set_ring_sync(False)) restores the psum path bit-for-bit; tuple axis
    specs and non-tiling widths fall back to psum inside ring_all_reduce."""
    from .ring_collective import ring_all_reduce, ring_sync_enabled

    if ring_sync_enabled() and isinstance(k_spec, str):
        return ring_all_reduce(y, k_spec, mesh.shape[k_spec])
    return jax.lax.psum(y, k_spec)


_q40_mm = custom_partitioning(_q40_mm_impl, static_argnums=(3, 4))
try:
    _q40_mm.def_partition(
        partition=_q40_mm_partition,
        infer_sharding_from_operands=_q40_mm_infer_sharding,
        # x [..., (b*32)], packed [(b*16), n], scales [b, n] -> [..., n]:
        # b = quant blocks of the contraction (reduction); the intra-block
        # subfactors must never be split across devices
        sharding_rule="... (b t), (b s) n, b n -> ... n",
        reduction_factors=("b",),
        need_replication_factors=("t", "s"),
        t=32,
        s=16,
    )
except TypeError:
    # older jax: no shardy sharding_rule/factor kwargs — GSPMD partitions
    # through the infer/partition callbacks alone, which carry the same
    # constraints, so dropping the rule only loses shardy support
    _q40_mm.def_partition(
        partition=_q40_mm_partition,
        infer_sharding_from_operands=_q40_mm_infer_sharding,
    )


def q40_matmul_partitioned(x: jnp.ndarray, w: PackedQ40, interpret: bool = False,
                           w_dtype=None) -> jnp.ndarray:
    """y = x @ dequant(w), partitionable under GSPMD meshes (TP/EP serving
    keeps dequant-in-matmul, closing round 1's 'Pallas disabled under any
    mesh' gap). Single device: identical to q40_matmul_pallas with XLA
    fallback for unsupported shapes."""
    return _q40_mm(x, w.packed, w.scales, interpret, w_dtype)
