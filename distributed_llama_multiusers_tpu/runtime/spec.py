"""Prompt-lookup draft index for speculative decoding.

Drafts come from the token stream itself: the previous occurrence of the
current suffix n-gram (3-gram, falling back to 2-gram) proposes the tokens
that followed it — no draft model. The index is maintained incrementally
(each committed token updates two dict entries), so a draft probe is O(1)
per step instead of a backward history scan.

Used by the continuous-batching scheduler (per lane) and the CLI inference
loop (single stream). The engine's verify program
(`InferenceEngine.decode_spec`) guarantees the speculative-verification
identity: greedy output streams are exactly the plain-decode streams.
"""

from __future__ import annotations

# drafts per speculative step (K = SPEC_DRAFT + 1 verified tokens); shared
# by the engine's verify program and the control plane's packet sizing
SPEC_DRAFT = 3


def pow2_floor(h: int) -> int:
    """Largest power of two <= h (0 for h < 1). The ONE bucketing rule for
    multi-step horizons: every dispatch site must land on these buckets so
    warmup_engine's compiled program is the one the serving loop uses."""
    return 1 << (h.bit_length() - 1) if h >= 1 else 0


class NgramDraftIndex:
    """Committed token history + n-gram -> last-start-position index."""

    GRAM_SIZES = (2, 3)

    def __init__(self, tokens=()):
        self.hist: list[int] = []
        self._last: dict = {}
        for t in tokens:
            self.append(t)

    def append(self, tok: int) -> None:
        self.hist.append(tok)
        for g in self.GRAM_SIZES:
            if len(self.hist) >= g:
                self._last[(g, tuple(self.hist[-g:]))] = len(self.hist) - g

    def draft(self, next_token: int, k: int) -> list[int]:
        """Up to k draft tokens continuing (hist + [next_token]). Each probe
        gram ends at a not-yet-committed token, so a hit is always a
        strictly earlier occurrence; the draft EXTENDS by re-probing over
        the virtual tail (hist + next_token + tokens drafted so far), so a
        short-period stream — whose last occurrence sits right at the end
        of history and offers at most period-1 continuation tokens in one
        lookup — still drafts the full k. (The pipelined chain needs this:
        its carry-alignment gate spends one candidate, so single-token
        probes could never accelerate a period-2 stream.)"""
        hist = self.hist
        nh = len(hist)
        # the VIRTUAL region: next_token + tokens drafted so far. Indexing
        # spans (hist ++ virt) WITHOUT copying the history — the probe is
        # O(k·gram), not O(history), and it runs per lane per dispatch.
        virt = [next_token]

        def at(i: int) -> int:
            return hist[i] if i < nh else virt[i - nh]

        # transient index over grams ending strictly before the current
        # tail (so a probe can never match itself): a period-p stream's
        # only earlier occurrence sits p tokens back, which is inside the
        # virtual region after the first few drafts
        overlay: dict = {}
        gmax = sorted(self.GRAM_SIZES, reverse=True)
        while len(virt) <= k:
            total = nh + len(virt)
            nxt = None
            for g in gmax:
                if total < g:
                    continue
                tail = tuple(at(total - g + j) for j in range(g))
                j = overlay.get((g, tail))
                if j is None:
                    j = self._last.get((g, tail))
                if j is not None and j + g < total:
                    nxt = at(j + g)
                    break
            if nxt is None:
                break
            # the tail's own grams become legal matches once a token
            # follows them — record them before appending
            for g in self.GRAM_SIZES:
                if total >= g:
                    overlay[(g, tuple(at(total - g + j) for j in range(g)))] = (
                        total - g
                    )
            virt.append(nxt)
        return virt[1:]


class SpecStream:
    """Single-stream speculative decode for the CLIs (inference AND chat):
    prompt-lookup drafts plus a pending-lookahead buffer, so greedy runs
    emit >1 token per forward when drafts hit while keeping the exact
    plain-decode token stream (speculative-verification identity).

    Per-stream analogue of the scheduler's per-lane spec path; near
    seq_len the draft length is clamped to the slots left (the cache
    scatter drops overshooting writes — models/llama.py KV append)."""

    def __init__(self, engine, config, enabled: bool, prompt_tokens=(),
                 multi_h: int = 0):
        """``multi_h`` > 1 enables the multi-step fallback for GREEDY
        streams: when no draft hits, chain up to that many decode steps in
        one device dispatch (engine.decode_multi) and serve the chained
        tokens from the same pending-lookahead buffer drafts use — one
        host round-trip per horizon instead of per token. Temperature>0
        callers must leave it 0 (they sample from last_logits every
        step)."""
        import numpy as np

        self.engine = engine
        self.config = config
        self.spec_k = getattr(engine, "SPEC_DRAFT", 0)
        self.enabled = (
            enabled
            and self.spec_k > 0
            and getattr(engine, "supports_speculative", False)
        )
        self.drafter = NgramDraftIndex(prompt_tokens) if self.enabled else None
        self.multi_h = (
            multi_h
            if multi_h > 1 and getattr(engine, "supports_multi_step", False)
            else 0
        )
        self.pending: list[int] = []  # produced-but-not-yet-emitted lookahead
        # whether `pending` came from a spec verify (counts toward the
        # speculation acceptance stats) or a multi-step horizon (must not)
        self._pending_spec = False
        # tokens already consumed from the CURRENT spec lookahead's verify
        # step (seq[0] counts at verify time): discard_pending() needs it
        # to retract a partially consumed step from the acceptance math
        self._pending_consumed = 0
        self._toks = np.zeros(engine.n_lanes, np.int32)
        self._poss = np.zeros(engine.n_lanes, np.int32)
        self.last_logits = None  # batch logits of the last real forward

    def extend_history(self, tokens) -> None:
        """Feed non-generated tokens (chat-turn prompts) to the draft index."""
        if self.drafter is not None:
            for t in tokens:
                self.drafter.append(int(t))

    def discard_pending(self) -> None:
        """Drop the unconsumed lookahead at a turn boundary (chat mode:
        spec tokens drafted past EOS are uncommitted cache scribble the
        next prefill overwrites — but the HOST-side buffer must go).

        Accounting: a spec verify whose lookahead is only PARTIALLY
        consumed is RETRACTED from the acceptance counters
        (``spec_lane_steps`` / ``spec_emitted``), not left dangling — the
        bench/stats acceptance ratio (emitted per drafted lane-step, class
        [1, K+1]) aggregates only fully realized steps, so a turn ending
        mid-lookahead can neither deflate it nor strand a lane-step whose
        emitted count no longer means anything. Counters never go below 0
        (a stats window reset between verify and discard clamps)."""
        if self.pending and self._pending_spec:
            stats = getattr(self.engine, "stats", None)
            if stats is not None:
                with stats.lock:
                    stats.spec_lane_steps = max(0, stats.spec_lane_steps - 1)
                    stats.spec_emitted = max(
                        0, stats.spec_emitted - self._pending_consumed
                    )
        self.pending.clear()
        self._pending_spec = False
        self._pending_consumed = 0

    def flush_pipeline(self) -> None:
        """Flush any live async-decode chain before a direct engine call:
        SpecStream's spec/multi/plain steps thread the same KV cache, and a
        device-fed chain still in flight would keep feeding tokens from a
        history this stream has moved past. No-op on engines without the
        pipelined family or with nothing in flight."""
        if getattr(self.engine, "pipeline_active", False):
            self.engine.pipeline_flush()

    def advance(self, cur: int, pos: int):
        """Commit ``cur`` at ``pos`` and return ``(next_token, used_forward)``.
        used_forward=False means the token came from the pending lookahead
        (its cache write already happened in the spec step that drafted it).
        For temperature>0 callers (spec disabled), sample from
        ``last_logits`` instead of the returned greedy token."""
        import numpy as np

        if self.pending:
            if self.drafter is not None:
                self.drafter.append(cur)
            stats = getattr(self.engine, "stats", None)
            if stats is not None and self._pending_spec:
                with stats.lock:
                    stats.spec_emitted += 1  # lookahead token consumed NOW
                self._pending_consumed += 1
            return self.pending.pop(0), False
        self.flush_pipeline()  # about to touch the engine directly
        draft: list[int] = []
        if self.drafter is not None:
            d_max = min(self.spec_k, self.config.seq_len - pos - 1)
            if d_max > 0:
                draft = self.drafter.draft(cur, self.spec_k)[:d_max]
            self.drafter.append(cur)
        self._toks[0] = cur
        self._poss[0] = pos
        if draft:
            drafts = np.zeros((self.engine.n_lanes, self.spec_k), np.int32)
            dlen = np.zeros(self.engine.n_lanes, np.int32)
            drafts[0, : len(draft)] = draft
            dlen[0] = len(draft)
            _, em, ne = self.engine.decode_spec(
                self._toks, drafts, dlen, self._poss
            )
            seq = [int(t) for t in em[0, : int(ne[0])]]
            self.pending = seq[1:]
            self._pending_spec = True
            self._pending_consumed = 1  # seq[0] is consumed below
            # consumed-only accounting, same semantics as the scheduler's
            # loop: the tokens still in `pending` count when popped (and
            # never count if a turn ends and discards them)
            stats = getattr(self.engine, "stats", None)
            if stats is not None:
                with stats.lock:
                    stats.spec_lane_steps += 1
                    stats.spec_emitted += 1  # seq[0], consumed now
            return seq[0], True
        if self.multi_h > 1:
            # no draft: chain a horizon of plain decode steps instead of
            # one. KV alignment matches the spec path: the scan feeds
            # cur, chosen[0..h-2] at pos..pos+h-1 (all written); the last
            # chosen token is fed by a later advance() forward.
            p = pow2_floor(min(self.multi_h, self.config.seq_len - pos))
            if p > 1:
                chosen = self.engine.decode_multi(self._toks, self._poss, h=p)
                seq = [int(chosen[j, 0]) for j in range(p)]
                self.pending = seq[1:]
                self._pending_spec = False
                return seq[0], True
        logits_b, greedy_b, _ = self.engine.decode(self._toks, self._poss)
        self.last_logits = logits_b
        return int(greedy_b[0]), True
