#!/usr/bin/env python
"""Fire N concurrent chat completions at a running dllama-api server — the
local-cluster stress analogue of the reference's examples/n-workers.sh
(here concurrency is request lanes, not worker processes).

    python examples/multi-user-stress.py [url] [n_clients]
"""

import json
import sys
import threading
import time
import urllib.request

URL = sys.argv[1] if len(sys.argv) > 1 else "http://localhost:9990"
N = int(sys.argv[2]) if len(sys.argv) > 2 else 8

results = {}


def client(i):
    body = json.dumps(
        {
            "messages": [{"role": "user", "content": f"Tell me fact #{i} about llamas."}],
            "max_tokens": 48,
            "temperature": 0.7,
            "seed": i,
        }
    ).encode()
    req = urllib.request.Request(
        URL + "/v1/chat/completions", data=body, headers={"Content-Type": "application/json"}
    )
    t0 = time.time()
    with urllib.request.urlopen(req, timeout=600) as r:
        out = json.loads(r.read())
    results[i] = (time.time() - t0, out["usage"]["completion_tokens"])


threads = [threading.Thread(target=client, args=(i,)) for i in range(N)]
t0 = time.time()
for t in threads:
    t.start()
for t in threads:
    t.join()
wall = time.time() - t0
total_tokens = sum(n for _, n in results.values())
print(f"{N} concurrent clients: {wall:.2f}s wall, {total_tokens} tokens, "
      f"{total_tokens / wall:.1f} tok/s aggregate")
for i, (dt, n) in sorted(results.items()):
    print(f"  client {i}: {dt:6.2f}s  {n} tokens")
