"""Weight loading: .m file -> LlamaParams pytree.

Replaces the reference's split-and-ship weight path (src/llm.cpp:447-483,
src/nn/nn-network.cpp:824-901): instead of slicing shards on the root and
streaming them to workers over TCP, tensors are dequantized host-side and
handed to jax.device_put with sharding annotations — PJRT does the
placement/transfer that NnRootWeightLoader did by hand.
"""

from __future__ import annotations

import ml_dtypes
import numpy as np
import jax
import jax.numpy as jnp

_BF16_NP = np.dtype(ml_dtypes.bfloat16)

from ..formats.model_file import ModelHeader, iter_model_tensors
from ..ops.rope import build_rope_cache
from ..quants.codec import FloatType, dequantize_q40, dequantize_q80
from ..quants.packed import (
    PackedQ40,
    pack_q40_from_blocks,
    pack_q40_host,
    pad_packed_d_out,
)
from .config import LlamaConfig
from .llama import LlamaLayerParams, LlamaParams


def _decode_tensor(raw: np.ndarray, float_type: int, shape: tuple[int, int]) -> np.ndarray:
    from .. import native

    if float_type == FloatType.F32:
        x = raw.view("<f4").astype(np.float32)
    elif float_type == FloatType.F16:
        x = raw.view("<f2").astype(np.float32)
    elif float_type == FloatType.Q40:
        # threaded C++ dequant when built (native/quant_codec.cpp), numpy else
        x = native.dequantize_q40(raw)
        if x is None:
            x = dequantize_q40(raw)
    elif float_type == FloatType.Q80:
        x = native.dequantize_q80(raw)
        if x is None:
            x = dequantize_q80(raw)
    else:
        raise ValueError(f"unsupported float type {float_type}")
    return np.ascontiguousarray(x.reshape(shape))


_TENSOR_NAME_MAP = {
    "block_matmul_q": "wq",
    "block_matmul_k": "wk",
    "block_matmul_v": "wv",
    "block_matmul_wo": "wo",
    "block_matmul_w1": "w1",
    "block_matmul_w2": "w2",
    "block_matmul_w3": "w3",
    "block_rms_norm_0": "rms_att",
    "block_rms_norm_1": "rms_ffn",
    # Qwen2-family projection biases (header.qkv_bias; absent otherwise)
    "block_bias_q": "bq",
    "block_bias_k": "bk",
    "block_bias_v": "bv",
}

_BIAS_KEYS = ("bq", "bk", "bv")
# per-layer [n]-vector tensors (everything else under _TENSOR_NAME_MAP is a
# [d_out, d_in] matmul weight)
_VECTOR_KEYS = {"rms_att", "rms_ffn", *_BIAS_KEYS}


def read_m_tensors(path: str, header: ModelHeader) -> dict:
    """Read a .m file as dequantized f32 arrays in file orientation
    ([d_out, d_in] matmuls): embedding, rms_final, wcls plus per-layer lists
    wq,wk,wv,wo,w1,w2,w3,rms_att,rms_ffn (order: src/llm.cpp:447-483).

    MoE files additionally yield per-layer "moe_gate" [n_experts, dim] and
    w1/w2/w3 as per-layer [n_experts, d_out, d_in] stacks."""
    config = LlamaConfig.from_header(header)
    L, E = config.n_layers, config.n_experts
    w: dict = {k: [None] * L for k in _TENSOR_NAME_MAP.values()}
    if E > 0:
        w["moe_gate"] = [None] * L
        for key in ("w1", "w2", "w3"):
            w[key] = [[None] * E for _ in range(L)]
    for spec, raw in iter_model_tensors(path, header):
        x = _decode_tensor(raw, spec.float_type, spec.shape)
        if spec.name == "embedding":
            w["embedding"] = x
        elif spec.name == "final_rms_norm":
            w["rms_final"] = x.reshape(-1)
        elif spec.name == "final_matmul_logits":
            w["wcls"] = x
        elif spec.name == "block_moe_gate":
            w["moe_gate"][spec.layer] = x
        else:
            key = _TENSOR_NAME_MAP[spec.name]
            if spec.expert >= 0:
                w[key][spec.layer][spec.expert] = x
            else:
                w[key][spec.layer] = x.reshape(-1) if key in _VECTOR_KEYS else x
    if not header.qkv_bias:
        for key in _BIAS_KEYS:
            del w[key]
    if E > 0:
        for key in ("w1", "w2", "w3"):
            w[key] = [np.stack(mats) for mats in w[key]]  # [E, d_out, d_in] per layer
    return w


def _rope_cache(config: LlamaConfig):
    return build_rope_cache(
        config.seq_len,
        config.head_size,
        config.rope_theta,
        config.rope_scaling_factor,
        config.rope_scaling_low_freq_factor,
        config.rope_scaling_high_freq_factor,
        config.rope_scaling_orig_max_seq_len,
    )


def _cast_fn(dtype):
    """Host-side pre-cast where a numpy dtype exists; bf16 has no plain numpy
    dtype, so it casts at device_put time instead."""
    np_dtype = np.dtype(jnp.dtype(dtype).name) if jnp.dtype(dtype) != jnp.bfloat16 else None

    def cast(x: np.ndarray) -> np.ndarray:
        return x if np_dtype is None else x.astype(np_dtype)

    return cast


def load_params_from_m(
    path: str,
    header: ModelHeader,
    dtype=jnp.bfloat16,
    device_put_fn=None,
) -> tuple[LlamaConfig, LlamaParams]:
    """Load and dequantize all tensors; matmul weights are transposed to
    [d_in, d_out] (the .m stores [d_out, d_in], src/llm.cpp:447-483) and
    per-layer tensors stacked along a leading [n_layers] axis.

    ``device_put_fn(name, np_array) -> jax.Array`` lets callers control
    placement/sharding; defaults to plain jnp.asarray.
    """
    config = LlamaConfig.from_header(header)
    put = device_put_fn or (lambda name, x: jnp.asarray(x))

    raw_w = read_m_tensors(path, header)
    embedding = raw_w["embedding"]
    rms_final = raw_w["rms_final"]
    wcls = raw_w["wcls"].T  # -> [dim, vocab]
    stacked = {}
    for key in _TENSOR_NAME_MAP.values():
        if key not in raw_w:
            continue  # bias keys absent on bias-free models
        mats = raw_w[key]
        if key in _VECTOR_KEYS:
            stacked[key] = np.stack(mats)
        else:
            # -> [L, d_in, d_out] (MoE ffn: [L, E, d_in, d_out])
            stacked[key] = np.stack([np.swapaxes(m, -1, -2) for m in mats])

    moe_gate = None
    if config.n_experts > 0:
        # [L, n_experts, dim] -> [L, dim, n_experts] for y @ gate
        moe_gate = np.swapaxes(np.stack(raw_w["moe_gate"]), -1, -2)

    cast = _cast_fn(dtype)
    cos, sin = _rope_cache(config)

    layers = LlamaLayerParams(
        moe_gate=(
            put("moe_gate", moe_gate).astype(jnp.float32) if moe_gate is not None else None
        ),
        wq=put("wq", cast(stacked["wq"])).astype(dtype),
        wk=put("wk", cast(stacked["wk"])).astype(dtype),
        wv=put("wv", cast(stacked["wv"])).astype(dtype),
        wo=put("wo", cast(stacked["wo"])).astype(dtype),
        w1=put("w1", cast(stacked["w1"])).astype(dtype),
        w2=put("w2", cast(stacked["w2"])).astype(dtype),
        w3=put("w3", cast(stacked["w3"])).astype(dtype),
        rms_att=put("rms_att", stacked["rms_att"]).astype(jnp.float32),
        rms_ffn=put("rms_ffn", stacked["rms_ffn"]).astype(jnp.float32),
        **{
            k: put(k, stacked[k]).astype(jnp.float32)
            for k in _BIAS_KEYS
            if k in stacked
        },
    )
    params = LlamaParams(
        embedding=put("embedding", cast(embedding)).astype(dtype),
        layers=layers,
        rms_final=put("rms_final", rms_final).astype(jnp.float32),
        wcls=put("wcls", cast(wcls)).astype(dtype),
        rope_cos=put("rope_cos", cos),
        rope_sin=put("rope_sin", sin),
    )
    return config, params


_MATMUL_KEYS = ("wq", "wk", "wv", "wo", "w1", "w2", "w3")


def load_params_from_m_quantized(
    path: str,
    header: ModelHeader,
    dtype=jnp.bfloat16,
    device_put_fn=None,
) -> tuple[LlamaConfig, LlamaParams]:
    """Load a Q40 .m keeping matmul weights quantized on device (PackedQ40:
    int4 nibbles + f16 block scales, quants/packed.py) — the TPU equivalent of
    the reference running Q40 weights at rest (src/nn/nn-cpu-ops.cpp:222-440).
    Non-Q40 matmul tensors (f32/f16 models) are loaded dense; embedding and
    norms are always dense (gather/elementwise ops want plain arrays)."""
    config = LlamaConfig.from_header(header)
    put = device_put_fn or (lambda name, x: jnp.asarray(x))
    L, E = config.n_layers, config.n_experts

    def empty(key):
        if E > 0 and key in ("w1", "w2", "w3"):
            return [[None] * E for _ in range(L)]
        return [None] * L

    dense: dict = {}
    packed_w: dict = {k: empty(k) for k in _MATMUL_KEYS}
    for spec, raw in iter_model_tensors(path, header):
        is_matmul = spec.name.startswith("block_matmul_") or spec.name == "final_matmul_logits"
        if is_matmul and spec.float_type == FloatType.Q40:
            pk, sc = pack_q40_from_blocks(raw, spec.shape)
            if spec.name == "final_matmul_logits":
                # pad vocab width for the slab kernel's wide tiles; the
                # model slices logits back to vocab_size (llama_forward)
                dense["wcls"] = ("q40", *pad_packed_d_out(pk, sc))
            else:
                key = _TENSOR_NAME_MAP[spec.name]
                if spec.expert >= 0:
                    packed_w[key][spec.layer][spec.expert] = (pk, sc)
                else:
                    packed_w[key][spec.layer] = (pk, sc)
        else:
            x = _decode_tensor(raw, spec.float_type, spec.shape)
            if spec.name == "embedding":
                dense["embedding"] = x
            elif spec.name == "final_rms_norm":
                dense["rms_final"] = x.reshape(-1)
            elif spec.name == "final_matmul_logits":
                dense["wcls"] = ("dense", x.T)
            elif spec.name == "block_moe_gate":
                dense.setdefault("moe_gate", [None] * L)
                dense["moe_gate"][spec.layer] = x
            else:
                key = _TENSOR_NAME_MAP[spec.name]
                dense.setdefault(key, [None] * L if spec.expert < 0 else empty(key))
                if spec.expert >= 0:
                    dense[key][spec.layer][spec.expert] = x
                else:
                    dense[key][spec.layer] = x.reshape(-1) if key in _VECTOR_KEYS else x

    cast = _cast_fn(dtype)

    def _flatten(entries):
        """Per-layer entries, or per-layer-per-expert lists, flattened."""
        for m in entries:
            if isinstance(m, list):
                yield from m
            else:
                yield m

    def _stack_tree(entries, pick):
        """np.stack over layers (and experts for MoE nested lists)."""
        if isinstance(entries[0], list):
            return np.stack([np.stack([pick(m) for m in layer]) for layer in entries])
        return np.stack([pick(m) for m in entries])

    def stack_packed(key: str):
        mats = packed_w[key]
        flat = list(_flatten(mats))
        if all(m is not None for m in flat):
            return PackedQ40(
                packed=put(key, _stack_tree(mats, lambda m: m[0])),
                scales=put(key + ".scales", _stack_tree(mats, lambda m: m[1])),
            )
        if any(m is not None for m in flat):
            # float_type is per-tensor in the .m header, so this is encodable
            # but no converter emits it; fail clearly rather than stack holes
            raise ValueError(
                f"{key}: tensors mix Q40 and non-Q40 float types; "
                "mixed quantization is not supported"
            )
        # dense fallback (non-Q40 model): same path as load_params_from_m
        return put(
            key, cast(_stack_tree(dense[key], lambda m: np.swapaxes(m, -1, -2)))
        ).astype(dtype)

    cos, sin = _rope_cache(config)
    moe_gate = None
    if E > 0:
        moe_gate = put(
            "moe_gate", np.swapaxes(np.stack(dense["moe_gate"]), -1, -2)
        ).astype(jnp.float32)
    layers = LlamaLayerParams(
        **{k: stack_packed(k) for k in _MATMUL_KEYS},
        rms_att=put("rms_att", np.stack(dense["rms_att"])).astype(jnp.float32),
        rms_ffn=put("rms_ffn", np.stack(dense["rms_ffn"])).astype(jnp.float32),
        moe_gate=moe_gate,
        **{
            k: put(k, np.stack(dense[k])).astype(jnp.float32)
            for k in _BIAS_KEYS
            if k in dense
        },
    )
    wcls_entry = dense["wcls"]
    if wcls_entry[0] == "q40":
        wcls = PackedQ40(packed=put("wcls", wcls_entry[1]), scales=put("wcls.scales", wcls_entry[2]))
    else:
        wcls = put("wcls", cast(wcls_entry[1])).astype(dtype)
    params = LlamaParams(
        embedding=put("embedding", cast(dense["embedding"])).astype(dtype),
        layers=layers,
        rms_final=put("rms_final", dense["rms_final"]).astype(jnp.float32),
        wcls=wcls,
        rope_cos=put("rope_cos", cos),
        rope_sin=put("rope_sin", sin),
    )
    return config, params


def quantize_params(params: LlamaParams, to_device: bool = True) -> LlamaParams:
    """Quantize a dense params pytree to PackedQ40 layer matmuls + wcls
    (through the bit-exact Q40 encoder). Fully host-side for numpy inputs —
    combine with ``params_from_random(..., to_device=False)`` so multi-GB
    dense weights never cross the host<->device link (which can be a slow
    tunnel); with ``to_device=False`` the packed planes also stay numpy for
    the caller to place (e.g. with mesh shardings)."""
    up = jnp.asarray if to_device else (lambda x: x)

    def q(w, pad: bool = False) -> PackedQ40:
        # w: [L?, d_in, d_out] device/numpy array -> file orientation then pack
        wf = np.swapaxes(np.asarray(w, np.float32), -1, -2)
        pk, sc = pack_q40_host(wf)
        if pad:  # wcls: widen vocab for the slab kernel (logits re-sliced)
            pk, sc = pad_packed_d_out(pk, sc)
        return PackedQ40(packed=up(pk), scales=up(sc))

    layers = params.layers._replace(**{k: q(getattr(params.layers, k)) for k in _MATMUL_KEYS})
    return params._replace(layers=layers, wcls=q(params.wcls, pad=True))


def params_from_random(
    config: LlamaConfig,
    seed: int = 0,
    dtype=jnp.bfloat16,
    scale: float = 0.02,
    to_device: bool = True,
) -> LlamaParams:
    """Random-weight params with the right shapes — used by benchmarks so that
    multi-GB models need not exist on disk. ``to_device=False`` keeps every
    leaf a host numpy array (bf16 via ml_dtypes) so nothing crosses the
    host->device link until the caller places it."""
    rng = np.random.default_rng(seed)
    L, dim, hidden, kv_dim, vocab = (
        config.n_layers,
        config.dim,
        config.hidden_dim,
        config.kv_dim,
        config.vocab_size,
    )

    np_dtype = (
        _BF16_NP if jnp.dtype(dtype) == jnp.bfloat16 else np.dtype(jnp.dtype(dtype).name)
    )

    def arr(x, d=None):
        return jnp.asarray(x, dtype=d) if to_device else np.asarray(x, dtype=d)

    def r(*shape):
        w = rng.standard_normal(shape, dtype=np.float32) * scale
        return jnp.asarray(w, dtype=dtype) if to_device else w.astype(np_dtype)

    cos, sin = _rope_cache(config)
    E = config.n_experts
    ffn_lead = (L, E) if E > 0 else (L,)
    layers = LlamaLayerParams(
        wq=r(L, dim, dim),
        wk=r(L, dim, kv_dim),
        wv=r(L, dim, kv_dim),
        wo=r(L, dim, dim),
        w1=r(*ffn_lead, dim, hidden),
        w2=r(*ffn_lead, hidden, dim),
        w3=r(*ffn_lead, dim, hidden),
        rms_att=arr(np.ones((L, dim), np.float32)),
        rms_ffn=arr(np.ones((L, dim), np.float32)),
        moe_gate=(
            arr(rng.standard_normal((L, dim, E), dtype=np.float32) * scale)
            if E > 0
            else None
        ),
    )
    return LlamaParams(
        embedding=r(vocab, dim),
        layers=layers,
        rms_final=arr(np.ones((dim,), np.float32)),
        wcls=r(dim, vocab),
        rope_cos=arr(cos),
        rope_sin=arr(sin),
    )
