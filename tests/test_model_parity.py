"""Model correctness: JAX forward vs the numpy oracle (reference semantics).

The bar mirrors BASELINE.json's "output token-identical to 1-node CPU
reference": greedy tokens from the XLA path must equal the oracle's.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.formats.model_file import RopeType
from distributed_llama_multiusers_tpu.formats.synthetic import tiny_header, write_synthetic_model
from distributed_llama_multiusers_tpu.models import (
    LlamaConfig,
    init_kv_cache,
    llama_forward,
    load_params_from_m,
)
from distributed_llama_multiusers_tpu.models.oracle import OracleLlama, oracle_weights_from_m


@pytest.fixture(scope="module")
def loaded(tmp_path_factory):
    d = tmp_path_factory.mktemp("parity")
    header = tiny_header(dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=96, seq_len=48)
    path = str(d / "m.m")
    write_synthetic_model(path, header, seed=3)
    h = load_model_header(path)
    config, params = load_params_from_m(path, h, dtype=jnp.float32)
    oracle = OracleLlama(config, oracle_weights_from_m(path, h), emulate_q80=True)
    return config, params, oracle


def jax_greedy(config, params, prompt, n_steps, emulate_q80=True):
    cache = init_kv_cache(config, n_lanes=1)
    fwd = jax.jit(
        lambda p, tok, pos, c: llama_forward(config, p, tok, pos, c, emulate_q80_activations=emulate_q80)
    )
    logits = None
    for i, t in enumerate(prompt):
        logits, cache = fwd(params, jnp.array([[t]], jnp.int32), jnp.array([[i]], jnp.int32), cache)
    out = []
    pos = len(prompt)
    cur = int(jnp.argmax(logits[0, -1]))
    for _ in range(n_steps):
        out.append(cur)
        logits, cache = fwd(params, jnp.array([[cur]], jnp.int32), jnp.array([[pos]], jnp.int32), cache)
        pos += 1
        cur = int(jnp.argmax(logits[0, -1]))
    return out


def test_single_step_logits_close(loaded):
    config, params, oracle = loaded
    oracle.reset()
    ref = oracle.forward(5, 0)
    cache = init_kv_cache(config, 1)
    logits, _ = llama_forward(
        config, params, jnp.array([[5]], jnp.int32), jnp.array([[0]], jnp.int32), cache,
        emulate_q80_activations=True,
    )
    got = np.asarray(logits[0, 0])
    assert np.abs(got - ref).max() < 5e-3, np.abs(got - ref).max()


def test_greedy_token_parity(loaded):
    config, params, oracle = loaded
    prompt = [1, 17, 42, 9]
    n = 16
    ref_tokens = oracle.generate_greedy(prompt, n)
    jax_tokens = jax_greedy(config, params, prompt, n)
    assert jax_tokens == ref_tokens


def test_prefill_matches_tokenwise_decode(loaded):
    """Chunked prefill (T>1) must produce the same cache/logits as feeding
    tokens one at a time — this is what makes fixing reference defect (a)
    [only token[0] ever fed] safe."""
    config, params, _ = loaded
    prompt = [3, 8, 21, 33, 7]
    # token-by-token
    cache1 = init_kv_cache(config, 1)
    logits1 = None
    for i, t in enumerate(prompt):
        logits1, cache1 = llama_forward(
            config, params, jnp.array([[t]], jnp.int32), jnp.array([[i]], jnp.int32), cache1
        )
    # one prefill call
    cache2 = init_kv_cache(config, 1)
    toks = jnp.array([prompt], jnp.int32)
    poss = jnp.arange(len(prompt), dtype=jnp.int32)[None, :]
    logits2, cache2 = llama_forward(config, params, toks, poss, cache2)
    np.testing.assert_allclose(
        np.asarray(logits1[0, 0]), np.asarray(logits2[0, -1]), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(np.asarray(cache1.k), np.asarray(cache2.k), rtol=1e-5, atol=1e-5)


def test_lanes_are_independent(loaded):
    """Two lanes decoding different prompts must not interfere (fixes
    reference defect (c): shared KV cache across concurrent requests)."""
    config, params, _ = loaded
    pa = [1, 17, 42, 9]
    pb = [2, 30, 5]
    # separate single-lane runs
    ta = jax_greedy(config, params, pa, 8, emulate_q80=False)
    tb = jax_greedy(config, params, pb, 8, emulate_q80=False)

    # joint 2-lane run with per-lane positions (lane b starts later)
    cache = init_kv_cache(config, 2)
    fwd = jax.jit(lambda p, tok, pos, c: llama_forward(config, p, tok, pos, c))
    # prefill lane a fully, lane b padded with its own tokens repeated
    la, lb = len(pa), len(pb)
    logits = {}
    for i in range(la):
        tok = jnp.array([[pa[i]], [pb[min(i, lb - 1)]]], jnp.int32)
        pos = jnp.array([[i], [min(i, lb - 1)]], jnp.int32)
        out, cache = fwd(params, tok, pos, cache)
        if i == lb - 1:
            logits[1] = out[1, 0]
        if i == la - 1:
            logits[0] = out[0, 0]
    cur = [int(jnp.argmax(logits[0])), int(jnp.argmax(logits[1]))]
    pos_now = [la, lb]
    got_a, got_b = [], []
    for _ in range(8):
        got_a.append(cur[0])
        got_b.append(cur[1])
        tok = jnp.array([[cur[0]], [cur[1]]], jnp.int32)
        pos = jnp.array([[pos_now[0]], [pos_now[1]]], jnp.int32)
        out, cache = fwd(params, tok, pos, cache)
        cur = [int(jnp.argmax(out[0, 0])), int(jnp.argmax(out[1, 0]))]
        pos_now = [pos_now[0] + 1, pos_now[1] + 1]
    assert got_a == ta
    assert got_b == tb


def test_llama31_rope_scaling_path():
    """Llama-3.1 rope scaling changes low-frequency components
    (src/nn/nn-core.cpp:307-340)."""
    from distributed_llama_multiusers_tpu.ops.rope import build_rope_cache

    cos_plain, _ = build_rope_cache(32, 64, 500000.0)
    cos_scaled, _ = build_rope_cache(
        32, 64, 500000.0, scaling_factor=8.0, low_freq_factor=1.0,
        high_freq_factor=4.0, orig_max_seq_len=8192,
    )
    assert not np.allclose(cos_plain, cos_scaled)
    # high-frequency (first pairs) unaffected
    np.testing.assert_allclose(cos_plain[:, 0], cos_scaled[:, 0])
