"""Ring collectives: TP activation sync overlapped with the dequant matmul.

The reference's TP sync is a quantized TCP all-gather after the wo/w2
row-parallel matmuls (SYNC_NODE_SLICES + merge_add, src/nn/nn-network.cpp:
537-569) — strictly sequential: every node finishes its whole partial
matmul, then the wire moves all the bytes, then decode continues. GSPMD
reproduces that schedule on ICI as one monolithic all-reduce at the matmul
output. This module replaces it with a RING schedule that the XLA scheduler
(and, on real TPU pods, a Pallas ``make_async_remote_copy`` hop — the JAX
distributed-Pallas idiom, SNIPPETS.md [1]) can overlap with compute:

- ``ring_reduce_scatter`` / ``ring_all_gather`` / ``ring_all_reduce``:
  shard-LOCAL ring collectives (call inside ``shard_map`` or a
  ``custom_partitioning`` lower). The payload moves as n-1 chunk-sized hops
  around the tp ring instead of one tensor-sized all-reduce, so each hop's
  ICI transfer is independent of the next chunk's accumulation add — XLA
  issues the collective-permutes async (start/done) and hides them under
  the arithmetic.

- ``ring_sync_matmul``: the fused form — a row-parallel (d_in-sharded)
  matmul whose OUTPUT is computed chunk-by-chunk interleaved with the ring:
  chip k streams its partial for chunk i to its right neighbor while the
  MXU computes chunk i+1's partial (the dequant-in-matmul kernel runs per
  column slice). The reduce half stays f32; the gather half optionally
  ships the Q80 wire format (int8 + f16 block scales — the reference's
  default transport, parallel/collectives.py) for ~4x fewer bytes.

- The per-hop shift is ``lax.ppermute`` (XLA's async collective-permute —
  the same ring schedule, testable on the virtual CPU mesh). On real TPU
  pods, ``DLLAMA_RING_RDMA=on`` opts the pure-TP shard_map paths into a
  Pallas hop built on ``pltpu.make_async_remote_copy`` (the ICI RDMA
  idiom of SNIPPETS.md [1]) that skips the HLO collective boundary;
  opt-in because no backend in this environment can execute it, and a
  Mosaic gap would only surface at compile time.

Escape hatch: ``DLLAMA_RING_SYNC=off`` (or ``set_ring_sync(False)``)
disables every ring path and restores the plain ``lax.psum`` sync
bit-for-bit (the pre-ring behavior).

Numerics: the ring reduce adds partials in ring order instead of XLA's
reduction tree — same f32 class (bitwise-identical at tp=2, where both
orders are a single commutative add). The Q80 wire applies exactly the
block rounding of ``parallel/collectives.q80_all_gather`` (~1e-2 rel).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..jax_compat import shard_map
from ..quants.jax_codec import Q80_BLOCK, q80_decode_blocks, q80_encode_blocks

_ring_sync = os.environ.get("DLLAMA_RING_SYNC", "on").lower() not in (
    "off", "0", "false"
)


def set_ring_sync(enabled: bool | None) -> None:
    """Toggle the ring TP sync (None -> re-read DLLAMA_RING_SYNC). The flag
    is read at TRACE time and is not part of any jit cache key: it affects
    programs traced after the flip only — an already-compiled executable
    keeps its ring/psum lowering (tests build a fresh jit per setting for
    exactly this reason). Flip it before engine construction/warmup."""
    global _ring_sync
    if enabled is None:
        _ring_sync = os.environ.get("DLLAMA_RING_SYNC", "on").lower() not in (
            "off", "0", "false"
        )
    else:
        _ring_sync = bool(enabled)


def ring_sync_enabled() -> bool:
    return _ring_sync


def ring_sync_engages(config, mesh_shape: dict) -> bool:
    """Whether the shard_map ring sync replaces the wo/w2 activation
    all-reduce in ``llama_forward`` — the twin of ``q80_sync_engages``
    (same pure-TP requirement: the sync shard_map replicates activations
    over every non-tp axis) plus ring divisibility: both synced outputs
    are ``dim`` wide and must split into whole per-hop chunks."""
    if not _ring_sync:
        return False
    tp = mesh_shape.get("tp", 1)
    if tp <= 1:
        return False
    if any(mesh_shape.get(ax, 1) > 1 for ax in ("dp", "sp", "ep", "pp")):
        return False
    return config.dim % tp == 0


# ---------------------------------------------------------------------------
# The per-hop shift primitive: ppermute everywhere; Pallas RDMA on real TPU.
# ---------------------------------------------------------------------------


def _ring_perm(n: int) -> list[tuple[int, int]]:
    return [(j, (j + 1) % n) for j in range(n)]


def _use_rdma() -> bool:
    """Pallas remote-DMA hop: OPT-IN (``DLLAMA_RING_RDMA=on``) and real TPU
    backends only. The HLO collective-permute ring is the shipping hop —
    same schedule, testable on the virtual CPU mesh; the RDMA kernel skips
    the HLO collective boundary but no backend in this environment can
    execute it, and a Mosaic gap would surface at COMPILE time (after
    tracing), where the except-and-fall-back below cannot catch it. Flip
    it on only on a pod where one warmup has been seen to pass."""
    if os.environ.get("DLLAMA_RING_RDMA", "off").lower() not in ("on", "1", "true"):
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


# rdma_ok threading: ``device_id=(right,)`` addresses the neighbor by its
# coordinate along the ring axis, which equals the logical device id ONLY
# when every other mesh axis is trivial — the pure-TP meshes the shard_map
# sync engages on. Callers on possibly-multi-axis meshes (the
# custom_partitioning contraction sync) keep rdma_ok=False and hop via
# XLA's async collective-permute, the same ring schedule through HLO.


def _rdma_shift(x: jnp.ndarray, axis: str, n: int, chan: int) -> jnp.ndarray:
    """One ring hop over ICI RDMA: send the local buffer to the right
    neighbor via ``pltpu.make_async_remote_copy`` (SNIPPETS.md [1] / the
    JAX distributed-Pallas guide), return what the left neighbor sent.
    Must run inside shard_map on a real TPU mesh. ``chan`` is the Mosaic
    collective_id: hop chains with NO data dependency between them (the
    Q80 wire's int8-values and f16-scales chains run concurrently) must
    use distinct channels or their collective semaphores alias."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        my = jax.lax.axis_index(axis)
        right = jax.lax.rem(my + 1, n)
        copy = pltpu.make_async_remote_copy(
            src_ref=x_ref,
            dst_ref=o_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(right,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        copy.start()
        copy.wait()

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=pltpu.CompilerParams(collective_id=chan)
        if hasattr(pltpu, "CompilerParams")
        else pltpu.TPUCompilerParams(collective_id=chan),
    )(x)


def _shift(x: jnp.ndarray, axis: str, n: int, rdma_ok: bool = False,
           chan: int = 0) -> jnp.ndarray:
    """Rotate ``x`` one hop rightward around the ring (device r receives
    device (r-1)'s buffer). ``chan``: see ``_rdma_shift`` — concurrent
    (data-independent) hop chains need distinct channels."""
    if rdma_ok and _use_rdma():
        try:
            return _rdma_shift(x, axis, n, chan)
        except Exception:  # Pallas/Mosaic gap on this backend: same ring via HLO
            pass
    return jax.lax.ppermute(x, axis, _ring_perm(n))


# ---------------------------------------------------------------------------
# Shard-local ring collectives (inside shard_map / custom_partitioning).
# ---------------------------------------------------------------------------


def _chunk_idx(chunks: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False)


def ring_reduce_scatter(x: jnp.ndarray, axis: str, n: int,
                        rdma_ok: bool = False) -> jnp.ndarray:
    """Ring reduce-scatter of the last dim: every device holds a full-width
    partial ``x`` [..., D]; device r returns the fully reduced chunk r
    [..., D/n]. n-1 hops, each carrying D/n elements — the accumulation add
    of hop s is independent of hop s+1's transfer, so the transfers hide
    under the arithmetic. Must run inside shard_map (or a
    custom_partitioning lower) with ``axis`` bound; D % n == 0."""
    if n <= 1:
        return x
    c = x.shape[-1] // n
    chunks = jnp.moveaxis(x.reshape(*x.shape[:-1], n, c), -2, 0)  # [n, ..., c]
    r = jax.lax.axis_index(axis)
    # invariant: after hop s, device r holds sum_{k=r-s..r} of every
    # device k's copy of chunk (r-1-s) mod n; s = n-1 lands chunk r reduced
    acc = _chunk_idx(chunks, (r - 1) % n)
    for s in range(1, n):
        acc = _shift(acc, axis, n, rdma_ok)
        acc = acc + _chunk_idx(chunks, (r - 1 - s) % n)
    return acc


def _reorder_arrivals(arrivals: list[jnp.ndarray], axis: str, n: int) -> jnp.ndarray:
    """Ring-arrival order -> chunk order: arrival j on device r originated
    on device (r-j) mod n, so output chunk k is arrival (r-k) mod n."""
    a = jnp.stack(arrivals)  # [n, ..., c]
    r = jax.lax.axis_index(axis)
    idx = (r - jnp.arange(n, dtype=jnp.int32)) % n
    b = jnp.take(a, idx, axis=0)  # b[k] = chunk k
    c = arrivals[0].shape[-1]
    return jnp.moveaxis(b, 0, -2).reshape(*arrivals[0].shape[:-1], n * c)


def ring_all_gather(x: jnp.ndarray, axis: str, n: int,
                    rdma_ok: bool = False) -> jnp.ndarray:
    """Ring all-gather of per-device chunks: device r holds chunk r
    [..., C]; returns [..., n*C] with chunk k = device k's data, identical
    on every device. Must run inside shard_map with ``axis`` bound."""
    if n <= 1:
        return x
    arrivals = [x]
    cur = x
    for _ in range(1, n):
        cur = _shift(cur, axis, n, rdma_ok)
        arrivals.append(cur)
    return _reorder_arrivals(arrivals, axis, n)


def ring_all_gather_q80(x: jnp.ndarray, axis: str, n: int,
                        rdma_ok: bool = False) -> jnp.ndarray:
    """``ring_all_gather`` shipping the Q80 wire format: the local chunk is
    encoded ONCE (int8 values + f16 block scales — the reference's ZQ-pipe
    transport, parallel/collectives.py) and the encoded pair rides all n-1
    hops; every arrival is decoded locally. ~25% of the f32 payload on the
    wire; the local chunk passes through the codec too, so all devices
    apply identical block rounding (the ``q80_all_gather`` contract).
    Needs C % 32 == 0."""
    if n <= 1:
        return x
    q, s = q80_encode_blocks(x.astype(jnp.float32), mode="converter")
    dec = lambda qq, ss: q80_decode_blocks(qq, ss, x.shape).astype(x.dtype)
    arrivals = [dec(q, s)]
    cq, cs = q, s
    for _ in range(1, n):
        # the two wire chains have no data dependency and may be scheduled
        # concurrently -> distinct RDMA channels (collective_ids)
        cq = _shift(cq, axis, n, rdma_ok, chan=0)
        cs = _shift(cs, axis, n, rdma_ok, chan=1)
        arrivals.append(dec(cq, cs))
    return _reorder_arrivals(arrivals, axis, n)


def ring_all_reduce(x: jnp.ndarray, axis: str, n: int) -> jnp.ndarray:
    """Ring all-reduce (reduce-scatter + all-gather): the drop-in for
    ``lax.psum`` over ``axis`` on a full-width partial. Falls back to psum
    when the ring cannot tile the payload (n does not divide the last dim)
    or the ring is degenerate — so callers can substitute unconditionally."""
    if n <= 1 or x.shape[-1] % n != 0:
        return jax.lax.psum(x, axis)
    return ring_all_gather(ring_reduce_scatter(x, axis, n), axis, n)


# ---------------------------------------------------------------------------
# The fused form: row-parallel matmul with the ring interleaved per chunk.
# ---------------------------------------------------------------------------


def ring_sync_supported(d_out: int, tp: int, q80_wire: bool = False) -> bool:
    """Whether a row-parallel output of width ``d_out`` can sync through
    the ring: whole chunks per hop, and whole Q80 blocks per chunk when the
    wire is compressed."""
    if tp <= 1 or d_out % tp != 0:
        return False
    return not q80_wire or (d_out // tp) % Q80_BLOCK == 0


def ring_sync_matmul(
    x: jnp.ndarray,
    w,
    mesh: Mesh,
    axis: str = "tp",
    q80_wire: bool = False,
) -> jnp.ndarray:
    """y = x @ w for a col-sliced (d_in-sharded) weight, with the TP sync
    RING-OVERLAPPED with the partial matmul instead of a sequential
    post-matmul all-reduce:

        for each of the n ring hops: compute the LOCAL partial for ONE
        d_out/n column chunk (dequant-in-matmul per column slice) and add
        the chunk partial that just arrived from the left neighbor; the
        hop transfer for chunk i is in flight WHILE chunk i+1's dot runs.

    After the reduce ring, device r holds reduced chunk r; a ring
    all-gather (Q80 wire when ``q80_wire`` — the reference's compressed
    transport) replicates the full output. Reduction is f32 regardless of
    the dot dtype (the reduce half of ``q80_sync_matmul`` has the same
    contract).

    x: [..., d_in] sharded over ``axis`` on its last dim; w: [d_in, d_out]
    dense or PackedQ40, sharded over ``axis`` on d_in. Returns [..., d_out]
    replicated over ``axis``. Needs ``ring_sync_supported(d_out, n,
    q80_wire)``."""
    from ..ops.linear import q40_matmul_local
    from ..quants.packed import PackedQ40

    n = mesh.shape[axis]
    packed = isinstance(w, PackedQ40)
    d_out = w.d_out if packed else w.shape[-1]
    if not ring_sync_supported(d_out, n, q80_wire):
        raise ValueError(
            f"ring_sync_matmul needs d_out ({d_out}) divisible by "
            f"mesh.shape[{axis!r}] ({n})"
            + (" with whole Q80 blocks per chunk" if q80_wire else "")
        )
    c = d_out // n
    nd = x.ndim

    def inner(xl, *wl):
        r = jax.lax.axis_index(axis)

        def part_chunk(idx):
            # local partial for output columns [idx*c, (idx+1)*c): column
            # chunking is exact (each output column reduces independently)
            if packed:
                pk = jax.lax.dynamic_slice_in_dim(wl[0], idx * c, c, axis=-1)
                sc = jax.lax.dynamic_slice_in_dim(wl[1], idx * c, c, axis=-1)
                part = q40_matmul_local(xl, PackedQ40(pk, sc))
            else:
                part = xl @ jax.lax.dynamic_slice_in_dim(wl[0], idx * c, c, axis=-1)
            return part.astype(jnp.float32)

        # ring reduce-scatter fused with the chunked matmul: the hop of
        # chunk s-1's accumulator and the dot for chunk s are independent,
        # so XLA runs the transfer concurrent with the MXU work
        acc = part_chunk((r - 1) % n)
        for s in range(1, n):
            # rdma_ok: this sync only engages on pure-TP meshes
            # (ring_sync_engages), where the tp coordinate IS the logical
            # device id the RDMA hop addresses
            acc = _shift(acc, axis, n, rdma_ok=True)
            acc = acc + part_chunk((r - 1 - s) % n)
        if q80_wire:
            out = ring_all_gather_q80(acc, axis, n, rdma_ok=True)
        else:
            out = ring_all_gather(acc, axis, n, rdma_ok=True)
        return out.astype(xl.dtype)

    x_spec = P(*([None] * (nd - 1) + [axis]))
    w_specs = (P(axis, None), P(axis, None)) if packed else (P(axis, None),)
    w_args = (w.packed, w.scales) if packed else (w,)
    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(x_spec,) + w_specs,
        out_specs=P(*([None] * nd)),
        check_vma=False,
    )(x, *w_args)
