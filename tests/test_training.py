"""Training mode + orbax checkpoint/resume (beyond-parity: the reference
is inference-only and persists nothing — SURVEY.md §5.4).

The load-bearing invariant is resume EXACTNESS: k steps + save + restore
+ (N-k) steps must equal N straight steps bit-for-bit, because the orbax
round-trip is exact for f32 and the compiled step is deterministic. That
is what makes checkpointing trustworthy on long runs.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_llama_multiusers_tpu.models import params_from_random
from distributed_llama_multiusers_tpu.models.config import LlamaConfig
from distributed_llama_multiusers_tpu.training import Trainer, next_token_loss


def _config():
    return LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=32,
    )


def _batches(config, n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, config.vocab_size, size=(2, 16)).astype(np.int32)
        for _ in range(n)
    ]


def _trainer(config, seed=1):
    params = jax.tree.map(
        jnp.asarray, params_from_random(config, seed=seed, to_device=False)
    )
    return Trainer(config, params, optax.adamw(1e-3))


def test_loss_decreases_over_steps():
    config = _config()
    t = _trainer(config)
    batch = _batches(config, 1)[0]
    losses = [t.step(batch) for _ in range(8)]  # same batch: must overfit
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_save_restore_resume_is_exact(tmp_path):
    config = _config()
    batches = _batches(config, 4)

    straight = _trainer(config)
    for b in batches:
        straight.step(b)

    resumed = _trainer(config)
    for b in batches[:2]:
        resumed.step(b)
    step_dir = resumed.save(str(tmp_path))
    assert step_dir.endswith("step_2")

    fresh = _trainer(config)  # different object, same structure templates
    fresh.restore(str(tmp_path))
    assert fresh.step_count == 2
    for b in batches[2:]:
        fresh.step(b)

    for a, b in zip(jax.tree.leaves(straight.params), jax.tree.leaves(fresh.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scheduled_lr_resume_is_exact(tmp_path):
    """Resume exactness must hold for a SCHEDULED learning rate too: the
    schedule's step count lives in the optax state, so a restored run
    continues the warmup/decay curve exactly where it left off (this is
    what --warmup-steps relies on)."""
    config = _config()
    batches = _batches(config, 4)
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=1e-3, warmup_steps=2, decay_steps=6,
        end_value=1e-4,
    )

    def make():
        params = jax.tree.map(
            jnp.asarray, params_from_random(config, seed=1, to_device=False)
        )
        return Trainer(config, params, optax.adamw(sched))

    straight = make()
    for b in batches:
        straight.step(b)

    resumed = make()
    for b in batches[:2]:
        resumed.step(b)
    resumed.save(str(tmp_path))

    fresh = make()
    fresh.restore(str(tmp_path))
    for b in batches[2:]:
        fresh.step(b)

    for a, b in zip(jax.tree.leaves(straight.params), jax.tree.leaves(fresh.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_step_selection(tmp_path):
    config = _config()
    t = _trainer(config)
    b = _batches(config, 1)[0]
    t.step(b)
    t.save(str(tmp_path))  # step_1
    t.step(b)
    t.save(str(tmp_path))  # step_2
    assert Trainer.latest_step(str(tmp_path)) == 2
    t2 = _trainer(config).restore(str(tmp_path), step=1)
    assert t2.step_count == 1


def test_checkpoint_restores_into_serving_engine(tmp_path):
    """The train->serve loop: checkpointed params ARE LlamaParams, so the
    serving engine consumes a restored checkpoint directly."""
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine

    config = _config()
    t = _trainer(config)
    t.step(_batches(config, 1)[0])
    t.save(str(tmp_path))
    restored = _trainer(config).restore(str(tmp_path))

    engine = InferenceEngine(
        config, restored.params, n_lanes=1, prefill_buckets=(8,)
    )
    logits, greedy, pos = engine.prefill(0, [1, 2, 3])
    assert pos == 3 and 0 <= int(greedy) < config.vocab_size
    assert np.all(np.isfinite(np.asarray(logits)))


def test_train_step_on_mesh_matches_single_device():
    """The same train step under a tp=2/dp=2 mesh (sharded params) produces
    the same loss as the unsharded step — GSPMD lays out the collectives,
    the math is identical."""
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    config = _config()
    host = params_from_random(config, seed=1, to_device=False)
    batch = _batches(config, 1)[0]

    plain = jax.tree.map(jnp.asarray, host)
    loss_plain = float(next_token_loss(config, plain, jnp.asarray(batch)))

    mesh = make_mesh(MeshPlan(dp=2, tp=2))
    sharded = shard_params(jax.tree.map(jnp.asarray, host), mesh)
    t = Trainer(config, sharded, optax.adamw(1e-3), mesh=mesh)
    loss_mesh = t.step(batch)
    np.testing.assert_allclose(loss_mesh, loss_plain, rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_mesh_sharded_save_restore_resume_exact(tmp_path):
    """Checkpoint/resume with GSPMD-sharded params: restore_args carry the
    trainer's shardings, so a mesh trainer resumes straight into its
    layout and the resumed run stays bit-exact with the straight run."""
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    config = _config()
    batches = _batches(config, 4, seed=3)
    host = params_from_random(config, seed=2, to_device=False)
    mesh = make_mesh(MeshPlan(dp=2, tp=2))

    def trainer():
        return Trainer(
            config, shard_params(jax.tree.map(jnp.asarray, host), mesh),
            optax.adamw(1e-3), mesh=mesh,
        )

    straight = trainer()
    for b in batches:
        straight.step(b)

    resumed = trainer()
    for b in batches[:2]:
        resumed.step(b)
    resumed.save(str(tmp_path))

    fresh = trainer()
    # the restore templates are fresh's own (shard_params-placed) pytrees;
    # restored leaves must come back in exactly those shardings (not a
    # device-0 pin or an uncommitted host array). The straight trainer's
    # post-step shardings are NOT the comparand: jit normalizes size-1
    # axes out of its output specs.
    from jax.sharding import NamedSharding

    want_shardings = [l.sharding for l in jax.tree.leaves(fresh.params)]
    fresh.restore(str(tmp_path))
    assert fresh.step_count == 2
    for got, want in zip(jax.tree.leaves(fresh.params), want_shardings):
        if isinstance(want, NamedSharding):
            assert got.sharding == want, (got.sharding, want)
    for b in batches[2:]:
        fresh.step(b)

    for a, b in zip(jax.tree.leaves(straight.params), jax.tree.leaves(fresh.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
