"""Fleet front-end tests (fleet/ — ISSUE 12).

Three layers:

- **balancer units** — least-loaded wins, breaker-open/draining/dead
  replicas excluded, typed-shed Retry-After honored, prefix-key
  determinism, and the consistent-hash property: when a replica leaves,
  ONLY the keys it owned move (~1/N), everything else stays put.
- **replica surfaces** — the /load JSON (one scrape per routing
  decision), the X-DLlama-Replica attribution header + terminal-chunk
  field, and the /admin/session export + /admin/migrate inject pair.
- **THE pin** — a live SSE stream moved off a dying replica mid-flight
  resumes on another replica BYTE-IDENTICAL to the uninterrupted run,
  with zero lost and zero duplicated output. MockAsyncEngine in
  content_keyed mode is the determinism class the real engine pins
  (tokens are f(prompt content, pos), never f(lane, pos)), so two
  replicas regenerate the same stream from the same (prompt, seed) —
  exactly the property PR 10's replay recovery established and the
  migration primitive reuses.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_llama_multiusers_tpu.fleet import (
    FleetBalancer,
    FleetRouter,
    prefix_key,
)
from distributed_llama_multiusers_tpu.runtime.scheduler import (
    ContinuousBatchingScheduler,
    Request,
)
from distributed_llama_multiusers_tpu.serving import StreamRegistry
from distributed_llama_multiusers_tpu.server import ApiServer
from distributed_llama_multiusers_tpu.tokenizer import TemplateType
from distributed_llama_multiusers_tpu.utils import faults
from distributed_llama_multiusers_tpu.utils.testing import (
    CharStreamTokenizer,
    MockAsyncEngine,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# balancer: load routing, eligibility, consistent hashing
# ---------------------------------------------------------------------------


def _loaded(b, rid, queue_depth=0, lanes_free=4, breaker="closed",
            draining=False):
    b.update_load(rid, {
        "queue_depth": queue_depth, "lanes_free": lanes_free,
        "lanes_total": 4, "breaker": breaker, "draining": draining,
    })


def test_least_loaded_wins():
    b = FleetBalancer(["h:1", "h:2", "h:3"])
    _loaded(b, "h:1", queue_depth=5, lanes_free=0)
    _loaded(b, "h:2", queue_depth=0, lanes_free=4)
    _loaded(b, "h:3", queue_depth=2, lanes_free=1)
    assert b.pick().rid == "h:2"
    # deeper queue loses even with free lanes equal
    _loaded(b, "h:2", queue_depth=9, lanes_free=4)
    assert b.pick().rid == "h:3"


def test_breaker_open_and_draining_replicas_excluded():
    b = FleetBalancer(["h:1", "h:2"])
    _loaded(b, "h:1", breaker="open")
    _loaded(b, "h:2")
    assert b.pick().rid == "h:2"
    # keyed picks walk the ring past the unhealthy replica too
    for key in range(0, 20000, 997):
        assert b.pick(key).rid == "h:2"
    _loaded(b, "h:2", draining=True)
    _loaded(b, "h:1", breaker="open")
    assert b.pick() is None  # nobody eligible: the router 503s
    assert not b.any_eligible()
    # recovery: a clean scrape restores eligibility
    _loaded(b, "h:1")
    assert b.any_eligible() and b.pick().rid == "h:1"


def test_shed_retry_after_honored_then_expires():
    b = FleetBalancer(["h:1", "h:2"])
    b.note_shed("h:1", retry_after_s=0.15)
    for _ in range(5):
        assert b.pick().rid == "h:2"
    assert b.min_retry_after_s() >= 1.0  # hint floor
    time.sleep(0.2)
    # horizon passed: h:1 is routable again (and, least-routed, wins)
    assert b.pick().rid == "h:1"


def test_dead_replica_backs_off_then_reprobes():
    b = FleetBalancer(["h:1", "h:2"], dead_backoff_s=0.1)
    b.note_dead("h:1")
    assert b.pick().rid == "h:2"
    time.sleep(0.15)
    # past the backoff the dead replica earns one inline probe
    assert {b.pick().rid for _ in range(4)} == {"h:1", "h:2"}


def test_prefix_key_same_leading_blocks_same_key():
    base = "system prompt block " * 100  # far beyond 4x256 chars
    k1 = prefix_key(base + "user question A")
    k2 = prefix_key(base + "a completely different user question B")
    assert k1 == k2  # leading blocks identical -> same key
    assert prefix_key("x" * 1024) != prefix_key("y" * 1024)
    assert prefix_key("short") is None  # no full block: no affinity
    # the chain folds earlier blocks: same block 1, different block 0
    a = ("A" * 256) + ("Z" * 256)
    bb = ("B" * 256) + ("Z" * 256)
    assert prefix_key(a) != prefix_key(bb)


def test_affinity_deterministic_and_ring_moves_one_over_n():
    """The consistent-hash property the warm-KV map depends on: removing
    one replica moves ONLY the keys it owned (~1/N), every other key
    keeps its replica — membership churn never reshuffles the fleet's
    prefix placement wholesale."""
    replicas = ["h:1", "h:2", "h:3", "h:4"]
    b1 = FleetBalancer(replicas)
    keys = [prefix_key(f"shared system prompt {i} " * 40)
            for i in range(400)]
    owners1 = {k: b1.ring_owner(k) for k in keys}
    # deterministic: a second balancer (fresh process stand-in) agrees
    assert {k: FleetBalancer(replicas).ring_owner(k) for k in keys} \
        == owners1
    # membership change: drop h:3 entirely
    b2 = FleetBalancer(["h:1", "h:2", "h:4"])
    owners2 = {k: b2.ring_owner(k) for k in keys}
    moved = [k for k in keys if owners1[k] != owners2[k]]
    was_on_removed = [k for k in keys if owners1[k] == "h:3"]
    # ONLY the removed replica's keys moved...
    assert set(moved) == set(was_on_removed)
    # ...and it owned roughly 1/N of the space (loose band: vnode noise)
    frac = len(was_on_removed) / len(keys)
    assert 0.10 < frac < 0.45, frac
    # failover (dead, not removed) keeps everyone else's keys too, and
    # the key comes back when the replica does
    b1.note_dead("h:3", backoff_s=60.0)
    for k in keys:
        got = b1.pick(k).rid
        if owners1[k] != "h:3":
            assert got == owners1[k]
        else:
            assert got != "h:3"


# ---------------------------------------------------------------------------
# replica surfaces: /load, attribution, session export + migrate inject
# ---------------------------------------------------------------------------


class TokenTextTokenizer(CharStreamTokenizer):
    """Prompt-dependent encoding + per-token distinct text: stream
    equality is a real assertion (CharStreamTokenizer home: the same
    prompt maps to the same tokens on every replica)."""

    def decode(self, token):
        return f"[{token}]"


def _replica(rid=None, n_lanes=2, grace_s=30.0, step_s=0.005,
             max_queue=0):
    """One in-process dllama-api stand-in: MockAsyncEngine in
    content_keyed mode (the replay-determinism class), resume registry
    (migration targets need one), ephemeral port."""
    from distributed_llama_multiusers_tpu.serving import QosQueue

    engine = MockAsyncEngine(n_lanes=n_lanes, max_chunk=8,
                             content_keyed=True, step_s=step_s)
    sched = ContinuousBatchingScheduler(
        engine, TokenTextTokenizer(64, max_chars=24),
        queue_=QosQueue(capacity=max_queue),
        speculative=False, prefix_min_tokens=0, multi_step=0,
    )
    sched.start()
    registry = StreamRegistry(grace_s=grace_s) if grace_s else None
    api = ApiServer(sched, TokenTextTokenizer(64, max_chars=24),
                    model_name="fleet", template_type=TemplateType.LLAMA2,
                    resume=registry, replica_id=rid)
    httpd = api.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"127.0.0.1:{httpd.server_address[1]}"
    return {"api": api, "sched": sched, "registry": registry,
            "httpd": httpd, "base": base, "rid": api.replica_id}


def _stop_replica(r):
    try:
        r["httpd"].shutdown()
    finally:
        if r["registry"] is not None:
            r["registry"].close()
        try:
            r["sched"].stop()
        except RuntimeError:
            pass


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def test_load_surface_one_scrape_json():
    r = _replica(rid="alpha")
    try:
        load, headers = _get_json(f"http://{r['base']}/load")
        assert load["status"] == "ok" and load["replica"] == "alpha"
        assert load["queue_depth"] == 0
        assert load["lanes_free"] == 2 and load["lanes_total"] == 2
        assert load["breaker"] == "closed" and load["draining"] is False
        assert headers["X-DLlama-Replica"] == "alpha"
        # /health carries the same machine fields (plus its status code)
        health, _ = _get_json(f"http://{r['base']}/health")
        assert health["queue_depth"] == 0 and health["breaker"] == "closed"
        # draining flips both: /load stays 200 (machine surface),
        # /health goes 503 (readiness surface)
        r["sched"]._draining.set()
        load, _ = _get_json(f"http://{r['base']}/load")
        assert load["status"] == "draining" and load["draining"] is True
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"http://{r['base']}/health", timeout=10)
        assert e.value.code == 503
        assert json.loads(e.value.read())["draining"] is True
        r["sched"]._draining.clear()
    finally:
        _stop_replica(r)


def test_replica_attribution_header_and_terminal_chunk():
    r = _replica(rid="attrib-1")
    try:
        req = urllib.request.Request(
            f"http://{r['base']}/v1/completions",
            data=json.dumps({"prompt": "attribution test prompt",
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["X-DLlama-Replica"] == "attrib-1"
            json.loads(resp.read())
        # streaming: the header AND the terminal chunk name the replica
        req = urllib.request.Request(
            f"http://{r['base']}/v1/completions",
            data=json.dumps({"prompt": "attribution test prompt",
                             "max_tokens": 4, "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        term = None
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["X-DLlama-Replica"] == "attrib-1"
            assert int(resp.headers["X-DLlama-Request"]) > 0
            for line in resp:
                line = line.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                p = json.loads(line[6:])
                if p.get("choices", [{}])[0].get("finish_reason"):
                    term = p
        assert term is not None and term["replica"] == "attrib-1"
    finally:
        _stop_replica(r)


def _stream_collect(url, body, timeout=60):
    """(delta texts, terminal payload, headers) for one SSE POST."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    texts, term = [], None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        headers = dict(resp.headers)
        for line in resp:
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            p = json.loads(line[6:])
            ch = p.get("choices", [{}])[0]
            if ch.get("finish_reason") is None:
                texts.append(ch.get("text", ""))
            else:
                term = p
    return texts, term, headers


def test_session_export_and_migrate_inject_round_trip():
    """The migration primitive end-to-end WITHOUT a router: export a
    live session's ticket from replica A, inject it into replica B,
    reattach from 0 — the regenerated stream is the same bytes."""
    a, b = _replica(rid="src"), _replica(rid="dst")
    try:
        # a slow-ish stream so the session is live while we export
        url = f"http://{a['base']}/v1/completions"
        body = {"prompt": "migration ticket round trip", "max_tokens": 24,
                "stream": True}
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = urllib.request.urlopen(req, timeout=30)
        rid = int(resp.headers["X-DLlama-Request"])
        # first delta = admitted; the export has the resolved seed
        first = None
        for line in resp:
            line = line.decode().strip()
            if line.startswith("data: ") and line != "data: [DONE]":
                first = json.loads(line[6:])
                break
        assert first is not None
        ticket, _ = _get_json(f"http://{a['base']}/admin/session/{rid}")
        assert ticket["id"] == rid and ticket["k"] == "admit"
        assert isinstance(ticket["seed"], int) and ticket["tokens"]
        assert ticket["stream"] is True
        # finish the source stream; keep its bytes as the reference
        texts = [first["choices"][0].get("text", "")]
        for line in resp:
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            p = json.loads(line[6:])
            if p.get("choices", [{}])[0].get("finish_reason") is None:
                texts.append(p["choices"][0].get("text", ""))
        resp.close()
        reference = "".join(texts)

        # inject into B: original id kept, stream path returned
        inj = urllib.request.Request(
            f"http://{b['base']}/admin/migrate",
            data=json.dumps(ticket).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(inj, timeout=30) as r2:
            out = json.loads(r2.read())
        assert out["request_id"] == rid
        # reattach from 0: the full regenerated stream replays
        req3 = urllib.request.Request(
            f"http://{b['base']}{out['stream_path']}",
            headers={"Last-Event-ID": "0"},
        )
        texts3 = []
        with urllib.request.urlopen(req3, timeout=60) as r3:
            for line in r3:
                line = line.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                p = json.loads(line[6:])
                if p.get("choices", [{}])[0].get("finish_reason") is None:
                    texts3.append(p["choices"][0].get("text", ""))
        assert "".join(texts3) == reference
    finally:
        _stop_replica(a)
        _stop_replica(b)


def test_migrate_inject_remaps_colliding_id():
    """Every replica numbers requests from 1, so an injected session's
    ORIGINAL id routinely names a LIVE request on the target — the
    endpoint must re-admit under a fresh id (the response's request_id
    is authoritative) instead of clobbering the live request's relay
    and session record."""
    a, b = _replica(rid="ca"), _replica(rid="cb")
    try:
        # a live stream on B whose id we will collide with
        url_b = f"http://{b['base']}/v1/completions"
        req_b = urllib.request.Request(
            url_b, data=json.dumps({"prompt": "the innocent bystander",
                                    "max_tokens": 40,
                                    "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp_b = urllib.request.urlopen(req_b, timeout=30)
        live_rid = int(resp_b.headers["X-DLlama-Request"])

        # a finished session on A exported as a ticket, re-labelled
        # with B's live id (the cross-replica collision shape)
        texts, _, _ = _stream_collect(
            f"http://{a['base']}/v1/completions",
            {"prompt": "the migrating session", "max_tokens": 12,
             "stream": True},
        )
        # rebuild the ticket by hand (the session finished; a live
        # export is covered by the round-trip test above)
        ticket = {
            "k": "admit", "id": live_rid,
            "prompt": "the migrating session",
            "tokens": TokenTextTokenizer(64, max_chars=24).encode(
                "the migrating session"),
            "max_tokens": 12, "temp": 0.0, "topp": 0.9, "seed": 5,
            "stream": True, "kind": "completion",
        }
        inj = urllib.request.Request(
            f"http://{b['base']}/admin/migrate",
            data=json.dumps(ticket).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(inj, timeout=30) as r:
            out = json.loads(r.read())
        assert out["request_id"] != live_rid  # remapped, not clobbered
        # the bystander's relay survived: its stream drains to a
        # natural terminal under its ORIGINAL id
        got_terminal = False
        for line in resp_b:
            line = line.decode().strip()
            if line.startswith("data: ") and line != "data: [DONE]":
                p = json.loads(line[6:])
                ch = p.get("choices", [{}])[0]
                if ch.get("finish_reason"):
                    assert ch["finish_reason"] == "length"
                    got_terminal = True
            elif line == "data: [DONE]":
                break
        assert got_terminal
        # and the migrated session streams fully under its NEW id
        req3 = urllib.request.Request(
            f"http://{b['base']}{out['stream_path']}",
            headers={"Last-Event-ID": "0"},
        )
        texts3 = []
        with urllib.request.urlopen(req3, timeout=60) as r3:
            for line in r3:
                line = line.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                p = json.loads(line[6:])
                if p.get("choices", [{}])[0].get("finish_reason") is None:
                    texts3.append(p["choices"][0].get("text", ""))
        assert "".join(texts3)  # regenerated under the remapped id
    finally:
        _stop_replica(a)
        _stop_replica(b)


def test_migrate_endpoint_refusals():
    # no resume registry on the target: a clear 409, not a shed
    r = _replica(rid="nogrz", grace_s=0)
    try:
        ticket = {"k": "admit", "id": 12345, "prompt": "p",
                  "tokens": [1, 2, 3], "max_tokens": 4, "temp": 0.0,
                  "topp": 0.9, "seed": 7, "stream": True}
        req = urllib.request.Request(
            f"http://{r['base']}/admin/migrate",
            data=json.dumps(ticket).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 409
        # malformed record: 400
        req = urllib.request.Request(
            f"http://{r['base']}/admin/migrate",
            data=json.dumps({"k": "finish", "id": 1}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 400
        # unknown session export: 404
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://{r['base']}/admin/session/424242", timeout=10
            )
        assert e.value.code == 404
    finally:
        _stop_replica(r)


# ---------------------------------------------------------------------------
# router: routing + typed sheds + THE migration pin
# ---------------------------------------------------------------------------


def _router(replicas, **kw):
    router = FleetRouter(
        {r["rid"]: r["base"] for r in replicas},
        scrape_interval_s=kw.pop("scrape_interval_s", 0.1),
        **kw,
    ).start()
    httpd = router.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    router.scrape_once()
    return router, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_router_routes_around_draining_replica_and_gives_up_typed():
    a, b = _replica(rid="ra"), _replica(rid="rb")
    router, rhttpd, rbase = _router([a, b])
    try:
        a["sched"]._draining.set()
        router.scrape_once()  # the scrape sees the drain flag
        body = {"prompt": "routing probe " * 30, "max_tokens": 4}
        for _ in range(3):
            req = urllib.request.Request(
                rbase + "/v1/completions",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.headers["X-DLlama-Replica"] == "rb"
                json.loads(resp.read())
        # both gone: ONE aggregate typed 503 with a Retry-After hint
        b["sched"]._draining.set()
        router.scrape_once()
        req = urllib.request.Request(
            rbase + "/v1/completions", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 503
        payload = json.loads(e.value.read())
        assert payload["reason"] == "fleet_exhausted"
        assert int(e.value.headers["Retry-After"]) >= 1
        assert router.giveups >= 1
        a["sched"]._draining.clear()
        b["sched"]._draining.clear()
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(a)
        _stop_replica(b)


def test_router_retries_replica_shed_elsewhere():
    """A typed 429 (queue full) from one replica is retried on another;
    the shed replica's Retry-After becomes a routing backoff."""
    a = _replica(rid="full", n_lanes=1, max_queue=1)
    b = _replica(rid="roomy")
    router, rhttpd, rbase = _router([a, b])
    try:
        # saturate A directly: 1 lane busy + 1 queued (paced so the
        # first hold reaches its lane before the second one fills the
        # capacity-1 queue — pushing both at once would shed here)
        hold = [Request(prompt="hold the lane", max_tokens=400)
                for _ in range(2)]
        a["sched"].submit(hold[0])
        deadline = time.monotonic() + 10
        while not a["sched"].queue.empty() and time.monotonic() < deadline:
            time.sleep(0.005)
        a["sched"].submit(hold[1])
        # keyless short prompt -> least-loaded may pick A (scraped before
        # saturation); the 429 must bounce to B transparently
        router.scrape_once()
        deadline = time.monotonic() + 30
        saw_roomy = False
        while time.monotonic() < deadline and not saw_roomy:
            req = urllib.request.Request(
                rbase + "/v1/completions",
                data=json.dumps({"prompt": "x", "max_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                saw_roomy = resp.headers["X-DLlama-Replica"] == "roomy"
                json.loads(resp.read())
        assert saw_roomy
        for h in hold:
            h.cancel()
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(a)
        _stop_replica(b)


def test_router_affinity_same_prefix_same_replica():
    a, b, c = _replica(rid="f1"), _replica(rid="f2"), _replica(rid="f3")
    router, rhttpd, rbase = _router([a, b, c])
    try:
        system = "you are a helpful assistant " * 40  # > 4 blocks
        served = set()
        for i in range(6):
            req = urllib.request.Request(
                rbase + "/v1/completions",
                data=json.dumps({
                    "prompt": system + f"user question {i}",
                    "max_tokens": 2,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                served.add(resp.headers["X-DLlama-Replica"])
        assert len(served) == 1  # same leading blocks -> same replica
        stats = router.handle_stats()
        assert stats["fleet_affinity_routes"] >= 6
        assert stats["fleet_affinity_hits"] >= 6
    finally:
        router.close()
        rhttpd.shutdown()
        for r in (a, b, c):
            _stop_replica(r)


def _stream_via_router(rbase, body, on_delta=None, timeout=120):
    """Stream through the router; returns (concatenated text, terminal
    payload, served-by header, router SSE ids)."""
    req = urllib.request.Request(
        rbase + "/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    texts, ids, term = [], [], None
    cur_id = None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        served = resp.headers.get("X-DLlama-Replica")
        for line in resp:
            line = line.decode().strip()
            if line.startswith("id: "):
                cur_id = int(line[4:])
                continue
            if not line.startswith("data: "):
                continue
            if line == "data: [DONE]":
                break
            p = json.loads(line[6:])
            if "error" in p:
                term = p
                continue
            ch = p.get("choices", [{}])[0]
            if ch.get("finish_reason") is None:
                texts.append(ch.get("text", ""))
                if cur_id is not None:
                    ids.append(cur_id)
                cur_id = None
                if on_delta is not None:
                    on_delta(len(texts))
            else:
                term = p
    return "".join(texts), term, served, ids


def test_live_migration_mid_stream_byte_identical():
    """THE pin (acceptance criterion): a streaming session moved off a
    dying replica resumes on another replica byte-identical to the
    uninterrupted run — zero lost, zero duplicated output — and the
    router's SSE ids stay gapless across the splice. The kill is the
    orderly-death shape (accept loop down + scheduler stopped with the
    stream mid-flight -> the force-cancel path a drain timeout or
    SIGTERM-then-die takes); transport-level breaks land in the same
    migrate branch via the socket-error path."""
    a, b = _replica(rid="m1"), _replica(rid="m2")
    router, rhttpd, rbase = _router([a, b])
    killed = []
    try:
        body = {"prompt": "migration pin prompt " * 20, "max_tokens": 40,
                "stream": True}
        # reference: the uninterrupted run through the router (content
        # keyed: the same prompt regenerates the same stream anywhere)
        ref_text, ref_term, ref_served, _ = _stream_via_router(rbase, body)
        assert ref_term["choices"][0]["finish_reason"] == "length"
        assert len(ref_text) > 0
        source = ref_served  # affinity: the next run lands there too

        def kill_source(n_deltas):
            if n_deltas == 5 and not killed:
                victim = a if source == "m1" else b
                killed.append(victim)
                victim["httpd"].shutdown()
                victim["sched"].stop()

        text, term, served, ids = _stream_via_router(
            rbase, body, on_delta=kill_source
        )
        assert killed, "the kill never fired"
        assert served == source
        # byte-identical client view: nothing lost, nothing duplicated
        assert text == ref_text
        assert term is not None and "error" not in term
        assert term["choices"][0]["finish_reason"] == "length"
        # the router's re-stamped ids are gapless across the migration
        assert ids == list(range(1, len(ids) + 1))
        assert router.migrations_ok == 1 and router.migrations_failed == 0
        # the metric saw it too
        assert "dllama_router_migrations_total" in router.handle_metrics()
    finally:
        router.close()
        rhttpd.shutdown()
        for r in (a, b):
            if r not in killed:
                _stop_replica(r)


def test_migration_rescues_engine_failure_terminal():
    """An engine-scoped failure on the source replica (contained by the
    supervised loop, PR 8 — the stream ends with a typed error) is
    migratable: the router moves the innocent session to a healthy
    replica instead of passing the failure through."""
    a, b = _replica(rid="e1"), _replica(rid="e2")
    router, rhttpd, rbase = _router([a, b])
    try:
        body = {"prompt": "engine failure rescue " * 20, "max_tokens": 30,
                "stream": True}
        ref_text, _, source, _ = _stream_via_router(rbase, body)

        fired = []

        def break_engine(n_deltas):
            if n_deltas == 4 and not fired:
                victim = a if source == "e1" else b
                fired.append(victim)
                # engine-scoped raise on the next dispatch: the
                # supervised loop contains it and fails the lane with
                # finish_reason="error"
                orig = victim["sched"].engine.decode_pipelined

                def boom(*args, **kw):
                    victim["sched"].engine.decode_pipelined = orig
                    raise RuntimeError("injected engine failure")

                victim["sched"].engine.decode_pipelined = boom

        text, term, served, _ = _stream_via_router(
            rbase, body, on_delta=break_engine
        )
        assert fired
        assert text == ref_text
        assert term["choices"][0]["finish_reason"] == "length"
        assert router.migrations_ok >= 1
    finally:
        router.close()
        rhttpd.shutdown()
        _stop_replica(a)
        _stop_replica(b)
