"""BPE tokenizer over the `.t` format, with streaming UTF-8 decode.

Re-design of src/tokenizer.cpp:42-380. Same observable behavior:

- vocab is split into regular / special at ``bos_id`` (the reference's
  "unstable assumption", src/tokenizer.cpp:137-139)
- encode: greedy longest-special-token scan, byte-accumulation seeding, then
  iterative best-score pair merging (src/tokenizer.cpp:301-368)
- decode: per-token streaming with UTF-8 validation + recovery emitting
  U+FFFD, holding back incomplete trailing sequences (src/tokenizer.cpp:214-299)
"""

from __future__ import annotations

import heapq

from ..formats.tokenizer_file import TokenizerData, load_tokenizer_file

# prompts at least this long merge in C++ (native.NativeBpe) when the
# library is available; below it the ctypes boundary costs more than the
# Python heap saves
NATIVE_MERGE_MIN_TOKENS = 256

_FFFD = b"\xef\xbf\xbd"


class Tokenizer:
    def __init__(self, data: TokenizerData | str):
        if isinstance(data, str):
            data = load_tokenizer_file(data)
        self.data = data
        self.vocab: list[bytes] = data.vocab
        self.scores: list[float] = data.scores
        self.bos_id: int = data.bos_id
        self.eos_token_ids: list[int] = list(data.eos_token_ids)
        self.chat_template: str | None = data.chat_template
        self.vocab_size: int = data.vocab_size

        self.regular_vocab_size = self.bos_id
        self.special_vocab_size = self.vocab_size - self.regular_vocab_size
        # token string -> id for the regular vocab (replaces the reference's
        # qsort+bsearch TokenIndex table, src/tokenizer.cpp:141-146)
        self._regular: dict[bytes, int] = {}
        for i in range(self.regular_vocab_size):
            self._regular.setdefault(self.vocab[i], i)
        # special tokens in id order (the reference scans them in id order and
        # takes the first prefix match, src/tokenizer.cpp:186-194)
        self._specials: list[tuple[int, bytes]] = [
            (i, self.vocab[i]) for i in range(self.regular_vocab_size, self.vocab_size)
        ]
        # first-byte index over specials: the id-order scan only has to touch
        # candidates that can possibly match at this position (long prompts
        # otherwise pay n_specials startswith calls per byte)
        self._specials_by_first: dict[int, list[tuple[int, bytes]]] = {}
        for tid, piece in self._specials:
            if piece:
                self._specials_by_first.setdefault(piece[0], []).append((tid, piece))
        self._decode_pending = b""  # held-back bytes of an incomplete UTF-8 seq
        self._native_bpe = None  # lazy C++ merge context (False = unavailable)

    # ---- encode -----------------------------------------------------------

    def encode(
        self,
        text: str | bytes,
        add_bos: bool = True,
        add_special_tokens: bool = True,
    ) -> list[int]:
        if isinstance(text, str):
            text = text.encode("utf-8")
        if len(text) >= NATIVE_MERGE_MIN_TOKENS:
            # long prompts (long-context admission) run the whole
            # scan+merge in C++ — one ctypes call, token-identical
            # (A/B'd in test_native.py). None = untokenizable somewhere:
            # fall through so the Python path raises the exact error.
            native = self._get_native_bpe()
            if native is not None:
                out = native.encode(
                    text, self.bos_id if add_bos else -1, add_special_tokens
                )
                if out is not None:
                    return out
        tokens: list[int] = []
        if add_bos:
            tokens.append(self.bos_id)

        buf = b""
        i = 0
        n = len(text)
        while i < n:
            if add_special_tokens:
                special = self._find_special_at(text, i)
                if special is not None:
                    if buf:
                        raise ValueError(f"untokenizable bytes before special token: {buf!r}")
                    tokens.append(special)
                    i += len(self.vocab[special])
                    continue
            buf += text[i : i + 1]
            i += 1
            tid = self._regular.get(buf)
            if tid is not None:
                tokens.append(tid)
                buf = b""
        if buf:
            # the reference asserts here (src/tokenizer.cpp:337)
            raise ValueError(f"untokenizable trailing bytes: {buf!r}")

        return self._merge(tokens)

    def _merge(self, tokens: list[int]) -> list[int]:
        """Iterative best-score pair merging (src/tokenizer.cpp:340-368), as
        a heap over candidate pairs instead of the reference's full rescan
        per merge: O(n log n), not O(n^2), so 100k-char prompts admit without
        stalling the scheduler thread. Order is identical to the reference —
        it takes the strictly-best score scanning left to right, i.e. the
        EARLIEST pair on ties, and merges only remove elements, so original
        position order equals current order and (-score, left_pos) keys pop
        in exactly the reference's merge sequence."""
        n = len(tokens)
        if n < 2:
            return tokens
        if n >= NATIVE_MERGE_MIN_TOKENS:
            # long prompts (the long-context admission path) take the C++
            # merge — token-identical by contract, A/B'd in test_native.py
            native = self._get_native_bpe()
            if native is not None:
                return native.merge(tokens)
        ids = list(tokens)
        nxt = list(range(1, n + 1))  # n = end sentinel
        prv = list(range(-1, n - 1))
        alive = [True] * n
        heap: list[tuple[float, int, int, int, int]] = []

        def push(j: int) -> None:
            k = nxt[j]
            if k >= n:
                return
            a, b = ids[j], ids[k]
            if a >= self.vocab_size or b >= self.vocab_size:
                return
            merged = self._regular.get(self.vocab[a] + self.vocab[b])
            # > -1e10: the reference's best-score sentinel never merges
            # pairs at or below it (src/tokenizer.cpp:342)
            if merged is not None and self.scores[merged] > -1e10:
                heapq.heappush(heap, (-self.scores[merged], j, merged, a, b))

        for j in range(n - 1):
            push(j)
        while heap:
            _, j, merged, a, b = heapq.heappop(heap)
            k = nxt[j]
            # stale entry: one side merged away or re-merged since the push
            if not alive[j] or k >= n or ids[j] != a or ids[k] != b:
                continue
            ids[j] = merged
            alive[k] = False
            nxt[j] = nxt[k]
            if nxt[k] < n:
                prv[nxt[k]] = j
            if prv[j] >= 0:
                push(prv[j])
            push(j)
        return [ids[j] for j in range(n) if alive[j]]

    def _get_native_bpe(self):
        """Lazy C++ merge context; False caches unavailability so the
        fallback costs one attribute check per encode."""
        if self._native_bpe is None:
            try:
                from ..native import NativeBpe

                self._native_bpe = NativeBpe(
                    self.vocab, self.regular_vocab_size, self.scores
                )
            except OSError:
                self._native_bpe = False
        return self._native_bpe or None

    def _find_special_at(self, text: bytes, pos: int) -> int | None:
        # candidates share the first byte; kept in id order so the first
        # prefix match is the same one the reference's scan picks
        # (src/tokenizer.cpp:186-194)
        for tid, piece in self._specials_by_first.get(text[pos], ()):
            if text.startswith(piece, pos):
                return tid
        return None

    # ---- decode -----------------------------------------------------------

    def is_eos(self, token: int) -> bool:
        return token in self.eos_token_ids

    def make_stream_decoder(self) -> "StreamDecoder":
        """Independent streaming decoder — one per concurrent request lane
        (the reference has a single shared strBuffer, src/tokenizer.cpp:154,
        which the multi-user loop bypassed entirely — defect (e))."""
        return StreamDecoder(self)

    def reset_decoder(self) -> None:
        self._decode_pending = b""

    def decode(self, token: int) -> str | None:
        """Streaming decode of one token; returns the printable delta or None.

        Mirrors Tokenizer::decode (src/tokenizer.cpp:281-299): BOS yields
        nothing; EOS flushes any held-back bytes; other tokens append their
        piece and emit the longest valid UTF-8 prefix.
        """
        if token == self.bos_id:
            return None
        if self.is_eos(token):
            if self._decode_pending:
                out = self._decode_pending.decode("utf-8", errors="replace")
                self._decode_pending = b""
                return out
            return None
        piece = self.vocab[token]
        return self._detok_utf8(self._decode_pending + piece)

    def decode_full(self, tokens: list[int]) -> str:
        """Non-streaming convenience: decode a whole sequence."""
        self.reset_decoder()
        parts = [self.decode(t) for t in tokens]
        pending = self._decode_pending.decode("utf-8", errors="replace")
        self._decode_pending = b""
        return "".join(p for p in parts if p) + pending

    def _detok_utf8(self, data: bytes) -> str | None:
        out, self._decode_pending = _detok_utf8(data)
        return out


def _detok_utf8(data: bytes) -> tuple[str | None, bytes]:
    """Pure port of detokUtf8 (src/tokenizer.cpp:214-279): emit the valid
    prefix, collapse runs of invalid bytes into a single U+FFFD, return
    (text, held-back bytes of an incomplete trailing sequence)."""
    out = bytearray()
    i = 0
    n = len(data)
    checkpoint_out = 0  # bytes of `out` confirmed (ends on char boundary)
    checkpoint_src = 0
    expect = 0
    while i < n:
        c = data[i]
        need_recovery = False
        if expect:
            if (c & 0xC0) == 0x80:
                out.append(c)
                i += 1
                expect -= 1
            else:
                need_recovery = True
        elif c <= 0x7F:
            out.append(c)
            i += 1
        elif 0xC0 <= c <= 0xDF:
            out.append(c)
            i += 1
            expect = 1
        elif 0xE0 <= c <= 0xEF:
            out.append(c)
            i += 1
            expect = 2
        elif 0xF0 <= c <= 0xF7:
            out.append(c)
            i += 1
            expect = 3
        else:
            need_recovery = True

        if not need_recovery:
            if expect == 0:
                checkpoint_out = len(out)
                checkpoint_src = i
        else:
            if expect:
                expect = 0
            else:
                i += 1
            del out[checkpoint_out:]
            out += _FFFD
    pending = data[checkpoint_src:] if i > checkpoint_src else b""
    if checkpoint_out > 0:
        return bytes(out[:checkpoint_out]).decode("utf-8", errors="replace"), pending
    return None, pending


class StreamDecoder:
    """Per-request streaming decoder sharing a Tokenizer's vocab but owning
    its own held-back-bytes state, so concurrent lanes never interleave."""

    def __init__(self, tokenizer: Tokenizer):
        self._t = tokenizer
        self._pending = b""

    def decode(self, token: int) -> str | None:
        t = self._t
        if token == t.bos_id:
            return None
        if t.is_eos(token):
            if self._pending:
                out = self._pending.decode("utf-8", errors="replace")
                self._pending = b""
                return out
            return None
        out, self._pending = _detok_utf8(self._pending + t.vocab[token])
        return out

    def reset(self) -> None:
        self._pending = b""
