"""Shared model/engine bootstrapping for the CLI entry points — the analogue
of runInferenceApp's setup sequence (src/app.cpp:233-312): load header ->
validate -> tokenizer -> build model -> place on devices -> engine."""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from ..formats import load_model_header
from ..models import load_params_from_m
from ..models.loader import load_params_from_m_quantized
from ..parallel import make_mesh, validate_mesh_for_config
from ..parallel.sharding import shard_params
from ..runtime import ContinuousBatchingScheduler, InferenceEngine
from ..runtime.kvpool import DEFAULT_MAX_PARKED, DEFAULT_PAGE_SIZE
from ..tokenizer import Tokenizer
from .args import parse_mesh_spec


def log(emoji: str, msg: str) -> None:
    print(f"{emoji} {msg}", flush=True)


def honor_cpu_platform_env() -> None:
    """Make `JAX_PLATFORMS=cpu dllama ...` actually run on CPU. Some hosts
    (this one included) register a TPU PJRT plugin at interpreter start whose
    discovery blocks on a network tunnel even when the platform filter says
    cpu, so the env var alone hangs the CLI; route through force_cpu_mesh,
    which also drops the non-cpu plugin factories. Device count comes from
    xla_force_host_platform_device_count in XLA_FLAGS (default 1). Must run
    before the first jax device/backend call."""
    import os
    import re

    if not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return
    from ..utils.testing import force_cpu_mesh

    m = re.search(
        r"xla_force_host_platform_device_count=(\d+)",
        os.environ.get("XLA_FLAGS", ""),
    )
    force_cpu_mesh(n_devices=int(m.group(1)) if m else 1)


def enable_compilation_cache() -> None:
    """Persistent XLA compilation cache (opt-out: DLLAMA_NO_COMPILE_CACHE=1).

    First TPU compiles over this box's device tunnel cost tens of seconds;
    the cache makes repeat builds of the same programs (bench phase
    children, CLI restarts, pod workers replaying identical programs)
    near-instant across processes. Kernel-geometry env knobs are safe: they
    change the serialized Mosaic kernel inside the HLO, so the cache key
    differs. Backends that cannot serialize executables degrade to a no-op
    inside JAX; the cache is an optimization, never fatal."""
    import os

    if os.environ.get("DLLAMA_NO_COMPILE_CACHE") == "1":
        return
    path = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "dllama_xla"),
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001
        print(
            f"⚠️ compilation cache disabled ({type(e).__name__}: {e})",
            file=sys.stderr,
            flush=True,
        )


def load_stack(args, n_lanes: int | None = None):
    """Returns (config, params, tokenizer, engine).

    Multi-host (--coordinator): joins the pod before touching the backend;
    on process 0 the engine comes back wrapped in RootControlEngine (every
    call is broadcast to the workers first), and on workers the raw engine
    carries `.control_plane` for `worker_loop`. Each host loads the model
    file itself — under SPMD there is no root-ships-weights protocol
    (reference: src/nn/nn-network.cpp:824-901)."""
    from ..parallel.multihost import maybe_initialize_distributed

    enable_compilation_cache()
    n_proc = maybe_initialize_distributed(args)
    if not args.model or not args.tokenizer:
        print("error: --model and --tokenizer are required", file=sys.stderr)
        raise SystemExit(2)
    header = load_model_header(args.model, max_seq_len=args.max_seq_len)
    config_dtype = jnp.bfloat16
    if jax.default_backend() == "cpu":
        config_dtype = jnp.float32  # parity-friendly on host runs

    log("💡", f"Dim: {header.dim}  HiddenDim: {header.hidden_dim}  Layers: {header.n_layers}")
    log("💡", f"Heads: {header.n_heads}/{header.n_kv_heads}  Vocab: {header.vocab_size}  SeqLen: {header.seq_len}")

    tokenizer = Tokenizer(args.tokenizer)
    log("📄", f"Vocab: {tokenizer.vocab_size}  Bos: {tokenizer.bos_id}  Eos: {tokenizer.eos_token_ids}")

    weights_mode = getattr(args, "weights", "auto")
    if weights_mode == "auto":
        weights_mode = "packed" if jax.default_backend() == "tpu" else "dense"
    if weights_mode == "packed":
        config, params = load_params_from_m_quantized(args.model, header, dtype=config_dtype)
        from ..quants.packed import PackedQ40

        if any(isinstance(x, PackedQ40) for x in [params.wcls, params.layers.wq]):
            log("🔷", "Q40 weights resident in HBM (dequant-in-matmul)")
        else:
            log("🔶", "model has no Q40 tensors; loaded dense")
    else:
        config, params = load_params_from_m(args.model, header, dtype=config_dtype)

    mesh = None
    plan = parse_mesh_spec(args.workers)
    if plan is not None and plan.n_devices > 1:
        validate_mesh_for_config(config, plan)
        mesh = make_mesh(plan)
        params = shard_params(params, mesh)
        # the Pallas Q40 kernel stays enabled: q40_matmul_partitioned carries
        # a GSPMD partitioning rule, so every shard runs dequant-in-matmul —
        # the reference's every-node-runs-the-quantized-matmul property
        # (src/nn/nn-cpu-ops.cpp:222-440)
        log(
            "⭕",
            f"Mesh: dp={plan.dp} pp={plan.pp} tp={plan.tp} sp={plan.sp} "
            f"ep={plan.ep} over {plan.n_devices} devices",
        )
    log("💿", "Weights loaded")

    # dequant chain selection (ops/pallas_q40.py): the CLI flag overrides
    # the DLLAMA_DEQUANT env default; both validate against the known-mode
    # list. Applied HERE — before the engine exists and warmup compiles —
    # because the mode is a static argname of the jitted matmul: a later
    # switch would retrace every warmed family mid-serving.
    from ..ops import pallas_q40 as _pq

    if getattr(args, "dequant", None) is not None:
        _pq.set_dequant_mode(args.dequant)
    if _pq.DEQUANT_MODE == "auto":
        from ..ops.dequant_select import freeze_for_serving

        prov = freeze_for_serving() or {}
        log("🎛️", f"Dequant mode: auto — per-site selection from "
                  f"{prov.get('path', 'ops/dequant_table.json')} "
                  f"(v{prov.get('version')}, {prov.get('rows')} rows, "
                  f"updated {prov.get('updated')}); resolved at warmup "
                  "trace time")
    elif _pq.DEQUANT_MODE != "v4":
        log("🎛️", f"Dequant mode: {_pq.DEQUANT_MODE} "
                  "(--dequant / DLLAMA_DEQUANT)")

    from ..quants.codec import FloatType

    emulate_q80 = args.buffer_float_type == FloatType.Q80
    q80_sync = False
    if emulate_q80 and mesh is not None:
        # same predicate llama_forward uses, so the log only claims the
        # transport when it will actually engage
        from ..parallel.collectives import q80_sync_engages

        q80_sync = q80_sync_engages(config, dict(mesh.shape))
    if q80_sync:
        synced = "wo" if config.n_experts > 0 else "wo/w2"
        log("🔶", f"Q80 sync transport: {synced} TP boundaries ship int8+scales "
                  "(--buffer-float-type q80 on a tp mesh)")
    elif emulate_q80:
        log("🔶", "Q80 activation-cast emulation enabled (--buffer-float-type q80)")
    # ring-overlapped TP activation sync (ops/ring_collective.py): CLI flag
    # overrides the DLLAMA_RING_SYNC env default; the log uses the same
    # predicate llama_forward does, so what is announced is what runs
    from ..ops.ring_collective import (
        ring_sync_engages,
        ring_sync_supported,
        set_ring_sync,
    )

    if getattr(args, "ring_sync", None) is not None:
        set_ring_sync(args.ring_sync == "on")
    if mesh is not None and ring_sync_engages(config, dict(mesh.shape)):
        # mirror llama_forward's FULL gate (engages + per-output support,
        # q80-wire blocks included — q80_sync_engages already guarantees the
        # block divisibility today, but the log must not outlive that
        # coincidence): what is announced is what runs
        tp = dict(mesh.shape).get("tp", 1)
        if ring_sync_supported(config.dim, tp, q80_sync):
            synced = "wo" if config.n_experts > 0 else "wo/w2"
            log("🔗", f"Ring TP sync: {synced} activation sync overlapped "
                      "with the dequant matmul"
                      + (" (Q80 wire)" if q80_sync else "")
                      + " — DLLAMA_RING_SYNC=off / --ring-sync off to fall "
                        "back to psum")
    if n_proc > 1 and mesh is None:
        print(
            "error: multi-host runs need a --workers mesh spec spanning the "
            "global device set",
            file=sys.stderr,
        )
        raise SystemExit(2)
    engine = InferenceEngine(
        config,
        params,
        # every process must compile identical programs: lane count comes
        # from --max-lanes on all hosts (n_lanes overrides are single-host)
        n_lanes=(n_lanes if n_proc == 1 else None) or args.max_lanes,
        # None -> bf16 KV on TPU, f32 on CPU (parity oracle); --kv-dtype
        # overrides (e.g. f32 on TPU for strict-parity serving, f8 for
        # double the lanes/context per chip)
        cache_dtype={
            "f32": jnp.float32, "bf16": jnp.bfloat16,
            "f8": jnp.float8_e4m3fn, "auto": None,
        }[getattr(args, "kv_dtype", "auto") or "auto"],
        emulate_q80_activations=emulate_q80,
        q80_sync=q80_sync,
        mesh=mesh,
        replicate_outputs=n_proc > 1,
        # async decode pipeline ring bound (None -> engine default 2);
        # every process must agree, like --max-lanes
        pipeline_depth=getattr(args, "pipeline_depth", None),
        # paged KV pool (runtime/kvpool.py): every process must agree on
        # the layout — the table leaf is part of the compiled programs'
        # pytree structure (OP_KV_TABLE replays assume paged workers)
        paged_kv=getattr(args, "paged_kv", "off") == "on",
        # pass explicit values through unmodified (None = flag absent):
        # a 0/negative --kv-page-size must die in for_seq_len's
        # validation, not silently become the default
        kv_page_size=(DEFAULT_PAGE_SIZE
                      if getattr(args, "kv_page_size", None) is None
                      else args.kv_page_size),
        kv_pool_pages=getattr(args, "kv_pool_pages", None),
        kv_max_parked=(DEFAULT_MAX_PARKED
                       if getattr(args, "kv_max_parked", None) is None
                       else args.kv_max_parked),
        # host-RAM swap tier budget (0 = disabled, drop-to-rebuild
        # bit-for-bit); host-side only, so processes need not agree,
        # but the OP_KV_SWAP replay assumes paged workers like pages
        kv_host_bytes=getattr(args, "kv_host_bytes", None) or 0,
        # grammar slab capacity (structured output): every process must
        # agree — the slab arrays are compiled-program operands
        grammar_slab_states=getattr(args, "grammar_slab_states", None),
    )
    if engine.kvpool is not None:
        log(
            "📑",
            f"Paged KV: {engine.kvpool.n_pages} pages x "
            f"{engine.kvpool.page_size} tokens, "
            f"{engine.kvpool.blocks_per_lane} blocks/lane, "
            f"max parked {engine.kvpool.max_parked}, "
            + (f"host swap tier "
               f"{engine.kvpool.host_tier.budget_bytes // (1 << 20)} MiB"
               if engine.kvpool.host_tier.enabled
               else "host swap tier off")
            + " (--paged-kv off restores contiguous planes)",
        )
    # structured output (grammar/; docs/SERVING.md "Structured output"):
    # register the tokenizer's piece table so response_format requests
    # compile token-level automata — on EVERY process (workers replay
    # OP_GRAMMAR attaches against their own identical table). --grammar
    # off is the escape hatch: requests carrying response_format then 400.
    if getattr(args, "grammar", "on") != "off":
        engine.grammar_init(
            [tokenizer.vocab[i] if i < tokenizer.bos_id else None
             for i in range(tokenizer.vocab_size)],
            tokenizer.eos_token_ids,
        )
        log("🧩", "Structured output: json_object / json_schema enabled "
                  "(--grammar off disables)")
    if n_proc > 1:
        from ..parallel.multihost import ControlPlane, RootControlEngine

        # packet slots must fit the largest prefill chunk AND (paged) a
        # full page-table row, or send_kv_table's pre-broadcast check
        # rejects long-context table updates
        plane_chunk = engine.prefill_buckets[-1]
        if engine.kvpool is not None:
            plane_chunk = max(plane_chunk, engine.kvpool.blocks_per_lane)
        plane = ControlPlane(engine.n_lanes, chunk=plane_chunk)
        if jax.process_index() == 0:
            log("⭕", f"Multi-host root: {n_proc} processes, control plane up")
            engine = RootControlEngine(engine, plane)
        else:
            log("⭕", f"Multi-host worker {jax.process_index()}/{n_proc}")
            engine.control_plane = plane
    return config, params, tokenizer, engine


def make_scheduler(engine, tokenizer, args=None) -> ContinuousBatchingScheduler:
    from ..runtime.engine import warmup_engine
    from ..serving import DeadlinePolicy, QosQueue

    speculative = not getattr(args, "no_spec", False)
    # pass prefix_min_tokens/multi_step only when the CLI provided them: the
    # scheduler defaults are the single source of truth for fallback values
    pmt = getattr(args, "prefix_min_tokens", None)
    ms = getattr(args, "multi_step", None)
    overrides = {}
    if pmt is not None:
        overrides["prefix_min_tokens"] = pmt
    if ms is not None:
        overrides["multi_step"] = ms
    fp = getattr(args, "fused_prefill", None)
    if fp is not None:  # --fused-prefill on/off (stall-free admissions)
        overrides["fused_prefill"] = fp == "on"
    # failure containment (serving/breaker.py, serving/watchdog.py): the
    # step watchdog arms when --step-deadline / DLLAMA_STEP_DEADLINE is
    # set; on a pod ROOT a trip crashes the process deliberately so
    # jax.distributed peer-failure detection surfaces the hang (the
    # multihost.py analysis: death beats silent desync)
    sd = getattr(args, "step_deadline", None)
    if sd is not None:
        overrides["step_deadline_s"] = sd
    overrides["watchdog_fatal"] = (
        getattr(engine, "_plane", None) is not None  # RootControlEngine
    )
    # crash durability (serving/journal.py): the append-only request
    # journal, off unless --journal-path names a file; recovery replay
    # (--recover-journal) is wired by dllama_api after the scheduler is
    # up, since stream reattach also needs the resume registry
    jp = getattr(args, "journal_path", None)
    if jp:
        from ..serving import RequestJournal

        overrides["journal"] = RequestJournal(jp)
        log("📓", f"Request journal: {jp} (crash-durable serving)")
    # QoS surface (--max-queue / --queue-timeout / --request-budget):
    # bounded admission with per-user fair share, plus deadlines
    max_queue = getattr(args, "max_queue", 0) or 0
    policy = DeadlinePolicy.from_args(args) if args is not None else DeadlinePolicy()
    # paged engines charge DRR fair share in PAGES — what admission
    # actually takes from the pool — instead of decode tokens; the
    # quantum rescales so the rotation grain stays ~128 tokens' worth
    qos_kw = {}
    pool = getattr(engine, "kvpool", None)
    if pool is not None:
        from ..serving.qos import page_cost

        qos_kw = {
            "cost": page_cost(pool.page_size),
            "quantum": max(1.0, 128.0 / pool.page_size),
        }
    log(
        "🚦",
        f"QoS: queue capacity {max_queue or 'unbounded'}, "
        f"queue timeout {policy.queue_timeout_s or 'off'}, "
        f"request budget {policy.request_budget_s or 'off'}"
        + (", fair share in KV pages" if pool is not None else ""),
    )
    log("⏳", "Warming serving programs (prefill buckets, decode, spec)...")
    t0 = time.perf_counter()
    sched = ContinuousBatchingScheduler(
        engine, tokenizer, speculative=speculative,
        queue_=QosQueue(capacity=max_queue, **qos_kw),
        deadlines=policy, **overrides,
    )
    warmup_engine(engine, spec=speculative, multi_step=sched.multi_step)
    log("⏳", f"Warmup done in {time.perf_counter() - t0:.1f}s")
    sched.start()
    return sched
