"""Staged Q40 kernel diagnostic: where do the cycles go?

Measures steady-state kernel throughput by streaming a stack of L weight
planes in ONE pallas_call (grid leads with the stack axis), with a small
carry operand threaded through a fori_loop so XLA cannot hoist the call out
of the timing loop (the round-3 kernel lab's read probe had exactly that
bug: a loop-invariant body gets CSE'd and you time one dispatch / reps).

Stages: DMA only, +u8 unpack to i32 lanes, +nibble extract, +float convert,
+scale mul, full matmul (two-dot formulation) — plus the same with the
packed plane pre-bitcast to u32 lanes, and dot-only MXU references with
pre-dequantized bf16/f32 planes.

Run: python scripts/stage_probe.py [d_in] [d_out] [L] [reps]
"""

from __future__ import annotations

import sys
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from distributed_llama_multiusers_tpu.ops.pallas_q40 import (  # noqa: E402
    _f16_bits_to_f32,
)

HBM_GB_S = 819.0  # v5e

CHUNK = 2048
TILE = 512
M = 8
_REPS = 8  # overridden by argv[4]


# Kernels get (t_ref, ...) and add t_ref[0, 0] to the output: the timing
# loop feeds the previous iteration's result through t, defeating CSE/LICM.


def _k_dma(t_ref, p_ref, o_ref):
    o_ref[...] = (
        p_ref[0:1, :].astype(jnp.int32).astype(jnp.float32) + t_ref[0, 0]
    )


def _k_unpack(t_ref, p_ref, o_ref):
    p = p_ref[...].astype(jnp.int32)
    o_ref[...] = (
        jnp.sum(p, axis=0, keepdims=True).astype(jnp.float32) + t_ref[0, 0]
    )


def _k_nib(t_ref, p_ref, o_ref):
    p = p_ref[...].astype(jnp.int32)
    lo = p & 0x0F
    hi = p >> 4
    o_ref[...] = (
        jnp.sum(lo + hi, axis=0, keepdims=True).astype(jnp.float32)
        + t_ref[0, 0]
    )


def _k_conv(t_ref, p_ref, o_ref, *, dt):
    p = p_ref[...].astype(jnp.int32)
    lo = (p & 0x0F).astype(dt)
    hi = (p >> 4).astype(dt)
    o_ref[...] = (
        jnp.sum((lo + hi).astype(jnp.float32), axis=0, keepdims=True)
        + t_ref[0, 0]
    )


def _k_scale(t_ref, p_ref, s_ref, o_ref):
    half_rows, tile = p_ref.shape
    n_blk = half_rows // 16
    p = p_ref[...].astype(jnp.int32)
    s = _f16_bits_to_f32(s_ref[...])[:, None, :]
    lo = (p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, tile) * s
    hi = (p >> 4).astype(jnp.float32).reshape(n_blk, 16, tile) * s
    o_ref[...] = (
        jnp.sum((lo + hi).reshape(half_rows, tile), axis=0, keepdims=True)
        + t_ref[0, 0]
    )


def _k_full(t_ref, x_lo_ref, x_hi_ref, p_ref, s_ref, o_ref, *, w_dtype):
    half_rows, tile = p_ref.shape
    n_blk = half_rows // 16
    p = p_ref[...].astype(jnp.int32)
    s = _f16_bits_to_f32(s_ref[...])[:, None, :]
    w_lo = ((p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, tile) * s)
    w_hi = ((p >> 4).astype(jnp.float32).reshape(n_blk, 16, tile) * s)
    w_lo = w_lo.reshape(half_rows, tile).astype(w_dtype)
    w_hi = w_hi.reshape(half_rows, tile).astype(w_dtype)
    # cast x DOWN to w_dtype (the product kernel's convention): w_dtype is
    # the dot's compute dtype, so "bf16w" really times a bf16 MXU dot
    o_ref[...] = (
        jnp.dot(x_lo_ref[...].astype(w_dtype), w_lo,
                preferred_element_type=jnp.float32)
        + jnp.dot(x_hi_ref[...].astype(w_dtype), w_hi,
                  preferred_element_type=jnp.float32)
        + t_ref[0, 0]
    )


def _k32_nib(t_ref, p_ref, o_ref):
    w = p_ref[...]
    acc = None
    for sh in range(0, 32, 4):
        nib = (w >> sh) & 0x0F
        acc = nib if acc is None else acc + nib
    o_ref[...] = (
        jnp.sum(acc, axis=0, keepdims=True).astype(jnp.float32) + t_ref[0, 0]
    )


def _k32_conv(t_ref, p_ref, o_ref, *, dt):
    w = p_ref[...]
    acc = None
    for sh in range(0, 32, 4):
        nib = ((w >> sh) & 0x0F).astype(dt)
        acc = nib if acc is None else acc + nib
    o_ref[...] = (
        jnp.sum(acc.astype(jnp.float32), axis=0, keepdims=True) + t_ref[0, 0]
    )


def _k_dot_only(t_ref, x_ref, w_ref, o_ref):
    o_ref[...] = (
        jnp.dot(x_ref[...], w_ref[...], preferred_element_type=jnp.float32)
        + t_ref[0, 0]
    )


def timeit(name, build_call, bytes_per_pass, reps=None):
    reps = reps if reps is not None else _REPS
    """build_call(t) -> output array; t is the carry scalar array [1, 128]."""

    @jax.jit
    def loop(seed):
        def body(_, acc):
            t = jnp.full((1, 128), acc, jnp.float32)
            out = build_call(t)
            return out.reshape(-1)[0].astype(jnp.float32) * 1e-30

        return jax.lax.fori_loop(0, reps, body, seed)

    try:
        np.asarray(loop(jnp.float32(0)))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(loop(jnp.float32(0)))
            best = min(best, time.perf_counter() - t0)
        sec = best / reps
        gbs = bytes_per_pass / sec / 1e9
        print(f"{name:22s} {sec * 1e3:8.3f} ms  {gbs:7.1f} GB/s "
              f"({gbs / HBM_GB_S * 100:5.1f}% HBM)", flush=True)
    except Exception as e:
        print(f"{name:22s} FAILED: {type(e).__name__}: {str(e)[:140]}",
              flush=True)


def main():
    d_in = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    d_out = int(sys.argv[2]) if len(sys.argv) > 2 else 14336
    global _REPS
    L = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    _REPS = int(sys.argv[4]) if len(sys.argv) > 4 else 8

    # Draw the planes ON DEVICE: timing only cares about bytes, and bulk
    # device_put over the axon tunnel is slow enough to wedge it (both
    # round-4/5 outages followed a multi-hundred-MB put). Only scalars
    # cross the link.
    half = d_in // 2
    kp, ks = jax.random.split(jax.random.PRNGKey(0))
    packed = jax.random.bits(kp, (L, half, d_out), jnp.uint8)
    scales = (
        jax.random.uniform(ks, (L, d_in // 32, d_out), jnp.float32) * 0.01
        + 0.001
    ).astype(jnp.float16)
    sbits = jax.lax.bitcast_convert_type(scales, jnp.int16)
    jax.block_until_ready((packed, sbits))
    pbytes = packed.size
    print(f"d_in={d_in} d_out={d_out} L={L} packed={pbytes / 1e6:.1f} MB "
          f"device={jax.devices()[0].device_kind}", flush=True)

    grid = (L, d_out // TILE, half // (CHUNK // 2))
    t_spec = pl.BlockSpec((1, 128), lambda l, j, k: (0, 0))
    p_spec = pl.BlockSpec((1, CHUNK // 2, TILE), lambda l, j, k: (l, k, j))
    s_spec = pl.BlockSpec((1, CHUNK // 32, TILE), lambda l, j, k: (l, k, j))
    o_spec = pl.BlockSpec((1, TILE), lambda l, j, k: (0, j))
    o_shape = jax.ShapeDtypeStruct((1, d_out), jnp.float32)
    params = pltpu.CompilerParams(
        dimension_semantics=("arbitrary", "parallel", "arbitrary"),
    )

    def staged(kernel, n_in):
        def call(t):
            ops = (packed, sbits)[:n_in]
            return pl.pallas_call(
                _squeeze_lead(kernel, n_in),
                grid=grid,
                in_specs=[t_spec] + [p_spec, s_spec][:n_in],
                out_specs=o_spec,
                out_shape=o_shape,
                compiler_params=params,
            )(t, *ops)

        return call

    def _squeeze_lead(kernel, n_in):
        # blocks arrive [1, r, c] because of the stack axis; drop the lead
        def wrapped(t_ref, *refs):
            ins = [r.at[0] for r in refs[:n_in]]
            kernel(t_ref, *ins, refs[-1])

        return wrapped

    timeit("u8 dma", staged(_k_dma, 1), pbytes)
    timeit("u8 +unpack_i32", staged(_k_unpack, 1), pbytes)
    timeit("u8 +nibbles", staged(_k_nib, 1), pbytes)
    timeit("u8 +convert_f32", staged(partial(_k_conv, dt=jnp.float32), 1), pbytes)
    timeit("u8 +convert_bf16", staged(partial(_k_conv, dt=jnp.bfloat16), 1), pbytes)
    timeit("u8 +scale", staged(_k_scale, 2), pbytes)

    # u32 lanes: group 4 consecutive d_out columns per lane
    p32 = jax.lax.bitcast_convert_type(
        packed.reshape(L, half, d_out // 4, 4), jnp.uint32
    ).astype(jnp.int32)
    grid32 = (L, d_out // 4 // (TILE // 4), half // (CHUNK // 2))
    p32_spec = pl.BlockSpec((1, CHUNK // 2, TILE // 4), lambda l, j, k: (l, k, j))
    o32_spec = pl.BlockSpec((1, TILE // 4), lambda l, j, k: (0, j))
    o32_shape = jax.ShapeDtypeStruct((1, d_out // 4), jnp.float32)

    def staged32(kernel):
        def call(t):
            def wrapped(t_ref, p_ref, o_ref):
                kernel(t_ref, p_ref.at[0], o_ref)

            return pl.pallas_call(
                wrapped, grid=grid32,
                in_specs=[t_spec, p32_spec],
                out_specs=o32_spec, out_shape=o32_shape,
                compiler_params=params,
            )(t, p32)

        return call

    timeit("u32 +nibbles", staged32(_k32_nib), pbytes)
    timeit("u32 +convert_f32", staged32(partial(_k32_conv, dt=jnp.float32)), pbytes)
    timeit("u32 +convert_bf16", staged32(partial(_k32_conv, dt=jnp.bfloat16)), pbytes)

    # MXU stream reference: dot over pre-dequantized planes at same shapes
    kx = jax.random.PRNGKey(1)
    x = jax.random.normal(kx, (M, d_in), jnp.float32)
    for dt, tag in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
        wd = jax.random.normal(
            jax.random.PRNGKey(2), (L, d_in, d_out), jnp.float32
        ).astype(dt)
        jax.block_until_ready(wd)
        x_spec = pl.BlockSpec((M, CHUNK), lambda l, j, k: (0, k))
        w_spec = pl.BlockSpec((1, CHUNK, TILE), lambda l, j, k: (l, k, j))
        od_spec = pl.BlockSpec((M, TILE), lambda l, j, k: (0, j))
        od_shape = jax.ShapeDtypeStruct((M, d_out), jnp.float32)
        xd = x.astype(dt)

        def call(t, w_stack=wd, x_op=xd):
            def wrapped(t_ref, x_ref, w_ref, o_ref):
                _k_dot_only(t_ref, x_ref, w_ref.at[0], o_ref)

            return pl.pallas_call(
                wrapped, grid=grid,
                in_specs=[t_spec, x_spec, w_spec],
                out_specs=od_spec, out_shape=od_shape,
                compiler_params=params,
            )(t, x_op, w_stack)

        timeit(f"dot_only {tag}", call, wd.size * wd.dtype.itemsize)
        del wd

    # full two-dot kernel (current product formulation), f32 and bf16 planes
    xf = jax.random.normal(jax.random.PRNGKey(3), (M, d_in), jnp.float32)
    xb = xf.reshape(M, d_in // 32, 2, 16)
    x_lo = xb[:, :, 0, :].reshape(M, half)
    x_hi = xb[:, :, 1, :].reshape(M, half)
    xs_spec = pl.BlockSpec((M, CHUNK // 2), lambda l, j, k: (0, k))
    of_spec = pl.BlockSpec((M, TILE), lambda l, j, k: (0, j))
    of_shape = jax.ShapeDtypeStruct((M, d_out), jnp.float32)
    for w_dt, x_dt, tag in (
        (jnp.float32, jnp.float32, "f32"),
        (jnp.bfloat16, jnp.float32, "bf16w"),
        (jnp.bfloat16, jnp.bfloat16, "bf16wx"),
    ):
        xl, xh = x_lo.astype(x_dt), x_hi.astype(x_dt)

        def call(t, xl=xl, xh=xh, w_dt=w_dt):
            def wrapped(t_ref, xl_ref, xh_ref, p_ref, s_ref, o_ref):
                _k_full(t_ref, xl_ref, xh_ref, p_ref.at[0], s_ref.at[0],
                        o_ref, w_dtype=w_dt)

            return pl.pallas_call(
                wrapped, grid=grid,
                in_specs=[t_spec, xs_spec, xs_spec, p_spec, s_spec],
                out_specs=of_spec, out_shape=of_shape,
                compiler_params=params,
            )(t, xl, xh, packed, sbits)

        timeit(f"full {tag}", call, pbytes)


if __name__ == "__main__":
    main()
