"""Staged Q40 kernel diagnostic: where do the cycles go?

Builds a series of Pallas kernels that incrementally add pipeline stages —
DMA only, +u8 unpack, +nibble extract, +f32 convert, +scale mul, +MXU dot —
and times each on the real TPU at decode shapes. The deltas attribute the
cost. Also times the same stages with the packed plane pre-bitcast to u32
(4 bytes/lane instead of 1) and an MXU-stream reference with pre-dequantized
bf16 planes.

Run: python scripts/stage_probe.py [d_in] [d_out] [L]
"""

from __future__ import annotations

import sys
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from distributed_llama_multiusers_tpu.quants.packed import (  # noqa: E402
    PackedQ40,
    pack_q40_host,
)
from distributed_llama_multiusers_tpu.ops.pallas_q40 import (  # noqa: E402
    _f16_bits_to_f32,
)

HBM_GB_S = 819.0  # v5e

CHUNK = 2048
TILE = 512


# --- u8-plane staged kernels ------------------------------------------------


def _k_dma(p_ref, o_ref):
    # touch one sublane so the block DMA is observable but compute ~ 0
    o_ref[...] = p_ref[0:1, :].astype(jnp.float32)


def _k_unpack(p_ref, o_ref):
    p = p_ref[...].astype(jnp.int32)
    o_ref[...] = jnp.sum(p, axis=0, keepdims=True).astype(jnp.float32)


def _k_nib(p_ref, o_ref):
    p = p_ref[...].astype(jnp.int32)
    lo = p & 0x0F
    hi = p >> 4
    o_ref[...] = jnp.sum(lo + hi, axis=0, keepdims=True).astype(jnp.float32)


def _k_conv(p_ref, o_ref):
    p = p_ref[...].astype(jnp.int32)
    lo = (p & 0x0F).astype(jnp.float32)
    hi = (p >> 4).astype(jnp.float32)
    o_ref[...] = jnp.sum(lo + hi, axis=0, keepdims=True)


def _k_conv_bf16(p_ref, o_ref):
    p = p_ref[...].astype(jnp.int32)
    lo = (p & 0x0F).astype(jnp.bfloat16)
    hi = (p >> 4).astype(jnp.bfloat16)
    o_ref[...] = jnp.sum(
        (lo + hi).astype(jnp.float32), axis=0, keepdims=True
    )


def _k_scale(p_ref, s_ref, o_ref):
    half_rows, tile = p_ref.shape
    n_blk = half_rows // 16
    p = p_ref[...].astype(jnp.int32)
    s = _f16_bits_to_f32(s_ref[...])[:, None, :]
    lo = (p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, tile) * s
    hi = (p >> 4).astype(jnp.float32).reshape(n_blk, 16, tile) * s
    o_ref[...] = jnp.sum(
        (lo + hi).reshape(half_rows, tile), axis=0, keepdims=True
    )


def _k_full(x_lo_ref, x_hi_ref, p_ref, s_ref, o_ref, *, w_dtype):
    half_rows, tile = p_ref.shape
    n_blk = half_rows // 16
    p = p_ref[...].astype(jnp.int32)
    s = _f16_bits_to_f32(s_ref[...])[:, None, :]
    w_lo = ((p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, tile) * s)
    w_hi = ((p >> 4).astype(jnp.float32).reshape(n_blk, 16, tile) * s)
    w_lo = w_lo.reshape(half_rows, tile).astype(w_dtype)
    w_hi = w_hi.reshape(half_rows, tile).astype(w_dtype)
    o_ref[...] = (
        jnp.dot(x_lo_ref[...], w_lo, preferred_element_type=jnp.float32)
        + jnp.dot(x_hi_ref[...], w_hi, preferred_element_type=jnp.float32)
    )


# --- u32-plane staged kernels (packed bytes pre-bitcast to u32 lanes) -------


def _k32_dma(p_ref, o_ref):
    o_ref[...] = p_ref[0:1, :].astype(jnp.float32)


def _k32_unpack(p_ref, o_ref):
    w = p_ref[...]  # already int32 lanes
    o_ref[...] = jnp.sum(w, axis=0, keepdims=True).astype(jnp.float32)


def _k32_nib(p_ref, o_ref):
    w = p_ref[...]
    acc = None
    for sh in range(0, 32, 4):
        nib = (w >> sh) & 0x0F
        acc = nib if acc is None else acc + nib
    o_ref[...] = jnp.sum(acc, axis=0, keepdims=True).astype(jnp.float32)


def _k32_conv(p_ref, o_ref):
    w = p_ref[...]
    acc = None
    for sh in range(0, 32, 4):
        nib = ((w >> sh) & 0x0F).astype(jnp.float32)
        acc = nib if acc is None else acc + nib
    o_ref[...] = jnp.sum(acc, axis=0, keepdims=True)


# --- MXU stream reference: pre-dequantized planes, dot only ------------------


def _k_dot_only(x_ref, w_ref, o_ref):
    o_ref[...] = jnp.dot(
        x_ref[...], w_ref[...], preferred_element_type=jnp.float32
    )


def run_staged(name, kernel, operands, specs, grid, out_shape, bytes_per_pass,
               reps=30):
    out_specs, scratch = out_shape

    @jax.jit
    def once(*ops):
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=specs,
            out_specs=out_specs,
            out_shape=scratch,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary"),
            ),
        )(*ops)

    @jax.jit
    def loop(*ops):
        def body(_, acc):
            return acc + once(*ops)[0, 0].astype(jnp.float32)

        return jax.lax.fori_loop(0, reps, body, jnp.float32(0))

    try:
        np.asarray(loop(*operands))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(loop(*operands))
            best = min(best, time.perf_counter() - t0)
        sec = best / reps
        gbs = bytes_per_pass / sec / 1e9
        print(f"{name:22s} {sec * 1e3:8.3f} ms  {gbs:7.1f} GB/s "
              f"({gbs / HBM_GB_S * 100:5.1f}% HBM)", flush=True)
    except Exception as e:
        print(f"{name:22s} FAILED: {type(e).__name__}: {str(e)[:140]}",
              flush=True)


def main():
    d_in = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    d_out = int(sys.argv[2]) if len(sys.argv) > 2 else 14336
    rng = np.random.default_rng(0)
    w = (rng.standard_normal((d_out, d_in), dtype=np.float32) * 0.05)
    packed, scales = pack_q40_host(w)
    packed = jnp.asarray(packed)  # [d_in//2, d_out]
    scales = jnp.asarray(scales)
    sbits = jax.lax.bitcast_convert_type(scales, jnp.int16)
    pbytes = packed.size
    print(f"d_in={d_in} d_out={d_out} packed={pbytes / 1e6:.1f} MB "
          f"device={jax.devices()[0].device_kind}", flush=True)

    half = d_in // 2
    grid = (d_out // TILE, half // (CHUNK // 2))
    p_spec = pl.BlockSpec((CHUNK // 2, TILE), lambda j, k: (k, j))
    s_spec = pl.BlockSpec((CHUNK // 32, TILE), lambda j, k: (k, j))
    o_spec = pl.BlockSpec((1, TILE), lambda j, k: (0, j))
    o_shape = jax.ShapeDtypeStruct((1, d_out), jnp.float32)

    run_staged("u8 dma", _k_dma, (packed,), [p_spec], grid,
               (o_spec, o_shape), pbytes)
    run_staged("u8 +unpack_i32", _k_unpack, (packed,), [p_spec], grid,
               (o_spec, o_shape), pbytes)
    run_staged("u8 +nibbles", _k_nib, (packed,), [p_spec], grid,
               (o_spec, o_shape), pbytes)
    run_staged("u8 +convert_f32", _k_conv, (packed,), [p_spec], grid,
               (o_spec, o_shape), pbytes)
    run_staged("u8 +convert_bf16", _k_conv_bf16, (packed,), [p_spec], grid,
               (o_spec, o_shape), pbytes)
    run_staged("u8 +scale", _k_scale, (packed, sbits), [p_spec, s_spec], grid,
               (o_spec, o_shape), pbytes)

    # u32 lanes: [half, d_out] u8 -> [half, d_out//4] u32 (4 consecutive
    # d_out columns per lane)
    p32 = jax.lax.bitcast_convert_type(
        packed.reshape(half, d_out // 4, 4), jnp.uint32
    ).astype(jnp.int32)
    grid32 = (d_out // 4 // (TILE // 4), half // (CHUNK // 2))
    p32_spec = pl.BlockSpec((CHUNK // 2, TILE // 4), lambda j, k: (k, j))
    o32_spec = pl.BlockSpec((1, TILE // 4), lambda j, k: (0, j))
    o32_shape = jax.ShapeDtypeStruct((1, d_out // 4), jnp.float32)

    run_staged("u32 dma", _k32_dma, (p32,), [p32_spec], grid32,
               (o32_spec, o32_shape), pbytes)
    run_staged("u32 +unpack", _k32_unpack, (p32,), [p32_spec], grid32,
               (o32_spec, o32_shape), pbytes)
    run_staged("u32 +nibbles", _k32_nib, (p32,), [p32_spec], grid32,
               (o32_spec, o32_shape), pbytes)
    run_staged("u32 +convert_f32", _k32_conv, (p32,), [p32_spec], grid32,
               (o32_spec, o32_shape), pbytes)

    # MXU stream reference at same logical shapes: bf16 / f32 dense planes
    m_pad = 8
    x = jnp.asarray(rng.standard_normal((m_pad, d_in), dtype=np.float32))
    for dt, tag in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
        wd = jnp.asarray(np.swapaxes(w, 0, 1), dtype=dt)  # [d_in, d_out]
        x_spec = pl.BlockSpec((m_pad, CHUNK), lambda j, k: (0, k))
        w_spec = pl.BlockSpec((CHUNK, TILE), lambda j, k: (k, j))
        od_spec = pl.BlockSpec((m_pad, TILE), lambda j, k: (0, j))
        od_shape = jax.ShapeDtypeStruct((m_pad, d_out), jnp.float32)
        run_staged(
            f"dot_only {tag}", _k_dot_only, (x.astype(dt), wd),
            [x_spec, w_spec], (d_out // TILE, d_in // CHUNK),
            (od_spec, od_shape), wd.size * wd.dtype.itemsize,
        )

    # full kernel (current product formulation) at m=8 for reference
    xf = jnp.asarray(rng.standard_normal((m_pad, d_in), dtype=np.float32))
    xb = xf.reshape(m_pad, d_in // 32, 2, 16)
    x_lo = xb[:, :, 0, :].reshape(m_pad, half)
    x_hi = xb[:, :, 1, :].reshape(m_pad, half)
    xs = pl.BlockSpec((m_pad, CHUNK // 2), lambda j, k: (0, k))
    of_spec = pl.BlockSpec((m_pad, TILE), lambda j, k: (0, j))
    of_shape = jax.ShapeDtypeStruct((m_pad, d_out), jnp.float32)
    for dt, tag in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
        run_staged(
            f"full_nocorr {tag}", partial(_k_full, w_dtype=dt),
            (x_lo, x_hi, packed, sbits), [xs, xs, p_spec, s_spec], grid,
            (of_spec, of_shape), pbytes,
        )


if __name__ == "__main__":
    main()
