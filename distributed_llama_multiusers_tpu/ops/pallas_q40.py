"""Pallas TPU kernel: y = x @ dequant(W) for Q40-packed weights.

The TPU analogue of the reference's dequant-in-matmul kernels
(matmul_Q80_Q40_F32, src/nn/nn-cpu-ops.cpp:222-440, and the Vulkan shader
src/nn/vulkan/matmul-forward-q80-q40-f32.comp): weights stay int4+f16-scale
in HBM (~4.5 bits/element) and are expanded to f32 tile-by-tile in VMEM,
never materializing the dense weight in HBM. Decode-time matmuls are
HBM-bandwidth-bound, so reading 4.5 bits instead of 16 (bf16) per element is
the main single-chip throughput lever.

Layout (quants/packed.py): block-local nibble halves — each 32-input quant
block is 16 consecutive packed rows (low nibble = block inputs [0,16), high
nibble = [16,32)) + 1 scale row, so a chunk of whole blocks covers the same
contiguous input range in `packed`, `scales`, and `x`.

Kernel formulation (round-3 kernel-lab "v1", landed round 4): TWO dots —
the low/high nibble planes each multiply a pre-split half of x, so the
kernel never concatenates/relayouts the dequantized tile — and the -8
nibble offset is folded into one small correction dot against per-block x
sums instead of a per-weight subtract. Per packed byte the VPU does one
shift+mask+scale-mul, the rest is MXU work.

Grid: (m tiles, d_out tiles, d_in chunks). The d_in axis is the reduction
(innermost, "arbitrary"); the output tile accumulates across it in an f32
VMEM scratch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants.packed import PackedQ40

# Upper bounds; actual tiles are fitted to the operand (see _pick_*).
DIN_CHUNK = 2048  # input rows per reduction step
DOUT_TILE = 512
M_TILE = 256
ROW_ALIGN = 8  # x rows padded to this multiple


def _f16_bits_to_f32(h: jnp.ndarray) -> jnp.ndarray:
    """Exact f16 -> f32 from int16 bit patterns (Mosaic has no f16 type).

    Exact for all finite f16 values, which the Q40 encoder guarantees.
    Normals: rebias the exponent into f32 position. Denormals: mant * 2^-24
    as a float product — no denormal f32 intermediates, so flush-to-zero
    hardware (XLA:CPU, TPU) cannot corrupt them."""
    h32 = h.astype(jnp.int32) & 0xFFFF
    exp = (h32 >> 10) & 0x1F
    mant = h32 & 0x3FF
    normal = jax.lax.bitcast_convert_type(
        ((exp + 112) << 23) | (mant << 13), jnp.float32
    )
    denorm = mant.astype(jnp.float32) * jnp.float32(5.9604644775390625e-08)  # 2^-24
    mag = jnp.where(exp == 0, denorm, normal)
    return jnp.where(h32 >> 15 != 0, -mag, mag)


def _q40_matmul_kernel(x_lo_ref, x_hi_ref, bsum_t_ref, packed_ref, scales_ref,
                       out_ref, acc_ref, *, w_dtype):
    """One (m tile, d_out tile, d_in chunk) step — the two-dot formulation
    (round-3 kernel lab "v1", promoted to the product per round-3 VERDICT):

    - NO nibble concat: the low/high nibble planes each feed their own MXU
      dot against a matching pre-split half of x, so the dequantized tile
      never needs the [n_blk, 32, tile] relayout the original kernel paid
      per chunk (the VPU shuffle that capped it at 44% HBM).
    - NO per-weight -8 subtract: folded into one small correction dot,
      8 * (per-block x sums) @ scales, subtracted from the accumulator.

    x_lo/x_hi: [mt, chunk/2] (block-interleaved halves of x's columns).
    bsum_t: [chunk/32, mt] f32 — per-quant-block sums of x, transposed so
    the (full-extent) lane dim is m. packed: [chunk/2, tile] uint8. scales:
    [chunk/32, tile] int16 (f16 bits). acc: [mt, tile] f32 scratch.
    ``w_dtype``: dtype of the dequantized weight planes fed to the MXU —
    f32 is exact; bf16 halves VMEM traffic but rounds (nibble*scale needs
    up to 15 mantissa bits).
    """
    k = pl.program_id(2)

    p = packed_ref[...].astype(jnp.int32)  # int32: Mosaic lacks i8 arithmetic
    half_rows, tile = packed_ref.shape
    n_blk = half_rows // 16
    s = _f16_bits_to_f32(scales_ref[...])  # [n_blk, tile] f32
    s3 = s[:, None, :]
    w_lo = ((p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, tile) * s3)
    w_hi = ((p >> 4).astype(jnp.float32).reshape(n_blk, 16, tile) * s3)
    w_lo = w_lo.reshape(half_rows, tile).astype(w_dtype)
    w_hi = w_hi.reshape(half_rows, tile).astype(w_dtype)

    # folded -8 offset: 8 * bsum_b @ s  == sum_i x_i * 8 * s_block(i)
    corr = jax.lax.dot_general(
        bsum_t_ref[...], s, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    partial_sum = (
        jnp.dot(x_lo_ref[...], w_lo, preferred_element_type=jnp.float32)
        + jnp.dot(x_hi_ref[...], w_hi, preferred_element_type=jnp.float32)
        - 8.0 * corr
    )

    @pl.when(k == 0)
    def _():
        acc_ref[...] = partial_sum

    @pl.when(k > 0)
    def _():
        acc_ref[...] = acc_ref[...] + partial_sum

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _pick_chunk(d_in: int) -> int | None:
    """Largest divisor of d_in that is a multiple of 32 and <= DIN_CHUNK
    (chunks must cover whole quant blocks). None unless d_in is 32-aligned;
    32 itself always qualifies, so a 32-aligned d_in always gets a chunk."""
    if d_in % 32 != 0:
        return None
    best = 32
    for c in range(64, min(d_in, DIN_CHUNK) + 1, 32):
        if d_in % c == 0:
            best = c
    return best


def _pick_tile(n: int, cap: int) -> int:
    for c in range(cap, 127, -128):
        if n % c == 0:
            return c
    return n


# the dequantized f32 weight tile (chunk x tile) must fit VMEM comfortably
# alongside x, packed, scales, and the accumulator
MAX_W_TILE_BYTES = 8 * 1024 * 1024


def pallas_supports(w: PackedQ40) -> bool:
    """True when the kernel's fitted block shapes are VMEM-safe; otherwise
    callers should take the q40_matmul_xla fallback (ops/linear.py)."""
    if w.packed.ndim != 2:
        return False
    chunk = _pick_chunk(w.d_in)
    if chunk is None:
        return False
    tile = _pick_tile(w.d_out, DOUT_TILE)
    return chunk * tile * 4 <= MAX_W_TILE_BYTES


@partial(jax.jit, static_argnames=("interpret", "w_dtype"))
def q40_matmul_pallas(x: jnp.ndarray, w: PackedQ40, interpret: bool = False,
                      w_dtype=jnp.float32) -> jnp.ndarray:
    """y = x @ dequant(w). x: [..., d_in]; returns [..., d_out] in x.dtype.

    ``w_dtype``: dtype of the in-VMEM dequantized weight planes (f32 exact —
    the default; bf16 trades exactness for VMEM bandwidth, bench ablation
    only)."""
    if w.packed.ndim != 2:
        raise ValueError(f"expected 2D packed weight, got {w.packed.shape}")
    d_in, d_out = w.d_in, w.d_out
    chunk = _pick_chunk(d_in)
    if chunk is None:
        raise ValueError(f"d_in={d_in} not 32-divisible; use q40_matmul_xla")
    lead = x.shape[:-1]
    m = 1
    for s in lead:
        m *= s

    xf = x.reshape(m, d_in).astype(jnp.float32)
    m_pad = max(ROW_ALIGN, ((m + ROW_ALIGN - 1) // ROW_ALIGN) * ROW_ALIGN)
    m_tile = min(M_TILE, m_pad)
    if m_pad % m_tile != 0:
        m_pad = ((m_pad + m_tile - 1) // m_tile) * m_tile
    if m_pad != m:
        xf = jnp.pad(xf, ((0, m_pad - m), (0, 0)))

    # kernel-side layout prep (fused into the surrounding jit; O(m*d_in),
    # negligible next to the weight read): split x's columns into the
    # block-local nibble halves matching the packed planes, and precompute
    # per-quant-block sums for the folded -8 correction. bsum is kept
    # TRANSPOSED [n_blk, m] so its (full-extent) lane dim is m — Pallas
    # lane-dim blocks must be multiples of 128 or the full extent.
    n_blk_total = d_in // 32
    xb = xf.reshape(m_pad, n_blk_total, 2, 16)
    x_lo = xb[:, :, 0, :].reshape(m_pad, d_in // 2)
    x_hi = xb[:, :, 1, :].reshape(m_pad, d_in // 2)
    bsum_t = xf.reshape(m_pad, n_blk_total, 32).sum(axis=2).T

    tile = _pick_tile(d_out, DOUT_TILE)
    grid = (m_pad // m_tile, d_out // tile, d_in // chunk)

    scale_bits = jax.lax.bitcast_convert_type(w.scales, jnp.int16)

    out = pl.pallas_call(
        partial(_q40_matmul_kernel, w_dtype=w_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((m_tile, chunk // 2), lambda i, j, k: (i, k)),
            pl.BlockSpec((m_tile, chunk // 2), lambda i, j, k: (i, k)),
            pl.BlockSpec((chunk // 32, m_tile), lambda i, j, k: (k, i)),
            pl.BlockSpec((chunk // 2, tile), lambda i, j, k: (k, j)),
            pl.BlockSpec((chunk // 32, tile), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((m_tile, tile), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, d_out), x.dtype),
        scratch_shapes=[pltpu.VMEM((m_tile, tile), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad * d_in * d_out,
            bytes_accessed=d_in * d_out // 2 + (d_in // 32) * d_out * 2
            + m_pad * d_in * 4 + m_pad * d_out * x.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x_lo, x_hi, bsum_t, w.packed, scale_bits)

    return out[:m].reshape(*lead, d_out)


# ---------------------------------------------------------------------------
# GSPMD integration: a partitioning rule for the kernel.
#
# Pallas calls are opaque to the SPMD partitioner, so without this a sharded
# forward would have to fall back to XLA dequant (round 1 disabled the kernel
# under any mesh). custom_partitioning teaches XLA to treat the quantized
# matmul like a dot: row-sliced weights (d_out sharded, reference
# sliceRowMatmul src/nn/nn-core.cpp:207-217) run the kernel per shard with a
# sharded output; col-sliced weights (d_in sharded, sliceColMatmul
# :219-230) run it per shard and psum the partial sums — the collective the
# reference realizes as its quantized TCP all-gather + merge_add.
# ---------------------------------------------------------------------------

from jax.experimental.custom_partitioning import custom_partitioning  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def _q40_mm_impl(x, packed, scales, interpret, w_dtype):
    """Single-shard implementation: Pallas when the (local) shapes fit,
    XLA dequant otherwise. Runs unmodified on 1 device; partitioned, each
    shard re-evaluates `pallas_supports` on its local shapes."""
    from ..quants.packed import q40_matmul_xla

    w = PackedQ40(packed=packed, scales=scales)
    if pallas_supports(w):
        return q40_matmul_pallas(x, w, interpret=interpret, w_dtype=w_dtype)
    return q40_matmul_xla(x, w)


def _pad_spec(sharding, rank):
    spec = tuple(sharding.spec) if sharding.spec is not None else ()
    return spec + (None,) * (rank - len(spec))


def _spec_axes(entry):
    if entry is None:
        return set()
    return set(entry) if isinstance(entry, tuple) else {entry}


def _plan(mesh, arg_shapes):
    """(x_spec, packed_spec, scales_spec, out_spec, k_spec) — the canonical
    sharding layout nearest to what the operands arrived with."""
    x_s, p_s, _ = (a.sharding for a in arg_shapes)
    x_rank = len(arg_shapes[0].shape)
    x_spec = _pad_spec(x_s, x_rank)
    p_spec = _pad_spec(p_s, 2)

    k_spec = p_spec[0] if p_spec[0] is not None else x_spec[-1]
    n_spec = p_spec[1]
    if _spec_axes(k_spec) & _spec_axes(n_spec):
        k_spec = None  # conflicting proposal: replicate the contraction
    used = _spec_axes(k_spec) | _spec_axes(n_spec)
    lead = tuple(s if not (_spec_axes(s) & used) else None for s in x_spec[:-1])

    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    return (
        ns(*lead, k_spec),
        ns(k_spec, n_spec),
        ns(k_spec, n_spec),
        ns(*lead, n_spec),
        k_spec,
    )


def _q40_mm_infer_sharding(interpret, w_dtype, mesh, arg_shapes, result_shape):
    del interpret, w_dtype, result_shape
    return _plan(mesh, arg_shapes)[3]


def _q40_mm_partition(interpret, w_dtype, mesh, arg_shapes, result_shape):
    del result_shape
    x_sh, p_sh, s_sh, out_sh, k_spec = _plan(mesh, arg_shapes)

    def lower(x, packed, scales):
        y = _q40_mm_impl(x, packed, scales, interpret, w_dtype)
        if k_spec is not None:
            y = jax.lax.psum(y, k_spec)
        return y

    return mesh, lower, out_sh, (x_sh, p_sh, s_sh)


_q40_mm = custom_partitioning(_q40_mm_impl, static_argnums=(3, 4))
_q40_mm.def_partition(
    partition=_q40_mm_partition,
    infer_sharding_from_operands=_q40_mm_infer_sharding,
    # x [..., (b*32)], packed [(b*16), n], scales [b, n] -> [..., n]:
    # b = quant blocks of the contraction (reduction); the intra-block
    # subfactors must never be split across devices
    sharding_rule="... (b t), (b s) n, b n -> ... n",
    reduction_factors=("b",),
    need_replication_factors=("t", "s"),
    t=32,
    s=16,
)


def q40_matmul_partitioned(x: jnp.ndarray, w: PackedQ40, interpret: bool = False,
                           w_dtype=jnp.float32) -> jnp.ndarray:
    """y = x @ dequant(w), partitionable under GSPMD meshes (TP/EP serving
    keeps dequant-in-matmul, closing round 1's 'Pallas disabled under any
    mesh' gap). Single device: identical to q40_matmul_pallas with XLA
    fallback for unsupported shapes."""
    return _q40_mm(x, w.packed, w.scales, interpret, w_dtype)
