"""Streaming stop-string detector.

Port of EosDetector (src/tokenizer.cpp:614-699): an incremental matcher over
decoded text that holds back bytes which may be the prefix of a stop string,
with left/right padding tolerance, emitting a safe delta for streaming UIs.
"""

from __future__ import annotations

from enum import IntEnum


class EosResult(IntEnum):
    MAYBE_EOS = 0
    EOS = 1
    NOT_EOS = 2


class EosDetector:
    def __init__(self, eos_token_ids: list[int], pieces: list[str], padding_left: int, padding_right: int):
        self.tokens = list(eos_token_ids)
        self.pieces = list(pieces)
        self.padding_left = padding_left
        self.padding_right = padding_right
        self._buffer = ""
        self._eos_pos: int = -1

    def is_eos(self, token_id: int) -> bool:
        return token_id in self.tokens

    def append(self, token_id: int, piece: str | None) -> EosResult:
        if piece is not None:
            self._buffer += piece

        if self.is_eos(token_id):
            self._eos_pos = len(self._buffer)
            return EosResult.EOS
        self._eos_pos = -1

        buffer_pos = len(self._buffer)
        for s, stop in enumerate(self.pieces):
            piece_size = len(stop)
            if buffer_pos > piece_size + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = buffer_pos - lo
                # n <= 0 must be skipped: the reference's `n > pieceSize +
                # paddingRight` is an int/size_t comparison, so negative n
                # wraps and skips the iteration (src/tokenizer.cpp:674)
                if n <= 0 or n > piece_size + self.padding_right:
                    continue
                if n > piece_size:
                    n = piece_size
                if self._buffer[lo : lo + n] == stop[:n]:
                    if n == piece_size:
                        # full stop string found: truncate buffer at its start
                        self._eos_pos = lo
                        self._buffer = self._buffer[:lo]
                        return EosResult.EOS
                    return EosResult.MAYBE_EOS
        return EosResult.NOT_EOS

    def get_delta(self) -> str | None:
        """The emit-safe text accumulated so far (src/tokenizer.cpp:690-695)."""
        if not self._buffer and self._eos_pos <= 0:
            return None
        if self._eos_pos == 0:
            return None
        return self._buffer if self._buffer else None

    def reset(self) -> None:
        self._buffer = ""
