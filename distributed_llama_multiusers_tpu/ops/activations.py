"""Hidden activations (src/nn/nn-cpu-ops.cpp:445-491). Computed in f32."""

from __future__ import annotations

import jax.numpy as jnp


def silu(x: jnp.ndarray) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    return (xf / (1.0 + jnp.exp(-xf))).astype(x.dtype)


def gelu(x: jnp.ndarray) -> jnp.ndarray:
    # tanh approximation, matching gelu_F32 (nn-cpu-ops.cpp:445-451)
    xf = x.astype(jnp.float32)
    return (0.5 * xf * (1.0 + jnp.tanh(0.797884560802865 * xf * (1.0 + 0.044715 * xf * xf)))).astype(x.dtype)
