"""Per-request deadlines: queue-wait timeout and wall-clock generation budget.

Two monotonic clocks per request, both optional and both overridable
per-request (``Request.queue_timeout_s`` / ``Request.budget_s``) on top of
the server-wide :class:`DeadlinePolicy` (``--queue-timeout`` /
``--request-budget``):

- **queue wait** (``submitted_at`` → admission): a request that waited
  longer than its timeout finishes with ``finish_reason="timeout"`` without
  ever claiming a lane. Checked when the scheduler pops it AND by a
  periodic sweep of the waiting queue (``QosQueue.remove_if``), so a
  saturated server — all lanes busy, nothing being popped — still times out
  its backlog instead of holding clients open forever.
- **generation budget** (``admitted_at`` → now): a lane whose request
  exceeded its wall-clock budget finishes with ``finish_reason="timeout"``
  at the next decode-loop iteration and frees the lane for the next queued
  request. With multi-step decode the check lands on horizon boundaries, so
  a budget can overshoot by up to ``multi_step`` tokens' worth of time.

``None`` or ``<= 0`` disables a limit. All helpers are pure functions of
(request, policy, now) so they are trivially testable and the scheduler owns
all state transitions.
"""

from __future__ import annotations

import time
from dataclasses import dataclass


@dataclass(frozen=True)
class DeadlinePolicy:
    """Server-wide deadline defaults; requests override field-by-field."""

    queue_timeout_s: float | None = None
    request_budget_s: float | None = None

    @staticmethod
    def from_args(args) -> "DeadlinePolicy":
        """Build from the CLI surface (--queue-timeout / --request-budget;
        the argparse defaults are 0 = disabled)."""
        return DeadlinePolicy(
            queue_timeout_s=getattr(args, "queue_timeout", 0) or None,
            request_budget_s=getattr(args, "request_budget", 0) or None,
        )

    @property
    def active(self) -> bool:
        return (
            (self.queue_timeout_s or 0) > 0 or (self.request_budget_s or 0) > 0
        )


def _limit(override: float | None, default: float | None) -> float | None:
    v = override if override is not None else default
    if v is None or v <= 0:
        return None
    return float(v)


def queue_timeout_for(req, policy: DeadlinePolicy) -> float | None:
    return _limit(getattr(req, "queue_timeout_s", None), policy.queue_timeout_s)


def budget_for(req, policy: DeadlinePolicy) -> float | None:
    return _limit(getattr(req, "budget_s", None), policy.request_budget_s)


def queue_expired(req, policy: DeadlinePolicy, now: float | None = None) -> bool:
    """Did ``req`` outwait its queue timeout? False when no timeout applies
    or the request was never stamped (direct library use)."""
    limit = queue_timeout_for(req, policy)
    t0 = getattr(req, "submitted_at", None)
    if limit is None or t0 is None:
        return False
    return (now if now is not None else time.monotonic()) - t0 > limit


def budget_expired(req, policy: DeadlinePolicy, now: float | None = None) -> bool:
    """Did ``req`` exceed its wall-clock generation budget? Measured from
    admission (lane claim), not submission — queue wait is governed by the
    queue timeout, not the budget."""
    limit = budget_for(req, policy)
    t0 = getattr(req, "admitted_at", None)
    if limit is None or t0 is None:
        return False
    return (now if now is not None else time.monotonic()) - t0 > limit
