"""dlint v5: the resource-lifecycle surface model.

Every latent-bug family this package has actually shipped — the PR 10
registry-entry-per-shed and journal-mark-per-stream leaks, PR 11's three
rounds of refcount-pin fixes, PR 16's admin thread racing the donated
cache pytree — is a *lifecycle* bug: an acquire whose release (or whose
thread-affinity contract) silently lost an exit path. This module
extracts that lifecycle surface from the AST, exactly as
analysis/lockgraph.py does for locks and analysis/jitmodel.py does for
compiled programs, so the two v5 checks (analysis/resource_check.py) and
the reviewer table (``--resource-table``) all read one model.

A class declares its pairing vocabulary in-source with plain
(non-annotated, so dataclasses ignore them) class attributes beside the
existing ``_dlint_guarded_by``:

    class KVPagePool:
        _dlint_acquires = {"kv-page": ("admit", "adopt")}
        _dlint_releases = {"kv-page": ("finish", "release", "reset")}

    class InferenceEngine:
        _dlint_device_affine = ("apply_paged_admit", "copy_lane", ...)

    class ContinuousBatchingScheduler:
        _dlint_loop_roots = ("_run",)

- ``_dlint_acquires`` / ``_dlint_releases`` — ``{kind: (method, ...)}``:
  calling an acquire method of *kind* takes ownership of one resource of
  that kind; calling a release method (directly or through any wrapper
  that transitively reaches one) gives it back. Method names must be
  distinctive within the package (same name-matching contract as
  guarded-by); declarations are rot-guarded — naming a method the class
  does not define is itself a finding.
- ``_dlint_device_affine`` — methods that touch donated device pytrees;
  legal only from the batching loop or through ``run_device_op`` (the
  device-affinity check owns the legality rules).
- ``_dlint_loop_roots`` — the batching-loop entry points; the set of
  same-class methods reachable from them (via ``self.X()`` calls, to a
  fixpoint) IS the loop-thread closure device-affine calls may live in.

The model is name-based and lexical, no type inference — the same
deliberate trade guarded-by makes: distinctive method names buy a
cross-file analysis that runs on bare CPython in milliseconds.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Finding, Project, SourceFile, last_component

ACQUIRE_DECL_NAME = "_dlint_acquires"
RELEASE_DECL_NAME = "_dlint_releases"
DEVICE_DECL_NAME = "_dlint_device_affine"
LOOP_DECL_NAME = "_dlint_loop_roots"

# the sanctioned cross-thread funnel for device-affine calls
# (runtime/scheduler.py run_device_op); a lambda/def passed as an
# argument to it executes ON the batching loop at a step boundary
DEVICE_FUNNEL = "run_device_op"


@dataclass
class KindDecl:
    """One resource kind's pairing vocabulary, merged across classes
    (kv-page spans KVPagePool and the engine's paged_* façade)."""

    kind: str
    acquires: dict[str, str] = field(default_factory=dict)  # method -> site
    releases: dict[str, str] = field(default_factory=dict)  # method -> site

    @property
    def vocabulary(self) -> frozenset[str]:
        return frozenset(self.acquires) | frozenset(self.releases)


@dataclass
class CallSite:
    """One call expression, recorded with the lexical context the checks
    need: is it inside a closure handed to run_device_op, and which
    try/except arms surround it."""

    name: str  # callee last component
    line: int
    in_funnel_arg: bool  # inside a lambda/def that is an argument to run_device_op
    # Trys whose BODY lexically contains this call, innermost first —
    # the interprocedural excuse asks whether a call site's enclosing
    # try has a releasing handler
    body_trys: tuple[ast.Try, ...] = ()


@dataclass
class RaiseSite:
    line: int
    # the Try whose HANDLER lexically contains this raise (None if the
    # raise is not inside an except arm); Python semantics: a raise in a
    # handler is NOT caught by its own try
    handler_try: ast.Try | None
    # Trys whose BODY contains this raise, innermost first — their
    # handlers will catch it
    body_trys: tuple[ast.Try, ...]


@dataclass
class FuncInfo:
    """One function/method (lambdas fold into their enclosing def — a
    lambda body cannot contain a raise statement or an acquire-with-
    later-raise shape, so per-call funnel flags carry all we need)."""

    path: str  # display path
    name: str
    qual: str  # Class.method or bare function name
    line: int
    cls: str | None
    node: ast.AST
    calls: list[CallSite] = field(default_factory=list)
    self_calls: set[str] = field(default_factory=set)  # self.X(...) callees
    raises: list[RaiseSite] = field(default_factory=list)

    def call_names(self) -> set[str]:
        return {c.name for c in self.calls}


class ResourceModel:
    """The cross-file lifecycle surface, built once per analyzer run by
    whichever v5 checker's collect pass sees a file first."""

    def __init__(self) -> None:
        self.kinds: dict[str, KindDecl] = {}
        # device-affine method -> "Class (path)" declaration site
        self.device_methods: dict[str, str] = {}
        self.device_decl_paths: set[str] = set()  # files that declared them
        # (path, class) -> declared loop-root method names
        self.loop_roots: dict[tuple[str, str], tuple[str, ...]] = {}
        self.functions: list[FuncInfo] = []
        # path -> {class -> set of method names} (proxy-class rule)
        self.class_methods: dict[str, dict[str, set[str]]] = {}
        self.files: dict[str, SourceFile] = {}
        self._seen: set[str] = set()

    # -- convenience views ---------------------------------------------------

    def functions_named(self, name: str) -> list[FuncInfo]:
        return [f for f in self.functions if f.name == name]

    def transitive_releasers(self, kind: str) -> set[str]:
        """Function NAMES that release ``kind`` directly or through any
        chain of same-package wrappers (``_paged_release`` ->
        ``paged_finish`` -> pool ``finish``), to a fixpoint."""
        decl = self.kinds.get(kind)
        if decl is None:
            return set()
        releasers = set(decl.releases)
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn.name in releasers:
                    continue
                if fn.call_names() & releasers:
                    releasers.add(fn.name)
                    changed = True
        return releasers

    def loop_closure(self, path: str, cls: str) -> set[str]:
        """Same-class methods reachable from the declared loop roots via
        ``self.X()`` calls, to a fixpoint — the batching-loop thread's
        call closure, inside which device-affine calls are legal."""
        roots = self.loop_roots.get((path, cls))
        if not roots:
            return set()
        by_name = {
            f.name: f
            for f in self.functions
            if f.path == path and f.cls == cls
        }
        closure = {r for r in roots if r in by_name}
        frontier = list(closure)
        while frontier:
            fn = by_name[frontier.pop()]
            for callee in fn.self_calls:
                if callee in by_name and callee not in closure:
                    closure.add(callee)
                    frontier.append(callee)
        return closure


# -- extraction ---------------------------------------------------------------


def _parse_kind_decl(stmt: ast.Assign) -> dict[str, tuple[str, ...]]:
    decl = ast.literal_eval(stmt.value)
    if not isinstance(decl, dict):
        raise ValueError("declaration must be a dict literal")
    out: dict[str, tuple[str, ...]] = {}
    for kind, methods in decl.items():
        if not isinstance(kind, str) or not kind:
            raise ValueError("kind names must be non-empty strings")
        methods_t = (methods,) if isinstance(methods, str) else tuple(methods)
        if not methods_t or not all(isinstance(m, str) for m in methods_t):
            raise ValueError("method names must be strings")
        out[kind] = methods_t
    return out


def _parse_name_tuple(stmt: ast.Assign) -> tuple[str, ...]:
    decl = ast.literal_eval(stmt.value)
    names = (decl,) if isinstance(decl, str) else tuple(decl)
    if not names or not all(isinstance(n, str) for n in names):
        raise ValueError("expected a tuple of method-name strings")
    return names


def _class_decls(model: ResourceModel, sf: SourceFile, project: Project,
                 node: ast.ClassDef, methods: set[str]) -> None:
    site = f"{node.name} ({sf.display})"
    for stmt in node.body:
        if not (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            continue
        target = stmt.targets[0].id
        if target not in (
            ACQUIRE_DECL_NAME, RELEASE_DECL_NAME,
            DEVICE_DECL_NAME, LOOP_DECL_NAME,
        ):
            continue
        check = (
            "device-affinity"
            if target in (DEVICE_DECL_NAME, LOOP_DECL_NAME)
            else "resource-balance"
        )
        try:
            if target in (ACQUIRE_DECL_NAME, RELEASE_DECL_NAME):
                by_kind = _parse_kind_decl(stmt)
            else:
                names = _parse_name_tuple(stmt)
        except (ValueError, TypeError, SyntaxError) as e:
            project.collect_findings.append(Finding(
                check, sf.display, stmt.lineno,
                f"malformed {target} on class {node.name}: {e}",
            ))
            continue
        if target in (ACQUIRE_DECL_NAME, RELEASE_DECL_NAME):
            for kind, names in by_kind.items():
                decl = model.kinds.setdefault(kind, KindDecl(kind))
                bucket = (
                    decl.acquires
                    if target == ACQUIRE_DECL_NAME
                    else decl.releases
                )
                for name in names:
                    if name not in methods:
                        # rot-guard: a declaration naming a method the
                        # class does not define is stale the moment it
                        # is written
                        project.collect_findings.append(Finding(
                            check, sf.display, stmt.lineno,
                            f"{target} on class {node.name} names "
                            f"{name!r}, which {node.name} does not "
                            "define",
                        ))
                        continue
                    bucket[name] = site
        elif target == DEVICE_DECL_NAME:
            for name in names:
                if name not in methods:
                    project.collect_findings.append(Finding(
                        check, sf.display, stmt.lineno,
                        f"{target} on class {node.name} names {name!r}, "
                        f"which {node.name} does not define",
                    ))
                    continue
                model.device_methods[name] = site
                model.device_decl_paths.add(sf.display)
        else:  # LOOP_DECL_NAME
            missing = [n for n in names if n not in methods]
            for name in missing:
                project.collect_findings.append(Finding(
                    check, sf.display, stmt.lineno,
                    f"{target} on class {node.name} names {name!r}, "
                    f"which {node.name} does not define",
                ))
            kept = tuple(n for n in names if n in methods)
            if kept:
                model.loop_roots[(sf.display, node.name)] = kept


def _funnel_names(fn_node: ast.AST) -> set[str]:
    """Names that alias run_device_op inside one function: the funnel
    itself, plus locals assigned from ``X.run_device_op`` or
    ``getattr(X, "run_device_op", ...)`` (the duck-typed dispatch the
    HTTP layer uses)."""
    names = {DEVICE_FUNNEL}
    for node in ast.walk(fn_node):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            continue
        v = node.value
        aliased = (
            isinstance(v, ast.Attribute) and v.attr == DEVICE_FUNNEL
        ) or (
            isinstance(v, ast.Call)
            and last_component(v.func) == "getattr"
            and any(
                isinstance(a, ast.Constant) and a.value == DEVICE_FUNNEL
                for a in v.args
            )
        )
        if aliased:
            names.add(node.targets[0].id)
    return names


def _extract_functions(model: ResourceModel, sf: SourceFile) -> None:
    """One pass with ancestor context: every def becomes a FuncInfo whose
    calls/raises carry the try/funnel context the checks consume."""
    stack: list[FuncInfo] = []
    class_stack: list[str] = []
    # per-FuncInfo funnel-alias set, computed lazily on entry
    funnels: list[set[str]] = []

    def rec(node: ast.AST, anc: list[ast.AST]) -> None:
        is_def = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        if isinstance(node, ast.ClassDef):
            class_stack.append(node.name)
        if is_def:
            cls = class_stack[-1] if class_stack else None
            # nested defs qualify under their own name only — calls in a
            # nested def still attribute to it, not the outer function
            qual = f"{cls}.{node.name}" if cls else node.name
            info = FuncInfo(
                path=sf.display, name=node.name, qual=qual,
                line=node.lineno, cls=cls, node=node,
            )
            model.functions.append(info)
            stack.append(info)
            funnels.append(_funnel_names(node))
        elif stack:
            info = stack[-1]
            if isinstance(node, ast.Call):
                name = last_component(node.func)
                if name is not None:
                    in_funnel = _inside_funnel_arg(anc, funnels[-1])
                    _, body_trys = _try_context(anc, node)
                    info.calls.append(CallSite(
                        name, node.lineno, in_funnel, body_trys,
                    ))
                    if (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                    ):
                        info.self_calls.add(name)
            elif isinstance(node, ast.Raise):
                handler_try, body_trys = _try_context(anc, node)
                info.raises.append(RaiseSite(
                    node.lineno, handler_try, body_trys,
                ))
        anc.append(node)
        for c in ast.iter_child_nodes(node):
            rec(c, anc)
        anc.pop()
        if is_def:
            stack.pop()
            funnels.pop()
        if isinstance(node, ast.ClassDef):
            class_stack.pop()

    rec(sf.tree, [])


def _try_context(
    anc: list[ast.AST], node: ast.AST
) -> tuple[ast.Try | None, tuple[ast.Try, ...]]:
    """(try whose HANDLER contains node, trys whose BODY contains node)
    — scanning outward to the function boundary. Python semantics drive
    the split: only body-trys' handlers will catch an exception leaving
    ``node``; a handler's own try will not."""
    handler_try: ast.Try | None = None
    body_trys: list[ast.Try] = []
    child: ast.AST = node
    for a in reversed(anc):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            break
        if isinstance(a, ast.ExceptHandler) and handler_try is None:
            for outer in anc:
                if isinstance(outer, ast.Try) and a in outer.handlers:
                    handler_try = outer
                    break
        elif isinstance(a, ast.Try) and any(child is s for s in a.body):
            body_trys.append(a)
        child = a
    return handler_try, tuple(body_trys)


def _inside_funnel_arg(anc: list[ast.AST], funnel_names: set[str]) -> bool:
    """True when some ancestor closure (lambda or nested def name) is an
    argument to a run_device_op(-aliased) call."""
    for i, a in enumerate(anc):
        if isinstance(a, ast.Lambda):
            parent = anc[i - 1] if i else None
            if (
                isinstance(parent, ast.Call)
                and a in parent.args
                and last_component(parent.func) in funnel_names
            ):
                return True
    return False


def ingest_file(model: ResourceModel, sf: SourceFile,
                project: Project) -> None:
    """Idempotent per-file extraction — both v5 checkers call this from
    collect; the first call per file does the work."""
    if sf.display in model._seen:
        return
    model._seen.add(sf.display)
    model.files[sf.display] = sf
    per_class = model.class_methods.setdefault(sf.display, {})
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef):
            methods = {
                s.name
                for s in node.body
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            per_class[node.name] = methods
            _class_decls(model, sf, project, node, methods)
    _extract_functions(model, sf)


def project_model(project: Project) -> ResourceModel:
    model = getattr(project, "resource_model", None)
    if model is None:
        model = ResourceModel()
        project.resource_model = model
    return model


# -- reviewer surfaces --------------------------------------------------------


def build_model(paths) -> ResourceModel:
    """Standalone model over ``paths`` (the CLI table / DOT dump and the
    rot-guard tests — no analyzer run needed)."""
    from .core import iter_py_files, parse_waivers

    model = ResourceModel()
    project = Project()
    project.resource_model = model
    for p in iter_py_files(paths):
        try:
            text = p.read_text(encoding="utf-8")
            tree = ast.parse(text, filename=str(p))
        except (OSError, SyntaxError, ValueError):
            continue
        sf = SourceFile(path=p, display=p.name, text=text, tree=tree)
        sf.waivers, _ = parse_waivers(text, {"resource-balance",
                                             "device-affinity"}, sf.display)
        ingest_file(model, sf, project)
    return model


def resource_dot(model: ResourceModel) -> str:
    """DOT resource-flow graph: acquire -> kind -> release edges per
    declared vocabulary; waived transfers (functions carrying an
    ``ok[resource-balance]`` waiver) attach dashed."""
    lines = [
        "digraph resources {",
        '  rankdir=LR; node [shape=box, fontsize=10];',
    ]
    for kind in sorted(model.kinds):
        decl = model.kinds[kind]
        knode = f'"[{kind}]"'
        lines.append(f'  {knode} [shape=ellipse, style=bold];')
        for name in sorted(decl.acquires):
            lines.append(f'  "{name}" -> {knode} [label="acquire"];')
        for name in sorted(decl.releases):
            lines.append(f'  {knode} -> "{name}" [label="release"];')
    # dashed edges: intentional transfers waived in-source
    for sf in model.files.values():
        for line_no, waiver in sorted(sf.waivers.items()):
            if not waiver.covers("resource-balance"):
                continue
            # attribute the waiver to the last function starting at or
            # before its line (lexical owner)
            owner = None
            for fn in model.functions:
                if fn.path != sf.display or fn.line > line_no:
                    continue
                if owner is None or fn.line > owner.line:
                    owner = fn
            label = waiver.reason.replace('"', "'")[:40]
            src = f'"{owner.qual}"' if owner else f'"{sf.display}:{line_no}"'
            lines.append(
                f'  {src} -> "transfer" [style=dashed, label="{label}"];'
            )
    lines.append("}")
    return "\n".join(lines)
