"""Training mode: next-token LM training on the exact model stack the
serving engine runs, with optax optimizers and orbax checkpoint/resume.

Beyond-parity subsystem: the reference is inference-only and persists no
state whatsoever (SURVEY.md §5.4 — "KV-cache state is never persisted",
src/llm.cpp; there is no trainer, optimizer, or checkpoint format in
LatadosUnited/distributed-llama-MultiUsers at all). Here the train->
save->resume->serve loop is first-class: checkpoints restore into
``LlamaParams``, which ``InferenceEngine`` consumes directly, and the
forward is ``llama_forward_train`` — bit-identical layer math to the
serving path, sharded over the same GSPMD mesh axes (dp/tp/sp/ep).

Checkpoints are orbax PyTree checkpoints (the TPU-native format: async-
capable, sharding-aware, multi-host-safe): one atomic checkpoint
``<dir>/step_<N>`` holding ``{params, opt_state}``.
"""

from __future__ import annotations

import os
import re
from functools import partial

import jax
import jax.numpy as jnp

from ..models.config import LlamaConfig
from ..models.llama import LlamaParams, llama_forward_train


def next_token_loss(config: LlamaConfig, params: LlamaParams,
                    tokens: jnp.ndarray, mesh=None) -> jnp.ndarray:
    """Mean causal cross-entropy of predicting tokens[:, 1:] from
    tokens[:, :-1] (the standard LM objective). tokens: [B, T] int32."""
    logits = llama_forward_train(config, params, tokens[:, :-1], mesh=mesh)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_train_step(config: LlamaConfig, optimizer, mesh=None):
    """Compiled (params, opt_state, tokens) -> (params, opt_state, loss).
    ``optimizer`` is any optax GradientTransformation; with ``mesh`` the
    step runs under the same GSPMD shardings as the serving engine (the
    caller shards params; grads/updates inherit the layout)."""

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(config, p, tokens, mesh=mesh)
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return train_step


_STEP_RE = re.compile(r"^step_(\d+)$")


class Trainer:
    """Minimal stateful wrapper: params + opt_state + step counter, one
    ``step(tokens)`` call per batch, ``save``/``restore`` for exact resume.

    Resume exactness contract (pinned by tests/test_training.py): N steps
    straight and k steps + save + restore + (N-k) steps produce identical
    parameters — the checkpoint round-trips f32 bit-exactly and the
    compiled step is deterministic."""

    def __init__(self, config: LlamaConfig, params: LlamaParams, optimizer,
                 mesh=None, step: int = 0):
        self.config = config
        self.optimizer = optimizer
        self.mesh = mesh
        self.params = params
        self.opt_state = optimizer.init(params)
        self.step_count = step
        self._train_step = make_train_step(config, optimizer, mesh=mesh)

    def step(self, tokens) -> float:
        """One optimizer step on a [B, T] int32 batch; returns the loss."""
        self.params, self.opt_state, loss = self._train_step(
            self.params, self.opt_state, jnp.asarray(tokens, jnp.int32)
        )
        self.step_count += 1
        return float(loss)

    # -- checkpoint/resume --------------------------------------------------

    def save(self, ckpt_dir: str) -> str:
        """Write ``<ckpt_dir>/step_<N>`` as ONE orbax PyTree checkpoint
        holding {params, opt_state}; returns the step directory. A single
        checkpoint is atomic (orbax stages to a tmp dir and renames), so a
        kill mid-save can never leave a half-written step_<N> that
        ``latest_step`` would pick and brick resume on."""
        import orbax.checkpoint as ocp

        step_dir = os.path.join(os.path.abspath(ckpt_dir), f"step_{self.step_count}")
        ckpt = ocp.PyTreeCheckpointer()
        # force: re-saving the same step (a rerun over an old directory)
        # replaces instead of raising
        ckpt.save(
            step_dir,
            {"params": self.params, "opt_state": self.opt_state},
            force=True,
        )
        return step_dir

    @staticmethod
    def latest_step(ckpt_dir: str) -> int | None:
        steps = [
            int(m.group(1))
            for d in (os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else [])
            if (m := _STEP_RE.match(d))
        ]
        return max(steps) if steps else None

    def restore(self, ckpt_dir: str, step: int | None = None) -> "Trainer":
        """Load params/opt_state from ``<ckpt_dir>/step_<N>`` (latest by
        default) into this trainer. The trainer's own current pytrees are
        the restore templates, so structures (NamedTuples, optax states)
        come back exactly — not dict-ified."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step(ckpt_dir)
            if step is None:
                raise FileNotFoundError(f"no step_<N> checkpoints in {ckpt_dir}")
        step_dir = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
        ckpt = ocp.PyTreeCheckpointer()

        def match_placement(restored, template):
            if self.mesh is None:
                # single-device: everything restores onto the one device
                # anyway; skip the (full-model) host round-trip below
                return restored
            # orbax returns leaves COMMITTED to device 0 when the template
            # carried no mesh sharding (optax scalar counters), which
            # conflicts with mesh-sharded neighbors inside one jit. Mesh-
            # sharded templates get their layout back via device_put;
            # single-device templates (uncommitted by construction —
            # optimizer.init output) get an uncommitted host round-trip so
            # jit may place them wherever the computation runs.
            import numpy as np
            from jax.sharding import SingleDeviceSharding

            def put(r, t):
                sh = getattr(t, "sharding", None)
                if sh is None or isinstance(sh, SingleDeviceSharding):
                    return jnp.asarray(np.asarray(r))
                return jax.device_put(r, sh)

            return jax.tree.map(put, restored, template)

        # restore_args carry the templates' shardings, so a mesh-sharded
        # trainer resumes straight into its GSPMD layout (and the
        # "populating sharding from file" warning never applies)
        if os.path.isdir(os.path.join(step_dir, "params")):
            # legacy two-checkpoint layout (step_<N>/{params,opt_state}):
            # readable forever; new saves always write the atomic layout
            def load(name, template):
                return ckpt.restore(
                    os.path.join(step_dir, name),
                    item=template,
                    restore_args=ocp.checkpoint_utils.construct_restore_args(
                        template
                    ),
                )

            self.params = match_placement(load("params", self.params), self.params)
            self.opt_state = match_placement(
                load("opt_state", self.opt_state), self.opt_state
            )
        else:
            template = {"params": self.params, "opt_state": self.opt_state}
            restored = ckpt.restore(
                step_dir,
                item=template,
                restore_args=ocp.checkpoint_utils.construct_restore_args(template),
            )
            self.params = match_placement(restored["params"], self.params)
            self.opt_state = match_placement(
                restored["opt_state"], self.opt_state
            )
        self.step_count = step
        return self
