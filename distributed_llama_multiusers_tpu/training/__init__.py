from .trainer import Trainer, make_train_step, next_token_loss

__all__ = ["Trainer", "make_train_step", "next_token_loss"]
