"""Serving QoS subsystem (ISSUE 1): bounded admission, priority classes,
per-user deficit-round-robin fair share, queue-wait/budget deadlines, and
graceful drain — the admission layer production continuous-batching servers
pair with the batching loop (Orca/vLLM-style), which the reference fork's
bare FIFO lacks entirely.

Unit tests exercise the queue/deadline logic directly; integration tests run
the real scheduler over a tiny synthetic model with a single lane so lane
saturation and reuse are deterministic.
"""

import contextlib
import json
import threading
import time
import urllib.error
import urllib.request

import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.server import ApiServer
from distributed_llama_multiusers_tpu.serving import (
    AdmissionRejected,
    DeadlinePolicy,
    Priority,
    QosQueue,
    budget_expired,
    queue_expired,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer


# ---------------------------------------------------------------------------
# queue unit tests (no model)
# ---------------------------------------------------------------------------


def _req(user="", prio=Priority.NORMAL, max_tokens=4, prompt="x"):
    return Request(prompt=prompt, user_id=user, priority=prio, max_tokens=max_tokens)


def test_capacity_bound_rejects_with_typed_error():
    q = QosQueue(capacity=2)
    q.push(_req())
    q.push(_req())
    with pytest.raises(AdmissionRejected) as ei:
        q.push(_req())
    e = ei.value
    assert e.reason == "queue_full"
    assert e.http_status == 429
    assert e.capacity == 2 and e.queue_depth == 2
    assert e.retry_after_s >= 1.0
    assert q.stats()["queue_rejected_full"] == 1
    assert q.depth() == 2  # the shed request never entered


def test_priority_classes_strict_order():
    q = QosQueue()
    q.push(_req(user="u1", prio=Priority.LOW))
    q.push(_req(user="u2", prio=Priority.NORMAL))
    q.push(_req(user="u3", prio=Priority.HIGH))
    assert [q.pop(timeout=0).priority for _ in range(3)] == [
        Priority.HIGH, Priority.NORMAL, Priority.LOW,
    ]
    assert q.pop(timeout=0) is None


def test_drr_interleaves_unequal_bursts():
    """One user's burst of 10 must not starve another user's 2: pops
    alternate between users within a priority class."""
    q = QosQueue()
    for _ in range(10):
        q.push(_req(user="heavy"))
    for _ in range(2):
        q.push(_req(user="light"))
    order = [q.pop(timeout=0).user_id for _ in range(12)]
    light_at = [i for i, u in enumerate(order) if u == "light"]
    assert light_at[0] <= 2 and light_at[1] <= 4, order


def test_drr_deficit_gates_large_requests():
    """A request costing several quanta waits for its user's credit to
    accumulate while cheap requests from other users keep flowing."""
    q = QosQueue(quantum=128)
    q.push(_req(user="big", max_tokens=512))
    for _ in range(6):
        q.push(_req(user="small", max_tokens=4))
    order = [q.pop(timeout=0).user_id for _ in range(7)]
    # big needs ceil(512/128) = 4 rotation visits of credit
    assert order[:3] == ["small"] * 3, order
    assert "big" in order[3:5], order


def test_drr_huge_cost_pops_in_constant_time():
    """Credit for a many-quanta request is advanced arithmetically, not one
    quantum per loop iteration under the queue lock: a single request with
    an absurd max_tokens must not stall every push/stats caller for
    cost/quantum iterations."""
    q = QosQueue(quantum=128)
    q.push(_req(user="whale", max_tokens=10**12))
    q.push(_req(user="minnow", max_tokens=4))
    t0 = time.monotonic()
    order = [q.pop(timeout=0).user_id for _ in range(2)]
    assert time.monotonic() - t0 < 1.0  # was ~minutes when spinning
    assert sorted(order) == ["minnow", "whale"]
    assert q.empty()


def test_priority_parse():
    assert Priority.parse("high") == Priority.HIGH
    assert Priority.parse("Normal") == Priority.NORMAL
    assert Priority.parse(2) == Priority.LOW
    with pytest.raises(ValueError):
        Priority.parse("urgent")


def test_remove_if_and_drain():
    q = QosQueue()
    rs = [_req(user=f"u{i % 2}") for i in range(6)]
    for r in rs:
        q.push(r)
    removed = q.remove_if(lambda r: r.user_id == "u0")
    assert len(removed) == 3 and q.depth() == 3
    rest = q.drain()
    assert len(rest) == 3 and q.empty() and q.depth() == 0
    # drained requests count as removed: the reconciliation invariant
    # (admitted = popped + removed + depth) survives a stop()/start() cycle
    s = q.stats()
    assert s["queue_removed"] == 6
    assert s["queue_admitted"] == s["queue_popped"] + s["queue_removed"] + s["queue_depth"]


def test_retry_after_reflects_stuck_backlog():
    """During full saturation nothing pops, so the Retry-After hint must
    come from the age of the oldest waiter, not the (empty or stale)
    recent-pop average — else 429s tell clients to hammer a stuck server."""
    q = QosQueue(capacity=2)
    old = _req(user="a")
    old.submitted_at = time.monotonic() - 7.5  # has waited ~7.5s already
    q.push(old)
    q.push(_req(user="b"))
    with pytest.raises(AdmissionRejected) as ei:
        q.push(_req(user="c"))
    assert ei.value.retry_after_s >= 7.0
    # sweeping the backlog is accounted: admitted = popped + removed + depth
    q.remove_if(lambda r: True)
    s = q.stats()
    assert s["queue_removed"] == 2
    assert s["queue_admitted"] == s["queue_popped"] + s["queue_removed"] + s["queue_depth"]


def test_plain_fifo_remove_if():
    """RequestQueue (reference-parity FIFO) supports the same targeted
    removal as QosQueue: the deadline sweep and the submit()/drain() race
    shed depend on it regardless of which queue the scheduler runs."""
    from distributed_llama_multiusers_tpu.runtime.scheduler import RequestQueue

    q = RequestQueue()
    rs = [_req(user=f"u{i % 2}") for i in range(4)]
    for r in rs:
        q.push(r)
    removed = q.remove_if(lambda r: r.user_id == "u0")
    assert removed == [rs[0], rs[2]]
    assert [q.pop(timeout=0) for _ in range(2)] == [rs[1], rs[3]]
    assert q.pop(timeout=0) is None


def test_pop_blocks_until_push():
    q = QosQueue()
    got = {}

    def consumer():
        got["req"] = q.pop(timeout=5)

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)  # consumer is parked on the condition, not spinning
    r = _req()
    q.push(r)
    t.join(timeout=5)
    assert got["req"] is r
    assert q.stats()["queue_wait_avg_s"] >= 0.0


# ---------------------------------------------------------------------------
# deadline unit tests
# ---------------------------------------------------------------------------


def test_deadline_policy_and_overrides():
    pol = DeadlinePolicy(queue_timeout_s=1.0, request_budget_s=2.0)
    r = _req()
    r.submitted_at = 100.0
    assert not queue_expired(r, pol, now=100.5)
    assert queue_expired(r, pol, now=101.5)
    r.admitted_at = 101.0
    assert not budget_expired(r, pol, now=102.5)
    assert budget_expired(r, pol, now=103.5)
    # per-request override beats the policy; <= 0 disables
    r.queue_timeout_s = 10.0
    assert not queue_expired(r, pol, now=105.0)
    r.budget_s = 0
    assert not budget_expired(r, pol, now=1000.0)
    # no policy, no overrides -> nothing ever expires
    off = DeadlinePolicy()
    assert not off.active
    fresh = _req()
    fresh.submitted_at = fresh.admitted_at = 0.0
    assert not queue_expired(fresh, off, now=1e9)
    assert not budget_expired(fresh, off, now=1e9)


# ---------------------------------------------------------------------------
# EngineStats snapshot (satellite: /stats reads one consistent copy)
# ---------------------------------------------------------------------------


def test_engine_stats_snapshot_is_consistent():
    from distributed_llama_multiusers_tpu.runtime.engine import EngineStats

    s = EngineStats()
    stop = threading.Event()

    def bump():
        while not stop.is_set():
            with s.lock:  # writers bump related fields under the lock
                s.decode_steps += 1
                s.multi_dispatches += 1

    t = threading.Thread(target=bump, daemon=True)
    t.start()
    try:
        for _ in range(300):
            snap = s.snapshot()
            # a field-by-field read could see the pair mid-update
            assert snap["decode_steps"] == snap["multi_dispatches"]
            assert "lock" not in snap
    finally:
        stop.set()
        t.join(timeout=5)
    reset_snap = s.reset()
    assert reset_snap.decode_steps == reset_snap.multi_dispatches
    assert s.decode_steps == 0


# ---------------------------------------------------------------------------
# scheduler integration (tiny model, ONE lane: saturation is deterministic)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def stack(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    engine = InferenceEngine(config, params, n_lanes=1, prefill_buckets=(8,))
    return config, engine, tok


def make_sched(engine, tok, **kw):
    # plain single-step decode: the slow_decode hold below must cover every
    # decode path, and speculation/multi-step/pipelining are covered
    # elsewhere (the pipelined path would dispatch around the wrapped
    # engine.decode and break the hold)
    return ContinuousBatchingScheduler(
        engine, tok, speculative=False, multi_step=0, pipelined=False, **kw
    )


@contextlib.contextmanager
def slow_decode(engine, delay: float):
    """Stretch each decode step so a 'blocker' request holds its lane for a
    test-controllable window (the tiny model otherwise decodes in ~ms)."""
    real = engine.decode

    def slowed(*a, **k):
        time.sleep(delay)
        return real(*a, **k)

    engine.decode = slowed
    try:
        yield
    finally:
        engine.decode = real


def _wait_generating(req, timeout=60):
    deadline = time.monotonic() + timeout
    while req.state.name != "GENERATING":
        assert time.monotonic() < deadline, f"stuck in {req.state}"
        assert not req.future.done(), req.error
        time.sleep(0.005)


def test_overflow_rejected_then_backlog_served(stack):
    """Lanes saturated + queue at capacity -> AdmissionRejected; freeing the
    lane serves the backlog (bounded admission sheds, never corrupts)."""
    config, engine, tok = stack
    sched = make_sched(engine, tok, queue_=QosQueue(capacity=2))
    sched.start()
    try:
        with slow_decode(engine, 0.05):
            blocker = sched.submit(Request(prompt="hello", max_tokens=1000))
            _wait_generating(blocker)
            q1 = sched.submit(Request(prompt="hello", max_tokens=2))
            q2 = sched.submit(Request(prompt="hello", max_tokens=2))
            with pytest.raises(AdmissionRejected) as ei:
                sched.submit(Request(prompt="hello", max_tokens=2))
            assert ei.value.reason == "queue_full"
            assert ei.value.http_status == 429
            blocker.cancel()
        assert isinstance(q1.future.result(timeout=120), str)
        assert isinstance(q2.future.result(timeout=120), str)
        assert blocker.future.result(timeout=120) is not None
        assert blocker.finish_reason == "cancelled"
        assert sched.qos_stats()["queue_rejected_full"] == 1
    finally:
        sched.stop()


def test_budget_expiry_finishes_timeout_and_lane_is_reused(stack):
    config, engine, tok = stack
    sched = make_sched(
        engine, tok, deadlines=DeadlinePolicy(request_budget_s=0.2)
    )
    sched.start()
    try:
        with slow_decode(engine, 0.05):
            r = sched.submit(Request(prompt="hello", max_tokens=1000))
            r.future.result(timeout=120)
        assert r.finish_reason == "timeout"
        # ~0.2s / 0.05s-per-step: nowhere near max_tokens or seq_len
        assert len(r.generated_tokens) < 30
        assert sched.budget_timeouts >= 1
        # the expired request freed its lane: the next request runs clean
        nxt = sched.submit(Request(prompt="hello", max_tokens=2))
        nxt.future.result(timeout=120)
        assert nxt.finish_reason in ("stop", "length")
        assert len(nxt.generated_tokens) >= 1
    finally:
        sched.stop()


def test_per_request_budget_override(stack):
    config, engine, tok = stack
    sched = make_sched(engine, tok)  # no policy: the request brings its own
    sched.start()
    try:
        with slow_decode(engine, 0.05):
            r = sched.submit(
                Request(prompt="hello", max_tokens=1000, budget_s=0.2)
            )
            r.future.result(timeout=120)
        assert r.finish_reason == "timeout"
    finally:
        sched.stop()


def test_queue_wait_timeout_fires_while_saturated(stack):
    """The deadline sweep resolves queued requests even though the lane
    never frees (nothing is ever popped) — no client held open forever."""
    config, engine, tok = stack
    sched = make_sched(
        engine, tok, deadlines=DeadlinePolicy(queue_timeout_s=0.2)
    )
    sched.start()
    try:
        with slow_decode(engine, 0.05):
            blocker = sched.submit(Request(prompt="hello", max_tokens=1000))
            _wait_generating(blocker)
            waiter = sched.submit(Request(prompt="hello", max_tokens=2))
            waiter.future.result(timeout=30)
            assert waiter.finish_reason == "timeout"
            assert waiter.generated_tokens == []
            assert not blocker.future.done()  # lane genuinely stayed busy
            blocker.cancel()
        blocker.future.result(timeout=120)
        assert sched.queue_timeouts >= 1
    finally:
        sched.stop()


def test_fair_share_interleaving_no_starvation(stack):
    """Two users, unequal bursts, one lane: completions interleave instead
    of the heavy user's burst running to completion first."""
    config, engine, tok = stack
    sched = make_sched(engine, tok)
    sched.start()
    done_order = []
    order_lock = threading.Lock()

    def track(req):
        def on_done(_f):
            with order_lock:
                done_order.append(req.user_id)

        req.future.add_done_callback(on_done)
        return req

    try:
        with slow_decode(engine, 0.01):
            blocker = sched.submit(
                Request(prompt="hello", max_tokens=8, user_id="warm")
            )
            _wait_generating(blocker)  # burst below queues as one batch
            heavy = [
                track(sched.submit(
                    Request(prompt="hello", max_tokens=2, user_id="alice")
                ))
                for _ in range(6)
            ]
            light = [
                track(sched.submit(
                    Request(prompt="hello", max_tokens=2, user_id="bob")
                ))
                for _ in range(2)
            ]
        for r in heavy + light:
            r.future.result(timeout=120)
        bob_at = [i for i, u in enumerate(done_order) if u == "bob"]
        assert bob_at[0] <= 2 and bob_at[1] <= 4, done_order
    finally:
        sched.stop()


def test_rejected_submit_keeps_no_stale_stamp(stack):
    """A shed request keeps no submitted_at: its queue-timeout clock must
    start when it actually enters the queue, not at the first (rejected)
    attempt — else a retry after backoff is judged instantly expired."""
    config, engine, tok = stack
    sched = make_sched(engine, tok, queue_=QosQueue(capacity=1))
    # loop not started: nothing pops, so capacity=1 fills deterministically
    first = sched.submit(Request(prompt="x", max_tokens=2))
    rej = Request(prompt="y", max_tokens=2)
    with pytest.raises(AdmissionRejected):
        sched.submit(rej)
    assert rej.submitted_at is None
    assert sched.queue.pop(timeout=0) is first  # backlog clears
    sched.submit(rej)  # the same object resubmits cleanly
    assert rej.submitted_at is not None
    assert sched.queue.pop(timeout=0) is rej


def test_drain_resolves_all_futures_then_sheds(stack):
    config, engine, tok = stack
    sched = make_sched(engine, tok)
    sched.start()
    reqs = [
        sched.submit(Request(prompt="hello", max_tokens=3)) for _ in range(3)
    ]
    assert sched.drain(timeout=120) is True
    for r in reqs:
        assert r.future.done()
        assert r.finish_reason in ("stop", "length")  # served, not cancelled
    assert sched.draining
    with pytest.raises(AdmissionRejected) as ei:
        sched.submit(Request(prompt="late"))
    assert ei.value.reason == "draining" and ei.value.http_status == 503
    stats = sched.qos_stats()
    assert stats["draining"] is True
    assert stats["queue_rejected_draining"] == 1
    sched.stop()  # idempotent after a clean drain
    # restartable: a drained scheduler can come back up
    sched.start()
    assert not sched.draining
    r = sched.submit(Request(prompt="hello", max_tokens=2))
    r.future.result(timeout=120)
    sched.stop()


def test_drain_race_loop_tail_sheds_503_not_500(stack):
    """A submit() that passes the pre-push shed check can land its push after
    the draining loop took its exit snapshot; the loop-tail flush must shed
    it with the retryable AdmissionRejected("draining") (the HTTP layer's
    503 + Retry-After, same shape submit() sheds with) — not "scheduler
    stopped" (an HTTP 500 mid rolling-restart), and not an empty 200 the
    client would mistake for the model's answer. Reproduced deterministically
    by running the loop tail inline with the racing request already queued."""
    config, engine, tok = stack
    sched = make_sched(engine, tok)
    racer = Request(prompt="hello", max_tokens=2)
    sched.queue.push(racer)  # the push that slipped past the exit snapshot
    sched._draining.set()
    sched._stop.set()  # loop body never runs: straight to the tail flush
    sched._run()
    assert racer.future.done()
    with pytest.raises(AdmissionRejected) as ei:
        racer.future.result(timeout=1)
    assert ei.value.reason == "draining" and ei.value.http_status == 503
    # emergency stop (no drain) keeps the hard-failure contract
    sched2 = make_sched(engine, tok)
    orphan = Request(prompt="hello", max_tokens=2)
    sched2.queue.push(orphan)
    sched2._stop.set()
    sched2._run()
    with pytest.raises(RuntimeError, match="scheduler stopped"):
        orphan.future.result(timeout=1)


def test_drain_timeout_force_cancels_but_resolves(stack):
    config, engine, tok = stack
    sched = make_sched(engine, tok)
    sched.start()
    with slow_decode(engine, 0.05):
        blocker = sched.submit(Request(prompt="hello", max_tokens=1000))
        _wait_generating(blocker)
        assert sched.drain(timeout=0.2) is False  # blocker outlives window
    assert blocker.future.done()  # force-cancelled, future still resolves
    assert blocker.finish_reason == "cancelled"


def test_client_disconnect_cancels_and_scheduler_moves_on(stack):
    """Satellite: BrokenPipe during streaming -> req.cancel() frees the lane
    and the scheduler admits the next queued request."""
    config, engine, tok = stack
    sched = make_sched(engine, tok)
    sched.start()
    api = ApiServer(sched, tok, model_name="tiny-qos")
    body = {"prompt": "hello world", "max_tokens": 1000, "stream": True}
    prepared = api.build_completion_request(body, streaming=True)
    req1, _deltas = prepared
    caught = {}

    def broken_pipe(_payload, event_id=None):
        raise BrokenPipeError("client went away")

    def run():
        try:
            api.handle_completion(body, send_chunk=broken_pipe, prepared=prepared)
        except BrokenPipeError as e:
            caught["e"] = e

    try:
        with slow_decode(engine, 0.02):
            t = threading.Thread(target=run)
            t.start()
            _wait_generating(req1)
            req2 = sched.submit(Request(prompt="hello", max_tokens=2))
            t.join(timeout=60)
        assert isinstance(caught.get("e"), BrokenPipeError)
        assert req1._cancelled.is_set()
        req1.future.result(timeout=120)
        assert req1.finish_reason == "cancelled"
        # the freed lane admitted the queued request
        req2.future.result(timeout=120)
        assert req2.finish_reason in ("stop", "length")
    finally:
        sched.stop()


# ---------------------------------------------------------------------------
# HTTP integration: 429/503 + Retry-After, /health flip, /stats counters
# ---------------------------------------------------------------------------


def _post(url, body, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_http_qos_surface(stack):
    config, engine, tok = stack
    sched = make_sched(engine, tok, queue_=QosQueue(capacity=1))
    sched.start()
    api = ApiServer(sched, tok, model_name="qos-test")
    httpd = api.serve(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    results = {}

    def post_async(key, body):
        def run():
            try:
                results[key] = _post(base + "/v1/completions", body)
            except urllib.error.HTTPError as e:
                results[key] = (e.code, json.loads(e.read()))

        t = threading.Thread(target=run)
        t.start()
        return t

    def poll_stats(pred, timeout=30):
        deadline = time.monotonic() + timeout
        while True:
            _, stats = _get(base + "/stats")
            if pred(stats):
                return stats
            assert time.monotonic() < deadline, stats
            time.sleep(0.02)

    try:
        assert _get(base + "/health")[0] == 200
        with slow_decode(engine, 0.05):
            t1 = post_async("blocker", {
                "prompt": "hello", "max_tokens": 1000, "user": "alice",
            })
            poll_stats(lambda s: s["lanes_busy"] == 1)
            t2 = post_async("queued", {
                "prompt": "hello", "max_tokens": 2, "user": "bob",
                "priority": "high",
            })
            poll_stats(lambda s: s["queue_depth"] == 1)
            # queue full -> 429 with Retry-After, request never admitted
            try:
                _post(base + "/v1/completions",
                      {"prompt": "hello", "max_tokens": 2, "user": "carol"})
                raise AssertionError("expected 429")
            except urllib.error.HTTPError as e:
                assert e.code == 429
                assert int(e.headers["Retry-After"]) >= 1
                assert json.loads(e.read())["reason"] == "queue_full"
            # streaming submissions shed BEFORE SSE headers commit
            try:
                _post(base + "/v1/completions",
                      {"prompt": "hello", "max_tokens": 2, "stream": True})
                raise AssertionError("expected 429")
            except urllib.error.HTTPError as e:
                assert e.code == 429
            # drain: health flips 503 while in-flight work completes
            drainer = threading.Thread(target=lambda: sched.drain(timeout=120))
            drainer.start()
            deadline = time.monotonic() + 30
            while True:
                try:
                    _get(base + "/health")
                except urllib.error.HTTPError as e:
                    assert e.code == 503
                    assert json.loads(e.read())["status"] == "draining"
                    break
                assert time.monotonic() < deadline
                time.sleep(0.02)
        drainer.join(timeout=120)
        t1.join(timeout=120)
        t2.join(timeout=120)
        # drained gracefully: both in-flight requests served to completion
        assert results["blocker"][0] == 200
        assert results["queued"][0] == 200
        # a post after drain is a clean 503 + Retry-After
        try:
            _post(base + "/v1/completions", {"prompt": "hello", "max_tokens": 2})
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["reason"] == "draining"
            assert int(e.headers["Retry-After"]) >= 1
        # /stats carries the QoS counters next to the engine counters
        _, stats = _get(base + "/stats")
        for key in (
            "queue_depth", "queue_capacity", "queue_admitted",
            "queue_rejected_full", "queue_rejected_draining",
            "queue_wait_avg_s", "queue_timeouts", "budget_timeouts",
            "draining", "decode_steps", "lanes_busy",
        ):
            assert key in stats, key
        assert stats["queue_capacity"] == 1
        assert stats["queue_rejected_full"] >= 2
        assert stats["queue_rejected_draining"] >= 1
        assert stats["draining"] is True
    finally:
        httpd.shutdown()
        sched.stop()


def test_http_bad_priority_is_400(stack):
    config, engine, tok = stack
    sched = make_sched(engine, tok)
    sched.start()
    api = ApiServer(sched, tok, model_name="qos-test")
    httpd = api.serve(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{port}/v1/completions",
                  {"prompt": "hello", "max_tokens": 2, "priority": "urgent"})
        assert ei.value.code == 400
    finally:
        httpd.shutdown()
        sched.stop()
