"""Model configuration, derived from the .m header (src/llm.hpp:39-67)."""

from __future__ import annotations

from dataclasses import dataclass

from ..formats.model_file import HiddenAct, ModelHeader, RopeType


@dataclass(frozen=True)
class LlamaConfig:
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    hidden_act: int = HiddenAct.SILU
    rope_theta: float = 10000.0
    rope_type: int = RopeType.LLAMA
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    norm_epsilon: float = 1e-5
    n_experts: int = 0
    n_active_experts: int = 0
    qkv_bias: int = 0  # Qwen2-family: add per-layer q/k/v projection biases

    def __post_init__(self):
        if self.n_experts > 0 and not (1 <= self.n_active_experts <= self.n_experts):
            raise ValueError(
                f"MoE config needs 1 <= n_active_experts <= n_experts, got "
                f"n_active_experts={self.n_active_experts}, n_experts={self.n_experts}"
            )

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        return (self.dim * self.n_kv_heads) // self.n_heads

    @staticmethod
    def from_header(h: ModelHeader) -> "LlamaConfig":
        return LlamaConfig(
            dim=h.dim,
            hidden_dim=h.hidden_dim,
            n_layers=h.n_layers,
            n_heads=h.n_heads,
            n_kv_heads=h.n_kv_heads,
            vocab_size=h.vocab_size,
            seq_len=h.seq_len,
            hidden_act=h.hidden_act,
            rope_theta=h.rope_theta,
            rope_type=h.rope_type,
            rope_scaling_factor=h.rope_scaling_factor,
            rope_scaling_low_freq_factor=h.rope_scaling_low_freq_factor,
            rope_scaling_high_freq_factor=h.rope_scaling_high_freq_factor,
            rope_scaling_orig_max_seq_len=h.rope_scaling_orig_max_seq_len,
            norm_epsilon=h.norm_epsilon,
            n_experts=h.n_experts,
            n_active_experts=h.n_active_experts,
            qkv_bias=h.qkv_bias,
        )
