# Build targets (reference: Makefile — here the compute path is XLA-compiled
# at runtime; native builds cover the C++ host components).

NATIVE_DIR := distributed_llama_multiusers_tpu/native
NATIVE_SO := $(NATIVE_DIR)/libdllama_native.so

.PHONY: all native test verify lint lockgraph protocol jitcheck leakcheck kernelcheck hooks sanitize dryrun chaos fleet tracecheck check clean

all: native

native: $(NATIVE_SO)

$(NATIVE_SO): $(NATIVE_DIR)/quant_codec.cpp
	python -c "from distributed_llama_multiusers_tpu.native import ensure_built; import sys; sys.exit(0 if ensure_built(quiet=False) else 1)"

test: native
	python -m pytest tests/ -x -q

# Canonical tier-1 gate (the exact command from ROADMAP.md) — the one
# entry point builders and CI invoke; keep in sync with ROADMAP.md.
# Depends on native like `test` does: without the .so the native codec
# tests skip and the gate would report success with less coverage.
verify: SHELL := /bin/bash
verify: native
	set -o pipefail; log=$$(mktemp /tmp/_t1.XXXXXX.log); \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee $$log; \
	rc=$$?; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' $$log | tr -cd . | wc -c); \
	rm -f $$log; exit $$rc

# Static project-invariant gate (docs/LINT.md): cross-file lock-order
# graph, blocking-under-lock, guarded-attr atomicity, pod-broadcast
# pairing, lock discipline on declared guarded state, host-sync
# transfers in the decode path, clock hygiene, condvar/thread hygiene,
# sharding-axis names. Companion to `verify` — run BOTH before shipping
# runtime/serving changes; lint is pure stdlib (no jax, no native
# build), so it's the cheap first gate. tests/test_dlint.py runs the
# same analysis inside tier-1, so `verify` fails on findings too; this
# target is the fast direct entry point. Under GitHub Actions the
# findings render as ::error workflow annotations on the PR diff.
LINT_FORMAT := $(if $(filter true,$(GITHUB_ACTIONS)),--format github,)
lint:
	python -m distributed_llama_multiusers_tpu.analysis $(LINT_FORMAT)

# One-command serving-path parity gate on the 8-virtual-device CPU mesh:
# scheduler decode / chunked prefill / speculative verify / multi-step /
# prefix cache / pipelined+fused churn (0 flushes) all stream-identical
# to the mesh-free engine, plus sharded + pipeline-parallel train steps.
# Banks MULTICHIP_r06.json. Run it before shipping mesh/collective/
# serving-dispatch changes — it is the CPU stand-in for a real pod.
dryrun:
	python scripts/dryrun_multichip.py

# Chaos gate (docs/SERVING.md "Failure containment & chaos testing" +
# "Crash recovery & stream resumption"): the deterministic
# fault-injection suite — engine faults contained mid-churn with
# unaffected streams byte-identical, breaker closed→open→half-open→
# closed over /health+/stats, watchdog firing on a blackholed consume,
# fault-plan determinism, control-packet integrity, the HTTP
# bounded-wait 503 — plus the crash-durability suite: kill the
# scheduler mid-stream, recover from the journal, and every resumed
# stream is byte-identical (zero lost / zero duplicated tokens).
# Mock-engine based: runs in seconds, no accelerator. Run it before
# shipping scheduler/serving/control-plane changes; the same tests ride
# tier-1 via `verify`.
chaos:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_failures.py tests/test_journal.py -q

# Fleet gate (docs/SERVING.md "Fleet serving"): the multi-replica router
# suite — routing under load (least-loaded wins, breaker-open replicas
# excluded), prefix-affinity determinism with the consistent-hash 1/N
# movement bound, typed shed handling, and THE pin: a live SSE stream
# migrated off a dying replica is byte-identical with zero lost and zero
# duplicated tokens vs the uninterrupted run. Mock-engine based: runs in
# seconds, no accelerator. Run it before shipping fleet/, server/http.py
# admin-endpoint, or recovery changes; the same tests ride tier-1 via
# `verify`.
fleet:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q

# Tracing gate (docs/OBSERVABILITY.md "Distributed tracing", ISSUE 20):
# the fleet-trace suite — wire-format mint/parse/accept, span-ring
# cursors and per-track drop accounting, clock-offset-corrected
# cross-replica merge, phase attribution end to end, and THE pin: a
# mid-stream migration yields ONE merged Perfetto timeline with every
# span carrying the client's trace id, the migration.gap slice bridging
# the splice, and summary.phases.migration_gap_ms reconciling with the
# router histogram. Mock-engine based: runs in seconds, no accelerator.
# Run it before shipping telemetry/, fleet/router.py, or summary-schema
# changes; the same tests ride tier-1 via `verify`.
tracecheck:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_tracectx.py -q

# The pre-ship bundle: the cheap static gate first, then the full
# tier-1 suite, then the tracing gate explicitly (it already rides
# `verify`; running it last gives a focused tail signal when the suite
# output is long). One command for "is this shippable".
check: lint verify tracecheck

# Reviewer aid for new lock/broadcast code (ROADMAP items 2-4): the
# statically computed lock-order DAG, DOT on stdout (waived edges
# dashed). Pipe into `dot -Tsvg` or read directly — new edges are what
# to eyeball in review.
lockgraph:
	python -m distributed_llama_multiusers_tpu.analysis --graph

# Reviewer aid for packet-layout changes (ROADMAP item 5 adds new ops +
# a shipped-KV-page replay surface): the wire-protocol op table
# extracted from parallel/multihost.py — op value, encoder, replay-arm
# line, fixed header widths — plus the diff vs the pinned
# analysis/protocol.lock. `make lint` FAILS when the layout changed at
# the same PROTOCOL_VERSION (docs/LINT.md "protocol-manifest"); after a
# legitimate bump, re-pin with
# `python -m distributed_llama_multiusers_tpu.analysis --update-protocol-manifest`.
protocol:
	python -m distributed_llama_multiusers_tpu.analysis --protocol-table

# Compile-stability gate (docs/LINT.md "The runtime recompile witness",
# ISSUE 15): prints the extracted device-program surface of
# runtime/engine.py — every compiled step family with its donation
# spec, dispatchers, and warmup coverage (the reviewer aid for new step
# families) — then runs the witness suite with DLLAMA_JITCHECK=1: a
# real serving churn must compile NOTHING after warmup, and a
# deliberately unwarmed family must make the witness FIRE. (The suite
# drives both strict and counter-only modes itself via jitcheck.force;
# its slow subprocess fixture exercises the DLLAMA_JITCHECK=1 env path
# end to end.) Run it before shipping engine/warmup/dispatch changes;
# the static checks (jit-stability / donation-discipline /
# warmup-coverage) ride `make lint`, and the serving pin rides tier-1
# via `verify`.
jitcheck:
	python -m distributed_llama_multiusers_tpu.analysis --jit-table
	env JAX_PLATFORMS=cpu python -m pytest tests/test_jitcheck.py -q

# Resource-lifecycle gate (docs/LINT.md "resource-balance" /
# "device-affinity" + "The runtime leak witness", ISSUE 17): prints the
# extracted lifecycle surface — every declared resource kind with its
# acquire/release vocabulary and transitive releaser closure, the
# device-affine methods, the batching-loop roots (the reviewer aid for
# new acquire/release pairs; `--graph resources` draws the same surface
# as DOT) — then runs the witness suite: a clean scheduler stop must
# hold NOTHING, and a deliberately leaked registry entry must make
# DLLAMA_LEAKCHECK=1 RAISE at the drain point. (The suite drives both
# strict and counter-only modes itself via leakcheck.force; its slow
# subprocess fixture reruns the serving+prefix suites under
# DLLAMA_LEAKCHECK=1 end to end.) Run it before shipping scheduler/
# pool/registry lifecycle changes; the static checks ride `make lint`,
# and every bench serving phase asserts leaked_resources == 0.
leakcheck:
	python -m distributed_llama_multiusers_tpu.analysis --resource-table
	env JAX_PLATFORMS=cpu python -m pytest tests/test_leakcheck.py -q

# Kernel-numerics gate (PERF.md "Promotion to shipping", ISSUE 18): the
# interpret-mode parity pins for the shipping dequant path, standalone on
# jax CPU — no TPU needed. Two layers: the kernel-lab oracle check (every
# variant vs numpy dequant, single-chunk plane) and the pytest pins —
# the i8blockdot (d_in, d_out, m) parity grid, shared-Q80Acts vs raw-x
# parity per mode, the BLOCKDOT_MAX_M routing boundary, and the
# selection-table semantics behind DLLAMA_DEQUANT=auto. Run it before
# shipping ops/pallas_q40.py or ops/dequant_select.py changes; the same
# pytest pins ride tier-1 via `verify` (the >=256-token decode-stream
# token-identity pin is slow-marked — run it explicitly when touching
# kernel numerics: pytest tests/test_pallas_q40.py -m slow).
kernelcheck:
	env JAX_PLATFORMS=cpu python scripts/kernel_lab3.py --check
	env JAX_PLATFORMS=cpu python -m pytest tests/test_pallas_q40.py tests/test_dequant_select.py -q -m 'not slow'

# Install the git pre-commit hook running the diff-proportional lint
# (`dlint --changed`, docs/LINT.md) so findings surface at commit time
# instead of in tier-1. Idempotent; refuses to clobber a foreign hook.
hooks:
	sh scripts/install_hooks.sh

# ASan+UBSan gate for the native codec (the reference's sanitizer-CI
# analogue, SURVEY.md §5.2): rebuilds the .so instrumented and reruns the
# native test suite against it. detect_leaks=0: CPython itself "leaks".
# The hard load assert matters: tests/test_native.py SKIPS when the library
# won't load, so without it a broken sanitized build would pass green.
# Path comes from the module (single source of truth, like the build line).
NATIVE_SAN_SO = $$(python -c "from distributed_llama_multiusers_tpu.native import _SO_SAN_PATH; print(_SO_SAN_PATH)")
sanitize:
	python -c "from distributed_llama_multiusers_tpu.native import ensure_built; import sys; sys.exit(0 if ensure_built(quiet=False, sanitize=True) else 1)"
	ASAN_OPTIONS=detect_leaks=0:detect_odr_violation=0 \
	LD_PRELOAD=$$(gcc -print-file-name=libasan.so) \
	DLLAMA_NATIVE_SO=$(NATIVE_SAN_SO) \
	sh -c 'python -c "from distributed_llama_multiusers_tpu.native import load; assert load() is not None, \"sanitized .so failed to load\"" && python -m pytest tests/test_native.py -q'

clean:
	rm -f $(NATIVE_SO) $(NATIVE_SAN_SO)
