"""Collective-traffic accounting from compiled XLA programs.

The reference counts every byte its TCP sockets move and prints Sent/Recv kB
per token (src/nn/nn-network.cpp:493-508, src/dllama.cpp:54-64). Under
GSPMD the collectives live inside the compiled executable, so the equivalent
observability comes from the post-partitioning HLO: every all-reduce /
all-gather / reduce-scatter / collective-permute op is visible there with
its per-chip output shape. This module parses them into a byte estimate —
an honest static analogue of the reference's measured socket counters
(payload bytes per chip per step; wire/ICI overheads not included).
"""

from __future__ import annotations

import re

_DTYPE_BYTES = {
    "pred": 1,
    "s4": 1, "s8": 1, "s16": 2, "s32": 4, "s64": 8,
    "u4": 1, "u8": 1, "u16": 2, "u32": 4, "u64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1,
    "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}

_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# e.g. `%all-reduce.3 = f32[8,2048]{1,0} all-reduce(` or a tuple shape
_OP_RE = re.compile(
    r"=\s*(?:\(([^)]*)\)|(\w+\[[\d,]*\])(?:\{[^}]*\})?)\s*"
    r"(" + "|".join(_COLLECTIVES) + r")(-start|-done)?\("
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def collective_stats_from_hlo(hlo_text: str) -> dict:
    """Parse post-SPMD HLO text into per-collective byte totals.

    Bytes counted are each collective's OUTPUT payload on one chip (for
    all-gather that is the received data; for reduce-scatter the reduced
    shard; for all-reduce the full reduced tensor)."""
    per_kind: dict[str, int] = {}
    counts: dict[str, int] = {}
    total = 0
    n_ops = 0
    for m in _OP_RE.finditer(hlo_text):
        tuple_body, single, kind, suffix = m.groups()
        # async -start/-done pairs would double count; count the -start only
        if suffix == "-done":
            continue
        shapes = _SHAPE_RE.findall(tuple_body if tuple_body else single)
        sizes = [_shape_bytes(dt, dims) for dt, dims in shapes]
        if suffix == "-start" and tuple_body:
            # async-start outputs carry (operand, result, contexts...): the
            # payload is the largest buffer, not the tuple sum
            nbytes = max(sizes, default=0)
        else:
            nbytes = sum(sizes)
        per_kind[kind] = per_kind.get(kind, 0) + nbytes
        counts[kind] = counts.get(kind, 0) + 1
        total += nbytes
        n_ops += 1
    return {
        "total_bytes": total,
        "n_collectives": n_ops,
        "bytes_by_kind": per_kind,
        "count_by_kind": counts,
    }


def collective_stats_of_compiled(compiled) -> dict:
    """Analyze an already-compiled executable's collective traffic."""
    try:
        text = compiled.as_text()
    except Exception:  # some backends restrict HLO dumps
        return {"total_bytes": 0, "n_collectives": 0, "error": "hlo unavailable"}
    return collective_stats_from_hlo(text)


def collective_stats_of(jitted_fn, *args, **kwargs) -> dict:
    """Compile and analyze a jitted function's collective traffic for the
    given example arguments. Callers that want to keep the executable (e.g.
    to dispatch it) should lower+compile themselves and use
    ``collective_stats_of_compiled``."""
    return collective_stats_of_compiled(jitted_fn.lower(*args, **kwargs).compile())
