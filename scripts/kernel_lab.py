"""Kernel lab: measure Q40 matmul variants on the real TPU.

Times a chain of L layer-like PackedQ40 matmuls (decode shape: m small) and
reports effective weight-read GB/s per variant, vs the v5e HBM roofline
(819 GB/s). Used to drive the round-3 kernel optimization (VERDICT Weak #1:
current kernel at 43.8% HBM while XLA dense-bf16 runs at ~92%).

Run: python scripts/kernel_lab.py [m] [d_in] [d_out] [L]
"""

from __future__ import annotations

import sys
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from distributed_llama_multiusers_tpu.quants.packed import (  # noqa: E402
    PackedQ40,
    pack_q40_host,
    q40_matmul_xla,
)
from distributed_llama_multiusers_tpu.ops.pallas_q40 import (  # noqa: E402
    _f16_bits_to_f32,
    q40_matmul_pallas,
)

HBM_GB_S = 819.0  # v5e


# ---------------------------------------------------------------------------
# v1: two-dot nibble kernel. No concat, no per-weight subtract: the -8 offset
# is folded into a per-block correction dot; x arrives pre-split into the
# lo/hi column groups so the kernel does no x shuffling at all.
# ---------------------------------------------------------------------------


def _v1_kernel(x_lo_ref, x_hi_ref, bsum_t_ref, packed_ref, scales_ref, out_ref,
               acc_ref, *, out_dtype_w):
    k = pl.program_id(2)
    half_rows, tile = packed_ref.shape
    n_blk = half_rows // 16

    p = packed_ref[...].astype(jnp.int32)
    s = _f16_bits_to_f32(scales_ref[...])  # [n_blk, tile] f32
    s3 = s[:, None, :]
    w_lo = ((p & 0x0F).astype(jnp.float32).reshape(n_blk, 16, tile) * s3)
    w_hi = ((p >> 4).astype(jnp.float32).reshape(n_blk, 16, tile) * s3)
    w_lo = w_lo.reshape(half_rows, tile).astype(out_dtype_w)
    w_hi = w_hi.reshape(half_rows, tile).astype(out_dtype_w)

    # correction for the folded -8 offset: 8 * bsum_b @ s  ([m, tile])
    corr = jax.lax.dot_general(
        bsum_t_ref[...], s, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    partial_sum = (
        jnp.dot(x_lo_ref[...], w_lo, preferred_element_type=jnp.float32)
        + jnp.dot(x_hi_ref[...], w_hi, preferred_element_type=jnp.float32)
        - 8.0 * corr
    )

    @pl.when(k == 0)
    def _():
        acc_ref[...] = partial_sum

    @pl.when(k > 0)
    def _():
        acc_ref[...] = acc_ref[...] + partial_sum

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _pick_chunk(d_in, cap):
    if d_in % 32 != 0:
        return None
    best = 32
    for c in range(64, min(d_in, cap) + 1, 32):
        if d_in % c == 0:
            best = c
    return best


def _pick_tile(n, cap):
    for c in range(cap, 127, -128):
        if n % c == 0:
            return c
    return n


@partial(jax.jit, static_argnames=("din_chunk", "dout_tile", "w_dtype", "x_dtype"))
def q40_matmul_v1(x, packed, scales, din_chunk=2048, dout_tile=512,
                  w_dtype=jnp.float32, x_dtype=jnp.float32):
    w = PackedQ40(packed=packed, scales=scales)
    d_in, d_out = w.d_in, w.d_out
    chunk = _pick_chunk(d_in, din_chunk)
    lead = x.shape[:-1]
    m = int(np.prod(lead)) if lead else 1

    xf = x.reshape(m, d_in).astype(jnp.float32)
    m_pad = max(8, ((m + 7) // 8) * 8)
    m_tile = min(256, m_pad)
    if m_pad != m:
        xf = jnp.pad(xf, ((0, m_pad - m), (0, 0)))

    n_blk_total = d_in // 32
    xb = xf.reshape(m_pad, n_blk_total, 2, 16)
    x_lo = xb[:, :, 0, :].reshape(m_pad, d_in // 2).astype(x_dtype)
    x_hi = xb[:, :, 1, :].reshape(m_pad, d_in // 2).astype(x_dtype)
    # transposed [n_blk, m] so the lane dim is m_pad (full) — Pallas lane-dim
    # blocking requires multiples of 128 or the full extent
    bsum_t = xf.reshape(m_pad, n_blk_total, 32).sum(axis=2).T

    tile = _pick_tile(d_out, dout_tile)
    grid = (m_pad // m_tile, d_out // tile, d_in // chunk)
    scale_bits = jax.lax.bitcast_convert_type(scales, jnp.int16)

    out = pl.pallas_call(
        partial(_v1_kernel, out_dtype_w=w_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((m_tile, chunk // 2), lambda i, j, k: (i, k)),
            pl.BlockSpec((m_tile, chunk // 2), lambda i, j, k: (i, k)),
            pl.BlockSpec((chunk // 32, m_tile), lambda i, j, k: (k, i)),
            pl.BlockSpec((chunk // 2, tile), lambda i, j, k: (k, j)),
            pl.BlockSpec((chunk // 32, tile), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((m_tile, tile), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, d_out), x.dtype),
        scratch_shapes=[pltpu.VMEM((m_tile, tile), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad * d_in * d_out,
            bytes_accessed=d_in * d_out // 2 + (d_in // 32) * d_out * 2
            + m_pad * d_in * 4 + m_pad * d_out * x.dtype.itemsize,
            transcendentals=0,
        ),
    )(x_lo, x_hi, bsum_t, packed, scale_bits)
    return out[:m].reshape(*lead, d_out)


# ---------------------------------------------------------------------------
# v2: v1 math + PRE-TILED weight planes. packed [J, d_in//2, T] u8 and
# scales [J, d_in//32, T] i16 with J = d_out // T: each grid step's weight
# block is one fully contiguous slab in HBM (the [d_in//2, d_out] layout
# gives the DMA 512-byte rows strided by d_out).
# ---------------------------------------------------------------------------

V2_TILE = 512


def retile(packed, scales, tile=V2_TILE):
    d_out = packed.shape[-1]
    j = d_out // tile
    pt = jnp.moveaxis(packed.reshape(packed.shape[0], j, tile), 1, 0)
    st = jnp.moveaxis(scales.reshape(scales.shape[0], j, tile), 1, 0)
    sbits = jax.lax.bitcast_convert_type(st, jnp.int16)
    return jnp.copy(pt), jnp.copy(sbits)


def _v2_kernel(x_lo_ref, x_hi_ref, bsum_t_ref, packed_ref, scales_ref, out_ref,
               acc_ref, *, out_dtype_w):
    k = pl.program_id(2)
    _, half_rows, tile = packed_ref.shape
    n_blk = half_rows // 16

    p = packed_ref[0].astype(jnp.int32)
    s = _f16_bits_to_f32(scales_ref[0])
    s3 = s[:, None, :]
    w_lo = ((p & 0x0F).astype(out_dtype_w).reshape(n_blk, 16, tile)
            * s3.astype(out_dtype_w)).reshape(half_rows, tile)
    w_hi = (((p >> 4).astype(out_dtype_w)).reshape(n_blk, 16, tile)
            * s3.astype(out_dtype_w)).reshape(half_rows, tile)

    corr = jax.lax.dot_general(
        bsum_t_ref[...], s, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    partial_sum = (
        jnp.dot(x_lo_ref[...], w_lo, preferred_element_type=jnp.float32)
        + jnp.dot(x_hi_ref[...], w_hi, preferred_element_type=jnp.float32)
        - 8.0 * corr
    )

    @pl.when(k == 0)
    def _():
        acc_ref[...] = partial_sum

    @pl.when(k > 0)
    def _():
        acc_ref[...] = acc_ref[...] + partial_sum

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


@partial(jax.jit, static_argnames=("din_chunk", "w_dtype", "x_dtype"))
def q40_matmul_v2(x, packed_t, scales_t, din_chunk=2048,
                  w_dtype=jnp.float32, x_dtype=jnp.float32):
    """x: [..., d_in]; packed_t [J, d_in//2, T] u8; scales_t [J, d_in//32, T]
    int16 (f16 bits)."""
    j, half, tile = packed_t.shape
    d_in, d_out = half * 2, j * tile
    chunk = _pick_chunk(d_in, din_chunk)
    lead = x.shape[:-1]
    m = int(np.prod(lead)) if lead else 1

    xf = x.reshape(m, d_in).astype(jnp.float32)
    m_pad = max(8, ((m + 7) // 8) * 8)
    m_tile = min(256, m_pad)
    if m_pad != m:
        xf = jnp.pad(xf, ((0, m_pad - m), (0, 0)))

    n_blk_total = d_in // 32
    xb = xf.reshape(m_pad, n_blk_total, 2, 16)
    x_lo = xb[:, :, 0, :].reshape(m_pad, d_in // 2).astype(x_dtype)
    x_hi = xb[:, :, 1, :].reshape(m_pad, d_in // 2).astype(x_dtype)
    bsum_t = xf.reshape(m_pad, n_blk_total, 32).sum(axis=2).T

    grid = (m_pad // m_tile, j, d_in // chunk)

    out = pl.pallas_call(
        partial(_v2_kernel, out_dtype_w=w_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((m_tile, chunk // 2), lambda i, j, k: (i, k)),
            pl.BlockSpec((m_tile, chunk // 2), lambda i, j, k: (i, k)),
            pl.BlockSpec((chunk // 32, m_tile), lambda i, j, k: (k, i)),
            pl.BlockSpec((1, chunk // 2, tile), lambda i, j, k: (j, k, 0)),
            pl.BlockSpec((1, chunk // 32, tile), lambda i, j, k: (j, k, 0)),
        ],
        out_specs=pl.BlockSpec((m_tile, tile), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, d_out), x.dtype),
        scratch_shapes=[pltpu.VMEM((m_tile, tile), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad * d_in * d_out,
            bytes_accessed=d_in * d_out // 2 + (d_in // 32) * d_out * 2
            + m_pad * d_in * 4 + m_pad * d_out * x.dtype.itemsize,
            transcendentals=0,
        ),
    )(x_lo, x_hi, bsum_t, packed_t, scales_t)
    return out[:m].reshape(*lead, d_out)


# ---------------------------------------------------------------------------
# read-only roofline probe: how fast can Pallas merely stream the packed
# bytes through VMEM with ~1 op/byte? Upper bound for any dequant kernel.
# ---------------------------------------------------------------------------


def _probe_kernel(packed_ref, out_ref, acc_ref):
    k = pl.program_id(1)
    p = packed_ref[...].astype(jnp.int32)

    @pl.when(k == 0)
    def _():
        acc_ref[...] = jnp.sum(p, axis=0, keepdims=True).astype(jnp.float32)

    @pl.when(k > 0)
    def _():
        acc_ref[...] = acc_ref[...] + jnp.sum(p, axis=0, keepdims=True)

    @pl.when(k == pl.num_programs(1) - 1)
    def _():
        out_ref[...] = acc_ref[...]


@partial(jax.jit, static_argnames=("chunk", "tile"))
def read_probe(packed, chunk=2048, tile=512):
    rows, d_out = packed.shape
    grid = (d_out // tile, rows // (chunk // 2))
    return pl.pallas_call(
        _probe_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((chunk // 2, tile), lambda j, k: (k, j))],
        out_specs=pl.BlockSpec((1, tile), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, d_out), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, tile), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(packed)


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def bench_chain(fn, x, weights, repeats=20, prep=None):
    """Time fn(x, w) chained over all weights, repeated on-device via
    fori_loop (one dispatch — the axon tunnel costs ~ms per call).
    Returns seconds per single pass over all weights."""

    @jax.jit
    def chain(x, ws):
        def body(_, x):
            for packed, scales in ws:
                y = fn(x, packed, scales)
                x = y[..., : x.shape[-1]].astype(x.dtype)
            return x

        return jax.lax.fori_loop(0, repeats, body, x)

    if prep is not None:
        weights = [PackedQ40(*prep(w.packed, w.scales)) for w in weights]

    ws = [(w.packed, w.scales) for w in weights]
    # np.asarray forces completion; axon's block_until_ready does not
    np.asarray(chain(x, ws))  # compile + warmup
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(chain(x, ws))
        best = min(best, time.perf_counter() - t0)
    return best / repeats


def main():
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 1
    d_in = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    d_out = int(sys.argv[3]) if len(sys.argv) > 3 else 14336
    L = int(sys.argv[4]) if len(sys.argv) > 4 else 8

    rng = np.random.default_rng(0)
    weights = []
    for _ in range(L):
        w = (rng.standard_normal((d_out, d_in), dtype=np.float32) * 0.05)
        packed, scales = pack_q40_host(w)
        weights.append(
            PackedQ40(packed=jnp.asarray(packed), scales=jnp.asarray(scales))
        )
    x = jnp.asarray(rng.standard_normal((m, d_in), dtype=np.float32))

    wbytes = L * (d_in * d_out // 2 + (d_in // 32) * d_out * 2)
    print(f"m={m} d_in={d_in} d_out={d_out} L={L} "
          f"weights={wbytes / 1e9:.3f} GB  device={jax.devices()[0].device_kind}")

    # correctness spot check
    ref = q40_matmul_xla(x, weights[0])
    pt, st = retile(weights[0].packed, weights[0].scales)
    for name, f in [
        ("v0", lambda: q40_matmul_pallas(x, weights[0])),
        ("v1", lambda: q40_matmul_v1(x, weights[0].packed, weights[0].scales)),
        ("v1_bf16", lambda: q40_matmul_v1(
            x, weights[0].packed, weights[0].scales,
            w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16)),
        ("v2", lambda: q40_matmul_v2(x, pt, st)),
    ]:
        err = float(jnp.max(jnp.abs(ref - f())) / (jnp.max(jnp.abs(ref)) + 1e-9))
        print(f"{name} rel err vs xla: {err:.2e}", flush=True)

    variants = {
        "v0_current": lambda x, p, s: q40_matmul_pallas(x, PackedQ40(p, s)),
        "v1_f32": lambda x, p, s: q40_matmul_v1(x, p, s),
        "v1_bf16w": lambda x, p, s: q40_matmul_v1(x, p, s, w_dtype=jnp.bfloat16),
        "v1_bf16wx": lambda x, p, s: q40_matmul_v1(
            x, p, s, w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16
        ),
        "v1_bf16_c4096_t512": lambda x, p, s: q40_matmul_v1(
            x, p, s, din_chunk=4096, dout_tile=512,
            w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16
        ),
        "v1_bf16_c2048_t1024": lambda x, p, s: q40_matmul_v1(
            x, p, s, din_chunk=2048, dout_tile=1024,
            w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16
        ),
        "v1_bf16_c1024_t1024": lambda x, p, s: q40_matmul_v1(
            x, p, s, din_chunk=1024, dout_tile=1024,
            w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16
        ),
        "v1_bf16_c1024_t2048": lambda x, p, s: q40_matmul_v1(
            x, p, s, din_chunk=1024, dout_tile=2048,
            w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16
        ),
        "v2_tiled_f32": (
            lambda x, p, s: q40_matmul_v2(x, p, s),
            retile,
        ),
        "v2_tiled_bf16": (
            lambda x, p, s: q40_matmul_v2(
                x, p, s, w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16),
            retile,
        ),
        "v2_tiled_bf16_c4096": (
            lambda x, p, s: q40_matmul_v2(
                x, p, s, din_chunk=4096,
                w_dtype=jnp.bfloat16, x_dtype=jnp.bfloat16),
            retile,
        ),
    }

    for name, fn in variants.items():
        prep = None
        if isinstance(fn, tuple):
            fn, prep = fn
        try:
            sec = bench_chain(fn, x, weights, prep=prep)
            gbs = wbytes / sec / 1e9
            print(f"{name:24s} {sec * 1e3:8.3f} ms  {gbs:7.1f} GB/s "
                  f"({gbs / HBM_GB_S * 100:5.1f}% HBM)")
        except Exception as e:
            print(f"{name:24s} FAILED: {type(e).__name__}: {str(e)[:120]}")

    # harness validation: dense bf16 chain (BENCH_r02 showed ~92% HBM for
    # the dense path inside the full model; if this shows garbage the harness
    # is broken, not the kernel)
    dense = [jnp.asarray(
        rng.standard_normal((d_in, d_out), dtype=np.float32), jnp.bfloat16)
        for _ in range(L)]
    dbytes = L * d_in * d_out * 2

    @jax.jit
    def dense_chain(x, ws):
        def body(_, x):
            for w in ws:
                y = jnp.dot(x.astype(jnp.bfloat16), w,
                            preferred_element_type=jnp.float32)
                x = y[..., : x.shape[-1]]
            return x

        return jax.lax.fori_loop(0, 20, body, x)

    dense_chain(x, dense).block_until_ready()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        dense_chain(x, dense).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    sec = best / 20
    gbs = dbytes / sec / 1e9
    print(f"{'dense_bf16_xla':24s} {sec * 1e3:8.3f} ms  {gbs:7.1f} GB/s "
          f"({gbs / HBM_GB_S * 100:5.1f}% HBM)")

    # pure read probe
    try:
        pk = weights[0].packed
        reps = 50

        @jax.jit
        def probe_loop(pk):
            def body(_, acc):
                return acc + read_probe(pk)[0, 0]

            return jax.lax.fori_loop(0, reps, body, jnp.float32(0))

        probe_loop(pk).block_until_ready()
        t0 = time.perf_counter()
        probe_loop(pk).block_until_ready()
        sec = (time.perf_counter() - t0) / reps
        gbs = pk.size / sec / 1e9
        print(f"{'read_probe':24s} {sec * 1e3:8.3f} ms  {gbs:7.1f} GB/s "
              f"({gbs / HBM_GB_S * 100:5.1f}% HBM)")
    except Exception as e:
        print(f"read_probe FAILED: {type(e).__name__}: {str(e)[:160]}")


if __name__ == "__main__":
    main()
