"""Sequence-parallel attention: blockwise LSE-combine and ring attention.

The reference has NO sequence parallelism — its KV cache is sharded only via
TP (kvDim) and attention is a serial per-head loop over 0..pos
(src/nn/nn-cpu-ops.cpp:749-784, SURVEY.md §5.7). Long context is therefore a
capability this framework adds, designed TPU-first:

- ``sp_attention``: the KV cache stays sharded along S over the ``sp`` mesh
  axis. Every device computes flash-style partial softmax stats (running
  max m, normalizer l, weighted value sum o) over ITS sequence block, then
  one tiny psum over sp combines the stats — no all-gather of the cache,
  communication is O(heads * head_size), independent of S. Works for decode
  (T=1) and for prefill with queries replicated over sp.

- ``ring_attention``: for sequence-sharded QUERIES (long-prompt prefill /
  training), KV blocks rotate around the sp ring via lax.ppermute while
  each device accumulates flash stats for its query block — classic ring
  attention (Liu et al. 2023), causal-masked. Communication overlaps with
  block compute; peak memory is O(S/sp) per device.

Both are shard_map programs over the (dp, tp, sp) mesh of parallel/mesh.py;
the dp and tp axes are embarrassingly parallel here (lanes, kv-head groups)
and carry no collectives.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..jax_compat import shard_map


def _block_stats(q, k, v, mask):
    """Flash-attention partial stats for one KV block.

    q: [B, T, K, G, H] f32; k/v: [B, S_blk, K, H] f32; mask: [B, T, S_blk].
    Returns (o [B,T,K,G,H], l [B,T,K,G], m [B,T,K,G]) with the convention
    m = -inf and o = l = 0 for fully-masked query rows."""
    scores = jnp.einsum("btkgh,bskh->btkgs", q, k)
    scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)  # [B,T,K,G], -inf when all masked
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - m_safe[..., None])  # exp(-inf) = 0 on masked slots
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("btkgs,bskh->btkgh", p, v)
    return o, l, m


def _merge_stats(o1, l1, m1, o2, l2, m2):
    """Combine two flash partial-stat triples (order-invariant)."""
    m = jnp.maximum(m1, m2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(l1 > 0, jnp.exp(jnp.where(jnp.isfinite(m1), m1, 0.0) - m_safe), 0.0)
    w2 = jnp.where(l2 > 0, jnp.exp(jnp.where(jnp.isfinite(m2), m2, 0.0) - m_safe), 0.0)
    o = o1 * w1[..., None] + o2 * w2[..., None]
    l = l1 * w1 + l2 * w2
    return o, l, m


def _finalize(o, l):
    return o / jnp.maximum(l, 1e-30)[..., None]


def sp_attention(
    q: jnp.ndarray,  # [B, T, n_kv, group, hd] (pre-scaled by caller or scale=)
    k_cache: jnp.ndarray,  # [B, S, n_kv, hd]
    v_cache: jnp.ndarray,  # [B, S, n_kv, hd]
    positions: jnp.ndarray,  # [B, T] int32 (query positions; mask is s <= pos)
    mesh: Mesh,
    scale: float,
) -> jnp.ndarray:
    """GQA attention over an S-sharded KV cache. Returns [B, T, n_kv, group, hd]
    f32, replicated over sp. One psum of flash stats crosses the sp axis."""
    n_sp = mesh.shape["sp"]
    s_total = k_cache.shape[1]
    s_blk = s_total // n_sp

    def inner(q, k, v, pos):
        # local S block: [B, s_blk, K/tp, H]; q replicated over sp
        start = jax.lax.axis_index("sp") * s_blk
        s_idx = start + jax.lax.broadcasted_iota(jnp.int32, (1, 1, s_blk), 2)
        mask = s_idx <= pos[:, :, None]  # [B, T, s_blk]
        o, l, m = _block_stats(q * scale, k, v, mask)

        # combine across sp: numerically exact psum of rescaled stats
        m_glob = jax.lax.pmax(m, "sp")
        m_safe = jnp.where(jnp.isfinite(m_glob), m_glob, 0.0)
        w = jnp.where(
            l > 0, jnp.exp(jnp.where(jnp.isfinite(m), m, 0.0) - m_safe), 0.0
        )
        o = jax.lax.psum(o * w[..., None], "sp")
        l = jax.lax.psum(l * w, "sp")
        return _finalize(o, l)

    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            P("dp", None, "tp", None, None),  # q
            P("dp", "sp", "tp", None),  # k
            P("dp", "sp", "tp", None),  # v
            P("dp", None),  # positions
        ),
        out_specs=P("dp", None, "tp", None, None),
        check_vma=False,
    )(q.astype(jnp.float32), k_cache.astype(jnp.float32), v_cache.astype(jnp.float32), positions)


def ring_attention(
    q: jnp.ndarray,  # [B, T, n_kv, group, hd] — T sharded over sp
    k: jnp.ndarray,  # [B, T, n_kv, hd]       — T sharded over sp
    v: jnp.ndarray,  # [B, T, n_kv, hd]
    mesh: Mesh,
    scale: float,
) -> jnp.ndarray:
    """Causal self-attention with sequence-sharded queries AND keys: KV blocks
    rotate around the sp ring (lax.ppermute) for n_sp steps while each device
    folds flash stats for its query block. Returns [B, T, n_kv, group, hd]
    f32 with the same sp sharding as q."""
    n_sp = mesh.shape["sp"]
    t_total = q.shape[1]
    t_blk = t_total // n_sp

    def inner(q, k, v):
        my = jax.lax.axis_index("sp")
        q_start = my * t_blk
        q_idx = q_start + jax.lax.broadcasted_iota(jnp.int32, (1, t_blk, 1), 1)
        qf = q * scale
        perm = [(i, (i + 1) % n_sp) for i in range(n_sp)]

        def fold(o, l, m, kr, vr, r):
            # kr/vr originated on device (my - r) % n_sp
            src = (my - r) % n_sp
            k_idx = src * t_blk + jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, t_blk), 2
            )
            mask = k_idx <= q_idx  # causal: key pos <= query pos
            o2, l2, m2 = _block_stats(qf, kr, vr, mask)
            return _merge_stats(o, l, m, o2, l2, m2)

        def step(carry, r):
            o, l, m, kr, vr = carry
            o, l, m = fold(o, l, m, kr, vr, r)
            kr = jax.lax.ppermute(kr, "sp", perm)
            vr = jax.lax.ppermute(vr, "sp", perm)
            return (o, l, m, kr, vr), None

        b, _, n_kv, g, hd = q.shape
        o0 = jnp.zeros((b, t_blk, n_kv, g, hd), jnp.float32)
        l0 = jnp.zeros((b, t_blk, n_kv, g), jnp.float32)
        m0 = jnp.full((b, t_blk, n_kv, g), -jnp.inf, jnp.float32)
        # n_sp - 1 fold+rotate steps, then fold the last received block with
        # no trailing rotation (its result would be discarded)
        (o, l, m, kr, vr), _ = jax.lax.scan(
            step, (o0, l0, m0, k, v), jnp.arange(n_sp - 1)
        )
        o, l, m = fold(o, l, m, kr, vr, jnp.int32(n_sp - 1))
        return _finalize(o, l)

    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            P("dp", "sp", "tp", None, None),
            P("dp", "sp", "tp", None),
            P("dp", "sp", "tp", None),
        ),
        out_specs=P("dp", "sp", "tp", None, None),
        check_vma=False,
    )(q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
