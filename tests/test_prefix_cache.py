"""Prefix caching: requests sharing a prompt prefix with KV already
resident in some lane skip re-prefilling the prefix via a whole-lane HBM
copy (engine.copy_lane) + tail prefill.

No reference analogue — its lanes share a single KV cache (SURVEY.md §2
defect (c)), which makes per-lane prefix reuse impossible there. The
invariant under test is exactness: a prefix-cached request must produce
token streams identical to a cold prefill, because the copied KV slots are
the same values a fresh prefill would have written (prefill is
deterministic given tokens+positions).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer


@pytest.fixture(scope="module")
def loaded(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    return config, params, tok


def _engine(config, params, n_lanes=2):
    return InferenceEngine(config, params, n_lanes=n_lanes, prefill_buckets=(8,))


def test_copy_lane_then_tail_prefill_matches_cold_prefill(loaded):
    """copy_lane + tail prefill == full prefill, bit-for-bit logits."""
    config, params, _ = loaded
    full = [5, 9, 3, 17, 2, 11, 7, 4, 13, 6]
    split = 8

    cold = _engine(config, params)
    logits_cold, greedy_cold, _ = cold.prefill(1, full)

    warm = _engine(config, params)
    warm.prefill(0, full[:split])  # prefix resident in lane 0
    warm.copy_lane(0, 1)
    logits_warm, greedy_warm, _ = warm.prefill(1, full[split:], start_pos=split)

    assert int(greedy_warm) == int(greedy_cold)
    np.testing.assert_array_equal(
        np.asarray(logits_warm), np.asarray(logits_cold)
    )


def _run(engine, tok, reqs, **sched_kw):
    sched = ContinuousBatchingScheduler(engine, tok, **sched_kw)
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs]


def test_scheduler_prefix_hit_skips_prefill_and_keeps_stream(loaded):
    """Sequential requests with a shared long system prefix: the second
    admission reuses the first lane's KV (prefix_hits/prefix_tokens_saved
    count it, fewer prefill chunks run) and the generated stream is
    IDENTICAL to a prefix-cache-disabled scheduler."""
    config, params, tok = loaded
    system = "aa bb cc dd ee ff gg hh "  # long shared prefix (char-level tok)
    prompts = [system + "11", system + "22"]

    def reqs():
        return [Request(prompt=p, max_tokens=8, temperature=0.0) for p in prompts]

    engine = _engine(config, params)
    chunks = []
    real = engine.prefill_chunk

    def spy(lane, chunk, start_pos, **kw):
        chunks.append((lane, len(chunk), start_pos))
        return real(lane, chunk, start_pos, **kw)

    engine.prefill_chunk = spy

    def run_sequential(eng, **kw):
        sched = ContinuousBatchingScheduler(eng, tok, **kw)
        sched.start()
        out = []
        try:
            for r in reqs():
                sched.submit(r)
                r.future.result(timeout=300)
                assert r.error is None, r.error
                out.append(list(r.generated_tokens))
        finally:
            sched.stop()
        return out

    got_hit = run_sequential(engine)
    assert engine.stats.prefix_hits == 1
    # the second request's prompt processing started past the shared
    # prefix: no prefill chunk after the first request re-ran position 0
    first_prompt_chunks = -(-len(tok.encode(prompts[0])) // 8)  # ceil div
    assert all(c[2] > 0 for c in chunks[first_prompt_chunks:]), chunks
    n_shared = len(tok.encode(prompts[0][:-2]))
    assert engine.stats.prefix_tokens_saved >= n_shared - 8  # >= prefix - bucket

    plain_engine = _engine(config, params)
    got_plain = run_sequential(plain_engine, prefix_min_tokens=0)
    assert got_hit == got_plain
    assert plain_engine.stats.prefix_hits == 0
    # the cached run prefilled strictly fewer prompt tokens
    assert engine.stats.prefill_tokens < plain_engine.stats.prefill_tokens


def test_scheduler_prefix_concurrent_batch_identical_streams(loaded):
    """Two concurrent requests sharing a prefix (second admitted while the
    first may still be prefilling — only committed chunks are reusable):
    streams match the prefix-disabled scheduler exactly."""
    config, params, tok = loaded
    system = "aa bb cc dd ee ff "

    def reqs():
        return [
            Request(prompt=system + "xx", max_tokens=8, temperature=0.0),
            Request(prompt=system + "yy", max_tokens=8, temperature=0.0),
            Request(prompt="zz unrelated", max_tokens=6, temperature=0.0),
        ]

    got_hit = _run(_engine(config, params, n_lanes=4), tok, reqs())
    got_plain = _run(
        _engine(config, params, n_lanes=4), tok, reqs(), prefix_min_tokens=0
    )
    assert got_hit == got_plain


def test_pod_root_engine_broadcasts_copy_lane():
    """RootControlEngine.copy_lane must broadcast OP_COPY_LANE before the
    root-side call (a silent __getattr__ forward would desync the pod),
    and worker_loop must replay it."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_COPY_LANE,
        ControlPlane,
        RootControlEngine,
        worker_loop,
    )

    sent = []

    class _Plane(ControlPlane):
        def _bcast(self, pkt):
            sent.append(np.array(pkt))
            return pkt

    class _Inner:
        n_lanes = 2
        copied = None

        def copy_lane(self, src, dst):
            self.copied = (src, dst)

    inner = _Inner()
    root = RootControlEngine(inner, _Plane(n_lanes=2, chunk=8))
    root.copy_lane(0, 1)
    assert inner.copied == (0, 1)
    assert len(sent) == 1
    # header: [magic, version, op, lane, n, start_pos]
    assert list(sent[0][2:6]) == [OP_COPY_LANE, 0, 0, 1]
    root.copy_lane(1, 1)  # no-op: nothing broadcast, nothing dispatched
    assert len(sent) == 1

    # worker side replays the header operands
    class _WEngine:
        copied = None

        def copy_lane(self, src, dst):
            self.copied = (src, dst)

    from tests.test_multihost import _ScriptedPlane
    from distributed_llama_multiusers_tpu.parallel.multihost import OP_STOP

    weng = _WEngine()
    plane = _ScriptedPlane([OP_COPY_LANE, OP_STOP])
    # _ScriptedPlane packs (magic, version, op, 0, 2, 0); patch the copy
    # packet's operands (lane=src at header slot 3, start_pos=dst at 5)
    plane._pkts[0][3] = 1  # src
    plane._pkts[0][5] = 0  # dst
    worker_loop(weng, plane)
    assert weng.copied == (1, 0)


def test_prefix_reuse_survives_idle_lane_decode_steps(loaded):
    """Round-5 code-review finding: every decode step scatters a KV write
    for EVERY lane; idle/finished lanes used to point at position 0,
    clobbering slot 0 of exactly the caches prefix admission wants to
    reuse. Idle lanes now write at seq_len (dropped). Scenario: A
    finishes, B keeps decoding (each step would have corrupted A's
    slot 0), then C reuses A's prefix — C's stream must equal a cold
    run's."""
    config, params, tok = loaded
    system = "aa bb cc dd ee ff gg hh "

    def make(mt, tail):
        return Request(prompt=system + tail, max_tokens=mt, temperature=0.0)

    def run(eng, **kw):
        sched = ContinuousBatchingScheduler(eng, tok, **kw)
        sched.start()
        try:
            a, b = make(2, "11"), make(30, "22")
            sched.submit(a)
            sched.submit(b)
            a.future.result(timeout=300)  # A done; B decodes on (idle A lane)
            c = make(8, "11")  # same prompt as A: prefix-hits A's lane
            sched.submit(c)
            c.future.result(timeout=300)
            b.future.result(timeout=300)
            assert all(r.error is None for r in (a, b, c))
            return list(c.generated_tokens)
        finally:
            sched.stop()

    warm_engine = _engine(config, params, n_lanes=2)
    got = run(warm_engine)
    assert warm_engine.stats.prefix_hits >= 1
    cold = run(_engine(config, params, n_lanes=2), prefix_min_tokens=0)
    assert got == cold
