"""Chat template rendering + chat stop strings.

Port of ChatTemplateGenerator / TokenizerChatStops (src/tokenizer.cpp:512-612):
hard-coded renderers for llama2 / llama3 / deepSeek3, auto-detected from the
Jinja template string stored in the tokenizer file.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import IntEnum

from .tokenizer import Tokenizer


class TemplateType(IntEnum):
    UNKNOWN = 0
    LLAMA2 = 1
    LLAMA3 = 2
    DEEP_SEEK3 = 3
    # framework extension beyond the reference's three renderers
    # (src/tokenizer.cpp:538-559): ChatML, the Qwen2-family turn format
    CHATML = 4


@dataclass
class ChatItem:
    role: str
    message: str


@dataclass
class GeneratedChat:
    content: str
    public_prompt: str | None  # deepSeek3 exposes its injected "<think>\n" tail


def template_type_from_name(name: str | None) -> TemplateType:
    """CLI --chat-template value -> TemplateType (None = auto-detect)."""
    return {
        None: TemplateType.UNKNOWN,
        "llama2": TemplateType.LLAMA2,
        "llama3": TemplateType.LLAMA3,
        "deepSeek3": TemplateType.DEEP_SEEK3,
        "chatml": TemplateType.CHATML,
    }[name]


def eos_piece_of(tokenizer: Tokenizer) -> str:
    """The first EOS token's text — the template's turn terminator."""
    if not tokenizer.eos_token_ids:
        return ""
    return tokenizer.vocab[tokenizer.eos_token_ids[0]].decode("utf-8", errors="replace")


def chat_generator_for(tokenizer: Tokenizer, name_or_type=None) -> "ChatTemplateGenerator":
    """Build a ChatTemplateGenerator from a tokenizer + optional CLI name."""
    t = name_or_type if isinstance(name_or_type, TemplateType) else template_type_from_name(name_or_type)
    return ChatTemplateGenerator(t, tokenizer.chat_template, eos_piece_of(tokenizer))


class TokenizerChatStops:
    """Stop strings = the pieces of the tokenizer's EOS tokens
    (src/tokenizer.cpp:512-525)."""

    def __init__(self, tokenizer: Tokenizer):
        self.stops: list[str] = [
            tokenizer.vocab[t].decode("utf-8", errors="replace") for t in tokenizer.eos_token_ids
        ]
        self.max_stop_length = max((len(s) for s in self.stops), default=0)


class ChatTemplateGenerator:
    def __init__(self, template_type: TemplateType, chat_template: str | None, eos: str):
        if template_type == TemplateType.UNKNOWN:
            if chat_template is None:
                raise ValueError("The tokenizer does not include chat template")
            if "[INST]" in chat_template:
                template_type = TemplateType.LLAMA2
            elif "<|start_header_id|>" in chat_template:
                template_type = TemplateType.LLAMA3
            elif "<｜Assistant｜>" in chat_template:
                template_type = TemplateType.DEEP_SEEK3
            elif "<|im_start|>" in chat_template:
                template_type = TemplateType.CHATML
            else:
                raise ValueError("Not supported chat template")
        self.type = template_type
        self.eos = eos

    def generate(self, items: list[ChatItem], append_generation_prompt: bool) -> GeneratedChat:
        buf = []
        public_prompt_size = 0
        eos = self.eos
        if self.type == TemplateType.LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                buf.append(
                    "[INST] <<SYS>>\n" + items[0].message + "\n<</SYS>>\n\n" + items[1].message + " [/INST]" + eos
                )
                i = 2
            for item in items[i:]:
                if item.role == "assistant":
                    buf.append(item.message + eos)
                elif item.role == "user":
                    buf.append("[INST] " + item.message + " [/INST]" + eos)
        elif self.type == TemplateType.LLAMA3:
            for item in items:
                buf.append(
                    "<|start_header_id|>" + item.role + "<|end_header_id|>\n\n" + item.message + eos
                )
            if append_generation_prompt:
                buf.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == TemplateType.CHATML:
            # <|im_start|>role\ncontent<|im_end|>\n per turn; the terminator
            # comes from the tokenizer's EOS piece (<|im_end|> for Qwen2).
            # Qwen's own template prepends a default system turn when the
            # conversation does not open with one — mirror that with the
            # Qwen2 default ("You are a helpful assistant."; Qwen2.5 ships a
            # longer brand-specific default — pass an explicit system
            # message to match it exactly).
            if not items or items[0].role != "system":
                buf.append(
                    "<|im_start|>system\nYou are a helpful assistant."
                    + eos + "\n"
                )
            for item in items:
                buf.append("<|im_start|>" + item.role + "\n" + item.message + eos + "\n")
            if append_generation_prompt:
                buf.append("<|im_start|>assistant\n")
        elif self.type == TemplateType.DEEP_SEEK3:
            i = 0
            if items and items[0].role == "system":
                buf.append(items[0].message)
                i = 1
            for item in items[i:]:
                if item.role == "user":
                    buf.append("<｜User｜>" + item.message)
                elif item.role == "assistant":
                    buf.append("<｜Assistant｜>" + item.message)
            if append_generation_prompt:
                buf.append("<｜Assistant｜><think>\n")
                public_prompt_size = 8
        content = "".join(buf)
        public_prompt = content[-public_prompt_size:] if public_prompt_size > 0 else None
        return GeneratedChat(content=content, public_prompt=public_prompt)
