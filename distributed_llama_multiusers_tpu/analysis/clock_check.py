"""clock: wall-clock reads are forbidden for durations, deadlines, seeds.

Every latency measurement, deadline, and retry hint in the serving path
runs on ``time.monotonic()`` / ``time.perf_counter()``; sampler seeds come
from OS entropy (``utils/seeds.py``). ``time.time()`` jumps under NTP
slew/step and DST-adjacent clock math, which turns queue timeouts, drain
windows, and Retry-After hints into lies — and two requests landing in
the same wall-clock microsecond used to get identical sampler seeds.

The only legitimate wall-clock use is an absolute timestamp leaving the
process (the OpenAI-compatible ``created`` fields); those sites carry
``# dlint: ok[clock]`` waivers. The check is import-aware, package-wide:
it flags dotted references through module aliases (``import time as t``
→ ``t.time``), naive-datetime "now" constructors through class imports
(``from datetime import datetime as dt`` → ``dt.now()``), and the
``from time import time`` import itself (the bound name has no
non-wall-clock use, so the import line is the finding).
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, Project, SourceFile

# members banned on the resolved dotted path
WALL_CLOCK_ATTRS = {
    "time.time",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
}
# `from <module> import <name>`: bindings banned at the import line
BANNED_FROM_IMPORTS = {("time", "time")}

_MESSAGE = (
    "is wall clock: use time.monotonic()/perf_counter() for durations and "
    "deadlines, utils.seeds.fresh_seed() for seeds; waive only absolute "
    "timestamps that leave the process (API 'created')"
)


class ClockChecker(Checker):
    name = "clock"
    description = (
        "time.time()/datetime.now() are wall clock — durations, deadlines "
        "and seeds must use time.monotonic()/perf_counter()/OS entropy"
    )

    def check(self, sf: SourceFile, project: Project):
        # name -> canonical dotted prefix it stands for:
        #   import time            -> {"time": "time"}
        #   import time as t       -> {"t": "time"}
        #   from datetime import datetime as dt -> {"dt": "datetime.datetime"}
        aliases: dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if (node.module, a.name) in BANNED_FROM_IMPORTS:
                        yield Finding(
                            self.name, sf.display, node.lineno,
                            f"'from {node.module} import {a.name}' binds the "
                            f"wall clock directly; '{node.module}.{a.name}' "
                            + _MESSAGE,
                        )
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Attribute):
                continue
            dotted = self._resolve(node, aliases)
            if dotted in WALL_CLOCK_ATTRS:
                yield Finding(
                    self.name, sf.display, node.lineno,
                    f"'{ast.unparse(node)}' " + _MESSAGE,
                )

    @staticmethod
    def _resolve(node: ast.Attribute, aliases: dict[str, str]) -> str | None:
        """Dotted path with the root name resolved through the import
        aliases; None when the chain doesn't start at a plain Name."""
        parts = [node.attr]
        cur = node.value
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = aliases.get(cur.id, cur.id)
        return ".".join([root, *reversed(parts)])
