"""Chaos suite: the failure-containment layer under deterministic faults.

The properties pinned here are the ISSUE 8 acceptance criteria:

- an engine-scoped fault mid-churn fails ONLY the requests holding lanes
  at that moment (``finish_reason="error"``, futures carry the
  request_id), the pipeline ring drains, and every later request's
  stream is byte-identical to a fault-free run — the loop thread never
  dies;
- the circuit breaker walks closed → open → half-open → closed over
  real ``/health`` + ``/stats`` HTTP reads;
- the watchdog fires on a stalled (blackholed) consume within its
  deadline and trips the breaker;
- a fault plan is a pure function of its spec: same seed, same schedule;
- control-plane packets carry a validated magic/version word: a torn or
  skewed packet is a classified ReplayError that does not burn a
  supervised-restart budget;
- the HTTP layer's bounded future waits turn a wedged scheduler into a
  request_id-carrying 503 instead of a hung socket.

Everything runs on the MockAsyncEngine (utils/testing.py) — tokens are
a pure function of (lane, position), so stream identity across a
contained failure is exact equality, with zero accelerator timing noise.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_llama_multiusers_tpu.runtime.scheduler import (
    ContinuousBatchingScheduler,
    EngineFailure,
    Request,
    classify_failure,
)
from distributed_llama_multiusers_tpu.serving import (
    AdmissionRejected,
    CircuitBreaker,
    StepWatchdog,
)
from distributed_llama_multiusers_tpu.utils import faults
from distributed_llama_multiusers_tpu.utils.faults import (
    FaultPlan,
    InjectedFault,
)
from distributed_llama_multiusers_tpu.utils.testing import (
    MockAsyncEngine,
    StubStreamTokenizer,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test leaves the process-global fault plan unarmed."""
    faults.disarm()
    yield
    faults.disarm()


def _sched(engine, **kw):
    kw.setdefault("speculative", False)
    kw.setdefault("prefix_min_tokens", 0)
    kw.setdefault("multi_step", 0)
    return ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size), **kw
    )


def _drive(engine, reqs, staggered=True, gap=None, **kw):
    """Submit ``reqs`` (staggered behind a live chain, or all up front)
    and wait for every future to RESOLVE — success or failure. Returns
    the scheduler."""
    sched = _sched(engine, **kw)
    sched.start()
    try:
        if staggered:
            sched.submit(reqs[0])
            deadline = time.monotonic() + 60
            while len(reqs[0].generated_tokens) < 2:
                assert time.monotonic() < deadline, "first request never ran"
                time.sleep(0.002)
            for r in reqs[1:]:
                sched.submit(r)
                time.sleep(gap if gap is not None else engine.step_s * 2)
        else:
            for r in reqs:
                sched.submit(r)
        for r in reqs:
            try:
                r.future.result(timeout=60)
            except Exception:  # noqa: BLE001 — failures are the subject here
                pass
    finally:
        sched.stop()
    return sched


def _reqs(n, max_tokens=20):
    return [
        Request(prompt="chaos request text", max_tokens=max_tokens,
                temperature=0.0)
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------


def test_classify_failure():
    assert classify_failure(ValueError("empty prompt")) == "request"
    assert classify_failure(RuntimeError("XLA boom")) == "engine"
    assert classify_failure(InjectedFault("engine.dispatch", 3)) == "engine"


# ---------------------------------------------------------------------------
# the headline: mid-churn engine fault, contained
# ---------------------------------------------------------------------------


def test_engine_fault_mid_churn_contained():
    """One injected dispatch fault mid-churn: the requests holding lanes
    fail with finish_reason="error" and an EngineFailure carrying their
    request_id; everything admitted afterwards completes with streams
    byte-identical to a fault-free run; the ring drains; the loop thread
    is still alive and serving."""
    n = 6
    base_engine = MockAsyncEngine(n_lanes=2, max_chunk=4)
    base_reqs = _reqs(n)
    _drive(base_engine, base_reqs, staggered=False, pipelined=False)
    base = [list(r.generated_tokens) for r in base_reqs]
    assert all(r.error is None for r in base_reqs)

    engine = MockAsyncEngine(n_lanes=2, max_chunk=4, step_s=0.002)
    reqs = _reqs(n)
    # fire once, well after the chain forms (the _drive gate waits for
    # the first request to be demonstrably generating)
    faults.arm("engine.dispatch:@10:n=1")
    sched = _drive(engine, reqs, staggered=True)

    failed = [r for r in reqs if r.finish_reason == "error"]
    ok = [r for r in reqs if r.finish_reason != "error"]
    assert failed, "the injected fault failed no request"
    assert len(failed) <= 2, "containment failed more lanes than exist"
    for r in failed:
        assert r.error and "injected fault" in r.error
        exc = r.future.exception()
        assert isinstance(exc, EngineFailure)
        assert exc.request_id == r.id  # the 500/SSE payload can name it
    # every unaffected request's stream is byte-identical to the
    # fault-free run (mock tokens are f(lane, pos): exact equality)
    by_prompt = {r.id: list(r.generated_tokens) for r in reqs}
    for r in ok:
        assert r.error is None, r.error
        assert by_prompt[r.id] in base, (
            f"stream of unaffected request {r.id} diverged from the "
            "fault-free run"
        )
    assert len(ok) == n - len(failed)
    # ring drained, loop survived long enough to serve everything after
    # the fault and to stop cleanly (sched.stop() in _drive did not raise)
    assert engine.pipeline_inflight() == 0
    assert not engine.pipeline_active
    snap = engine.stats.snapshot()
    assert snap["pipeline_dispatches"] > 6  # served on after containment
    stats = sched.qos_stats()
    assert stats["engine_failure_rounds"] == 1
    assert stats["engine_failures"].get("engine") == 1


def test_engine_fault_sync_path_contained():
    """The same containment on the synchronous (pipelined=False) path:
    a decode raise fails the active lanes and the loop keeps serving."""
    engine = MockAsyncEngine(n_lanes=2, max_chunk=4)
    reqs = _reqs(4, max_tokens=8)
    faults.arm("engine.dispatch:@3:n=1")
    sched = _drive(engine, reqs, staggered=False, pipelined=False)
    failed = [r for r in reqs if r.finish_reason == "error"]
    ok = [r for r in reqs if r.finish_reason != "error"]
    assert failed and ok
    assert all(len(r.generated_tokens) == 8 for r in ok)
    assert sched.qos_stats()["engine_failure_rounds"] == 1


def test_request_scoped_failure_fails_one_request():
    """A tokenizer failure (request-scoped) fails only that request —
    no containment round, no breaker movement, batch untouched."""

    class _BadTok(StubStreamTokenizer):
        def encode(self, text, add_bos=True, add_special_tokens=True):
            if "poison" in text:
                raise ValueError("tokenizer rejected prompt")
            return super().encode(text, add_bos, add_special_tokens)

    engine = MockAsyncEngine(n_lanes=2, max_chunk=4)
    sched = ContinuousBatchingScheduler(
        engine, _BadTok(engine.config.vocab_size), speculative=False,
        prefix_min_tokens=0, multi_step=0,
    )
    good = Request(prompt="fine", max_tokens=6, temperature=0.0)
    bad = Request(prompt="poison", max_tokens=6, temperature=0.0)
    sched.start()
    try:
        sched.submit(good)
        sched.submit(bad)
        assert good.future.result(timeout=60)is not None
        with pytest.raises(ValueError, match="tokenizer rejected"):
            bad.future.result(timeout=60)
    finally:
        sched.stop()
    assert bad.finish_reason == "error"
    assert good.error is None and len(good.generated_tokens) == 6
    stats = sched.qos_stats()
    assert stats["engine_failure_rounds"] == 0
    assert stats["breaker_state"] == "closed"
    assert stats["engine_failures"].get("request") == 1


# ---------------------------------------------------------------------------
# circuit breaker: transitions over /health + /stats
# ---------------------------------------------------------------------------


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_breaker_unit_transitions():
    b = CircuitBreaker(threshold=2, cooldown_s=0.05)
    assert b.state == "closed" and b.allow()
    b.record_engine_failure("one")
    assert b.state == "closed"  # not consecutive enough yet
    b.record_success()
    b.record_engine_failure("one")
    b.record_engine_failure("two")
    assert b.state == "open"
    assert not b.allow()  # inside cooldown: shed
    assert b.retry_after_s() >= 1.0
    time.sleep(0.06)
    assert b.allow()  # the probe
    assert b.state == "half_open"
    assert not b.allow()  # only one probe per window
    b.record_engine_failure("probe failed")
    assert b.state == "open"  # probe failure re-opens
    time.sleep(0.06)
    assert b.allow()
    b.record_success()
    assert b.state == "closed"
    s = b.stats()
    assert s["breaker_trips"] == 2
    assert s["engine_failures"]["engine"] == 4
    assert s["breaker_last_recovery_s"] is not None


def test_breaker_over_health_and_stats_http():
    """closed → open (engine faults) → half-open probe → closed, observed
    through real /health and /stats HTTP reads, with shed submissions
    getting 503 + Retry-After."""
    from distributed_llama_multiusers_tpu.server import ApiServer
    from distributed_llama_multiusers_tpu.tokenizer import TemplateType

    engine = MockAsyncEngine(n_lanes=2, max_chunk=4)
    tok = StubStreamTokenizer(engine.config.vocab_size)
    breaker = CircuitBreaker(threshold=1, cooldown_s=0.3)
    sched = ContinuousBatchingScheduler(
        engine, tok, speculative=False, prefix_min_tokens=0, multi_step=0,
        breaker=breaker,
    )
    api = ApiServer(sched, tok, model_name="chaos-test",
                    template_type=TemplateType.LLAMA2)
    httpd = api.serve(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    sched.start()
    try:
        status, body = _get(base + "/health")
        assert status == 200 and body["status"] == "ok"

        # one engine fault trips the threshold-1 breaker
        faults.arm("engine.dispatch:@1:n=1")
        victim = Request(prompt="x", max_tokens=4, temperature=0.0)
        sched.submit(victim)
        with pytest.raises(EngineFailure):
            victim.future.result(timeout=60)

        status, body = _get(base + "/health")
        assert status == 503 and body["status"] == "unhealthy"
        assert body["breaker"] == "open"
        status, stats = _get(base + "/stats")
        assert stats["breaker_state"] == "open"
        assert stats["breaker_state_code"] == 2
        assert stats["engine_failures"]["engine"] == 1

        # shed while open: typed 503 with Retry-After
        with pytest.raises(AdmissionRejected) as ei:
            sched.submit(Request(prompt="y", max_tokens=4))
        assert ei.value.reason == "breaker_open"
        assert ei.value.http_status == 503
        status, stats = _get(base + "/stats")
        assert stats["breaker_shed"] >= 1
        assert stats["queue_rejected_breaker"] >= 1

        # /metrics carries the native gauge + classified counter
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "dllama_breaker_state 2" in text
        assert (
            'dllama_engine_failures_total{failure_class="engine"} 1' in text
        )

        # cooldown elapses: the next submit is the half-open probe, its
        # success closes the breaker
        time.sleep(0.35)
        probe = sched.submit(Request(prompt="z", max_tokens=4,
                                     temperature=0.0))
        probe.future.result(timeout=60)
        assert probe.error is None
        deadline = time.monotonic() + 10
        while breaker.state != "closed":
            assert time.monotonic() < deadline, breaker.stats()
            time.sleep(0.01)
        status, body = _get(base + "/health")
        assert status == 200 and body["status"] == "ok"
        status, stats = _get(base + "/stats")
        assert stats["breaker_state"] == "closed"
        assert stats["breaker_probes"] >= 1
        assert stats["breaker_last_recovery_s"] is not None
    finally:
        httpd.shutdown()
        sched.stop()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_stalled_consume():
    """A blackholed consume (kind=hang fault) trips the watchdog within
    its deadline: the breaker opens while the step is still stuck, and
    serving resumes once the hang clears."""
    engine = MockAsyncEngine(n_lanes=2, max_chunk=4, step_s=0.002)
    # one consume blackholes for ~1.2s; watchdog deadline 0.25s
    faults.arm("engine.consume:@4:n=1:kind=hang:hang=1.2")
    sched = _sched(engine, step_deadline_s=0.25)
    req = Request(prompt="stall", max_tokens=30, temperature=0.0)
    t0 = time.monotonic()
    sched.start()
    try:
        sched.submit(req)
        # the breaker must open while the consume is still blackholed
        deadline = time.monotonic() + 30
        while sched.breaker.state != "open":
            assert time.monotonic() < deadline, (
                "watchdog never tripped the breaker"
            )
            time.sleep(0.01)
        tripped_after = time.monotonic() - t0
        # fired within the deadline's order of magnitude, not the hang's
        assert tripped_after < 1.2, tripped_after
        assert sched.watchdog.stats()["watchdog_trips"] == 1
        # the hang clears; the request still completes (slow, not dead)
        req.future.result(timeout=60)
        assert req.error is None
        assert len(req.generated_tokens) == 30
    finally:
        sched.stop()
    stats = sched.qos_stats()
    assert stats["engine_failures"].get("watchdog") == 1
    assert stats["watchdog_trips"] == 1


def test_breaker_holds_open_across_watchdog_trip_no_flap():
    """ISSUE-10 satellite: breaker/watchdog interaction under repeated
    injected faults. A watchdog trip opens the breaker; the blackholed
    step then RETURNS (slow, not dead) and successful steps resume
    immediately — but the breaker must NOT flap closed off those early
    successes (`record_success` from OPEN closes only once the cooldown
    has held), and a later injected engine fault during the same window
    must not re-count a trip. One trip, one recovery, monotone
    closed -> open -> closed."""
    engine = MockAsyncEngine(n_lanes=2, max_chunk=4, step_s=0.002)
    # one consume blackholes for ~0.9s (watchdog deadline 0.2s) AND two
    # dispatch faults land while the breaker is already open: repeated
    # faults across the trip window
    faults.arm(
        "engine.consume:@4:n=1:kind=hang:hang=0.9;"
        "engine.dispatch:@40:n=2"
    )
    breaker = CircuitBreaker(threshold=3, cooldown_s=0.6)
    sched = _sched(engine, step_deadline_s=0.2, breaker=breaker)
    reqs = _reqs(4, max_tokens=40)
    sched.start()
    flapped = []
    stop_probe = threading.Event()

    def probe():
        # watch for an open->closed transition BEFORE the cooldown held
        opened_at = None
        while not stop_probe.is_set():
            s = breaker.state
            now = time.monotonic()
            if s == "open" and opened_at is None:
                opened_at = now
            elif s == "closed" and opened_at is not None:
                if now - opened_at < 0.5:  # cooldown is 0.6
                    flapped.append(now - opened_at)
                opened_at = None
            time.sleep(0.005)

    watcher = threading.Thread(target=probe, daemon=True)
    watcher.start()
    try:
        for r in reqs:
            try:
                sched.submit(r)
            except AdmissionRejected:
                pass  # shed while open is correct behavior
            time.sleep(0.05)
        deadline = time.monotonic() + 30
        while breaker.state != "open":
            assert time.monotonic() < deadline, "watchdog never tripped"
            time.sleep(0.01)
        # recovery: successful steps + cooldown close it exactly once
        deadline = time.monotonic() + 30
        while breaker.state != "closed":
            assert time.monotonic() < deadline, "breaker never recovered"
            time.sleep(0.02)
        for r in reqs:
            if r.future.done() or r.submitted_at is not None:
                try:
                    r.future.result(timeout=60)
                except Exception:  # noqa: BLE001 — faulted ones may error
                    pass
    finally:
        stop_probe.set()
        watcher.join(timeout=5)
        sched.stop()
    assert flapped == [], f"breaker flapped closed early: {flapped}"
    br = breaker.stats()
    # ONE trip (the watchdog's): the dispatch faults inside the open
    # window are contained + counted but never re-trip an open breaker,
    # and the early successes never closed it before the cooldown held
    assert br["breaker_trips"] == 1, br
    assert br["breaker_state"] == "closed"
    assert sched.watchdog.stats()["watchdog_trips"] == 1


def test_watchdog_unit_no_false_trip():
    """Armed steps that finish inside the deadline never trip; an armed
    step past the deadline trips exactly once."""
    trips = []
    wd = StepWatchdog(0.1, on_trip=trips.append)
    wd.start()
    try:
        for _ in range(5):
            wd.begin_step()
            time.sleep(0.01)
            wd.step_done()
        time.sleep(0.25)  # idle (disarmed): no trip
        assert trips == []
        wd.begin_step()
        deadline = time.monotonic() + 5
        while not trips:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        time.sleep(0.25)  # tripped once, stays disarmed
        assert len(trips) == 1
        assert trips[0] >= 0.1
    finally:
        wd.stop()
    assert wd.stats()["watchdog_trips"] == 1


# ---------------------------------------------------------------------------
# fault plan determinism
# ---------------------------------------------------------------------------


def test_fault_plan_determinism():
    """Same spec (same seed) → same schedule, both via the pure
    schedule() enumeration and via live fire() counting."""
    spec = "engine.dispatch:p=0.3,seed=42:n=5;engine.consume:@3+4"
    a = FaultPlan.parse(spec)
    b = FaultPlan.parse(spec)
    assert a.schedule("engine.dispatch", 50) == b.schedule(
        "engine.dispatch", 50
    )
    assert a.schedule("engine.consume", 20) == [3, 7, 11, 15, 19]
    # live fires land exactly on the precomputed schedule
    want = a.schedule("engine.dispatch", 50)
    fired = []
    for i in range(1, 51):
        try:
            a.fire("engine.dispatch")
        except InjectedFault as f:
            assert f.arrival == i
            fired.append(i)
    assert fired == want
    assert len(fired) == 5  # the n=5 cap held
    # a different seed produces a different schedule (overwhelmingly)
    c = FaultPlan.parse("engine.dispatch:p=0.3,seed=43:n=5")
    assert c.schedule("engine.dispatch", 50) != want


def test_fault_plan_parse_errors():
    with pytest.raises(ValueError, match="unknown fault point"):
        FaultPlan.parse("engine.bogus:@1")
    with pytest.raises(ValueError, match="trigger"):
        FaultPlan.parse("engine.dispatch")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("engine.dispatch:@1:kind=explode")
    with pytest.raises(ValueError, match="empty fault spec"):
        FaultPlan.parse(" ; ")


def test_faults_env_arming(monkeypatch):
    monkeypatch.setenv("DLLAMA_FAULTS", "engine.dispatch:@2:n=1")
    plan = faults.maybe_arm_from_env()
    assert plan is not None and faults.armed()
    faults.fire("engine.dispatch")  # arrival 1: no fire
    with pytest.raises(InjectedFault):
        faults.fire("engine.dispatch")
    faults.disarm()
    assert not faults.armed()
    faults.fire("engine.dispatch")  # unarmed: no-op


# ---------------------------------------------------------------------------
# control-plane packet integrity
# ---------------------------------------------------------------------------


def test_packet_magic_and_version_validated():
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        PACKET_MAGIC,
        PROTOCOL_VERSION,
        ControlPlane,
        ReplayError,
    )

    sent = []

    class _Plane(ControlPlane):
        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    plane = _Plane(n_lanes=2, chunk=8)
    plane.send_stop()
    pkt = sent[0]
    assert int(pkt[0]) == PACKET_MAGIC
    assert int(pkt[1]) == PROTOCOL_VERSION
    ControlPlane.validate(pkt)  # round-trips clean

    torn = pkt.copy()
    torn[0] = 0xDEAD
    with pytest.raises(ReplayError, match="magic mismatch"):
        ControlPlane.validate(torn)

    skewed = pkt.copy()
    skewed[1] = PROTOCOL_VERSION + 1
    with pytest.raises(ReplayError, match="protocol version"):
        ControlPlane.validate(skewed)

    # a truncated (even empty) packet is still the CLASSIFIED error, not
    # an IndexError burning a restart
    with pytest.raises(ReplayError, match="truncated"):
        ControlPlane.validate(np.zeros(0, np.int32))
    with pytest.raises(ReplayError, match="truncated"):
        ControlPlane.validate(pkt[:3])


def test_pod_root_pipeline_abort_broadcasts_flush():
    """Containment on a pod root must tell the workers: pipeline_abort
    broadcasts OP_PIPELINE_FLUSH (the drain op workers already honor)
    before aborting the root ring WITHOUT consuming — a silent
    __getattr__ forward would leave worker rings permanently diverged
    and burn their restart budgets on every later pipelined packet."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_PIPELINE_FLUSH,
        ControlPlane,
        RootControlEngine,
    )

    sent = []

    class _Plane(ControlPlane):
        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    class _Inner:
        n_lanes = 2
        aborted = 0
        consumed = 0

        def pipeline_abort(self):
            self.aborted += 1
            return 2

        def pipeline_consume(self):  # must NOT be called: it would re-raise
            self.consumed += 1

    inner = _Inner()
    root = RootControlEngine(inner, _Plane(n_lanes=2, chunk=8))
    assert root.pipeline_abort() == 2
    assert inner.aborted == 1 and inner.consumed == 0
    assert len(sent) == 1 and int(sent[0][2]) == OP_PIPELINE_FLUSH


def test_worker_serve_protocol_errors_do_not_burn_restarts():
    """Torn packets interleaved with good replays: worker_serve absorbs
    them as classified protocol errors WITHOUT burning its (tiny) restart
    budget, keeps replaying, counts them on engine.stats, and still exits
    on stop."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_DECODE,
        OP_STOP,
        PACKET_MAGIC,
        PROTOCOL_VERSION,
        worker_serve,
    )
    from distributed_llama_multiusers_tpu.runtime.engine import EngineStats

    class _Plane:
        HEADER = 6

        def __init__(self, script, chunk=8):
            self.chunk = chunk
            self._pkts = [self._pkt(kind) for kind in script]

        def _pkt(self, kind):
            from distributed_llama_multiusers_tpu.parallel.multihost import (
                ControlPlane,
            )

            pkt = np.zeros(self.HEADER + 7 * self.chunk, np.int32)
            if kind == "torn":
                pkt[0:6] = (0xBAD, PROTOCOL_VERSION, OP_DECODE, 0, 2, 0)
            elif kind == "skewed":
                pkt[0:6] = (PACKET_MAGIC, 99, OP_DECODE, 0, 2, 0)
            elif kind == "unknown":
                pkt[0:6] = (PACKET_MAGIC, PROTOCOL_VERSION, 777, 0, 2, 0)
            else:
                pkt[0:6] = (PACKET_MAGIC, PROTOCOL_VERSION, kind, 0, 2, 0)
            return pkt

        def recv(self):
            from distributed_llama_multiusers_tpu.parallel.multihost import (
                ControlPlane,
            )

            pkt = self._pkts.pop(0)
            ControlPlane.validate(pkt)
            return pkt

        def slot(self, pkt, i, n):
            start = self.HEADER + i * self.chunk
            return pkt[start : start + n]

    class _Eng:
        SPEC_DRAFT = 3
        stats = EngineStats()

        def __init__(self):
            self.calls = 0

        def decode(self, *a, want_logits=True, g_states=None):
            self.calls += 1

    script = [OP_DECODE, "torn", OP_DECODE, "skewed", OP_DECODE,
              "unknown", OP_DECODE, OP_STOP]
    engine = _Eng()
    # max_restarts=0: ANY non-classified error would raise immediately —
    # surviving the script proves protocol errors burn no restarts
    worker_serve(engine, _Plane(script), max_restarts=0, log=lambda m: None)
    assert engine.calls == 4  # every good packet replayed
    snap = engine.stats.snapshot()
    assert snap["worker_replay_errors"] == 3
    assert snap["worker_restarts"] == 0


def test_worker_serve_engine_errors_still_bounded():
    """Engine replay errors (post-validation) still burn the budget and
    raise when persistent — the desync signature must stay fatal."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_DECODE,
        worker_serve,
    )
    from distributed_llama_multiusers_tpu.runtime.engine import EngineStats

    class _Plane:
        HEADER = 6

        def __init__(self, n, chunk=8):
            from distributed_llama_multiusers_tpu.parallel.multihost import (
                PACKET_MAGIC,
                PROTOCOL_VERSION,
            )

            self.chunk = chunk
            pkt = np.zeros(self.HEADER + 7 * self.chunk, np.int32)
            pkt[0:6] = (PACKET_MAGIC, PROTOCOL_VERSION, OP_DECODE, 0, 2, 0)
            self._pkts = [pkt.copy() for _ in range(n)]

        def recv(self):
            return self._pkts.pop(0)

        def slot(self, pkt, i, n):
            start = self.HEADER + i * self.chunk
            return pkt[start : start + n]

    class _Eng:
        SPEC_DRAFT = 3
        stats = EngineStats()

        def __init__(self):
            self.calls = 0

        def decode(self, *a, want_logits=True, g_states=None):
            self.calls += 1
            raise RuntimeError(f"replay #{self.calls}")

    engine = _Eng()
    with pytest.raises(RuntimeError, match="replay"):
        worker_serve(engine, _Plane(20), max_restarts=2, log=lambda m: None)
    assert engine.calls == 3  # restarts 1..3 > max_restarts=2
    assert engine.stats.snapshot()["worker_restarts"] == 3


# ---------------------------------------------------------------------------
# HTTP defense-in-depth: bounded waits
# ---------------------------------------------------------------------------


def test_http_bounded_wait_maps_to_503():
    """A scheduler that never resolves a future cannot hang a client
    socket: the server's bounded wait turns it into a request_id-carrying
    503 with Retry-After."""
    from distributed_llama_multiusers_tpu.server import ApiServer

    class _WedgedScheduler:
        """Accepts submissions and never serves them."""

        draining = False

        def __init__(self):
            self.cancelled = []

        def submit(self, req):
            req.submitted_at = time.monotonic()
            return req

        def occupancy(self):
            return (0, 1)

        class _E:
            class _S:
                @staticmethod
                def snapshot():
                    import collections

                    return collections.defaultdict(int, {
                        "pipeline_depth_hist": {}, "fused_bucket_hist": {},
                    })

            stats = _S()

        engine = _E()

    from distributed_llama_multiusers_tpu.tokenizer import TemplateType

    sched = _WedgedScheduler()
    tok = StubStreamTokenizer(64)
    api = ApiServer(sched, tok, model_name="wedged", result_timeout_s=0.3,
                    template_type=TemplateType.LLAMA2)
    httpd = api.serve(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        body = json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 4,
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        waited = time.monotonic() - t0
        assert ei.value.code == 503
        assert waited < 10  # bounded, not the urllib timeout
        payload = json.loads(ei.value.read())
        assert payload["reason"] == "stalled"
        assert "request_id" in payload
        assert ei.value.headers.get("Retry-After") is not None
    finally:
        httpd.shutdown()


# ---------------------------------------------------------------------------
# containment cleanup: the truly-fatal path still resolves futures
# ---------------------------------------------------------------------------


def test_fatal_loop_exit_still_resolves_futures():
    """Even when containment itself fails (engine so broken that failing
    lanes raises again — simulated with an engine whose every surface
    raises), the finally-path cleanup resolves every future."""

    class _BrokenEngine(MockAsyncEngine):
        def decode(self, *a, **kw):
            raise RuntimeError("dead device")

        def decode_pipelined(self, *a, **kw):
            raise RuntimeError("dead device")

        def prefill_chunk(self, *a, **kw):
            raise RuntimeError("dead device")

        def pipeline_abort(self):
            raise RuntimeError("even abort is dead")

    engine = _BrokenEngine(n_lanes=2, max_chunk=4)
    sched = _sched(engine, breaker=CircuitBreaker(threshold=2,
                                                  cooldown_s=30.0))
    reqs = _reqs(3, max_tokens=4)
    sched.start()
    try:
        for r in reqs:
            try:
                sched.submit(r)
            except AdmissionRejected:
                r.future.set_exception(RuntimeError("shed"))
        for r in reqs:
            with pytest.raises(Exception):
                r.future.result(timeout=60)
    finally:
        sched.stop()
    # every future resolved; the loop thread exited via stop() cleanly
    assert all(r.future.done() for r in reqs)
    assert sched.breaker.state == "open"
