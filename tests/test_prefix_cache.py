"""Prefix caching: requests sharing a prompt prefix with KV already
resident in some lane skip re-prefilling the prefix via a whole-lane HBM
copy (engine.copy_lane) + tail prefill.

No reference analogue — its lanes share a single KV cache (SURVEY.md §2
defect (c)), which makes per-lane prefix reuse impossible there. The
invariant under test is exactness: a prefix-cached request must produce
token streams identical to a cold prefill, because the copied KV slots are
the same values a fresh prefill would have written (prefill is
deterministic given tokens+positions).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

# char-level prompt-DEPENDENT tokenizer (shared text prefixes become
# shared token prefixes): one home in utils/testing.py, shared with the
# bench's serving_prefix phase so the two encodings cannot drift
from distributed_llama_multiusers_tpu.utils.testing import (
    CharStreamTokenizer as _CharTokenizer,
)


@pytest.fixture(scope="module")
def loaded(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    return config, params, tok


def _engine(config, params, n_lanes=2):
    return InferenceEngine(config, params, n_lanes=n_lanes, prefill_buckets=(8,))


def test_copy_lane_then_tail_prefill_matches_cold_prefill(loaded):
    """copy_lane + tail prefill == full prefill, bit-for-bit logits."""
    config, params, _ = loaded
    full = [5, 9, 3, 17, 2, 11, 7, 4, 13, 6]
    split = 8

    cold = _engine(config, params)
    logits_cold, greedy_cold, _ = cold.prefill(1, full)

    warm = _engine(config, params)
    warm.prefill(0, full[:split])  # prefix resident in lane 0
    warm.copy_lane(0, 1)
    logits_warm, greedy_warm, _ = warm.prefill(1, full[split:], start_pos=split)

    assert int(greedy_warm) == int(greedy_cold)
    np.testing.assert_array_equal(
        np.asarray(logits_warm), np.asarray(logits_cold)
    )


def _run(engine, tok, reqs, **sched_kw):
    sched = ContinuousBatchingScheduler(engine, tok, **sched_kw)
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs]


def test_scheduler_prefix_hit_skips_prefill_and_keeps_stream(loaded):
    """Sequential requests with a shared long system prefix: the second
    admission reuses the first lane's KV (prefix_hits/prefix_tokens_saved
    count it, fewer prefill chunks run) and the generated stream is
    IDENTICAL to a prefix-cache-disabled scheduler."""
    config, params, tok = loaded
    system = "aa bb cc dd ee ff gg hh "  # long shared prefix (char-level tok)
    prompts = [system + "11", system + "22"]

    def reqs():
        return [Request(prompt=p, max_tokens=8, temperature=0.0) for p in prompts]

    engine = _engine(config, params)
    chunks = []
    real = engine.prefill_chunk

    def spy(lane, chunk, start_pos, **kw):
        chunks.append((lane, len(chunk), start_pos))
        return real(lane, chunk, start_pos, **kw)

    engine.prefill_chunk = spy

    def run_sequential(eng, **kw):
        sched = ContinuousBatchingScheduler(eng, tok, **kw)
        sched.start()
        out = []
        try:
            for r in reqs():
                sched.submit(r)
                r.future.result(timeout=300)
                assert r.error is None, r.error
                out.append(list(r.generated_tokens))
        finally:
            sched.stop()
        return out

    got_hit = run_sequential(engine)
    assert engine.stats.prefix_hits == 1
    # the second request's prompt processing started past the shared
    # prefix: no prefill chunk after the first request re-ran position 0
    first_prompt_chunks = -(-len(tok.encode(prompts[0])) // 8)  # ceil div
    assert all(c[2] > 0 for c in chunks[first_prompt_chunks:]), chunks
    n_shared = len(tok.encode(prompts[0][:-2]))
    assert engine.stats.prefix_tokens_saved >= n_shared - 8  # >= prefix - bucket

    plain_engine = _engine(config, params)
    got_plain = run_sequential(plain_engine, prefix_min_tokens=0)
    assert got_hit == got_plain
    assert plain_engine.stats.prefix_hits == 0
    # the cached run prefilled strictly fewer prompt tokens
    assert engine.stats.prefill_tokens < plain_engine.stats.prefill_tokens


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_scheduler_prefix_concurrent_batch_identical_streams(loaded):
    """Two concurrent requests sharing a prefix (second admitted while the
    first may still be prefilling — only committed chunks are reusable):
    streams match the prefix-disabled scheduler exactly."""
    config, params, tok = loaded
    system = "aa bb cc dd ee ff "

    def reqs():
        return [
            Request(prompt=system + "xx", max_tokens=8, temperature=0.0),
            Request(prompt=system + "yy", max_tokens=8, temperature=0.0),
            Request(prompt="zz unrelated", max_tokens=6, temperature=0.0),
        ]

    got_hit = _run(_engine(config, params, n_lanes=4), tok, reqs())
    got_plain = _run(
        _engine(config, params, n_lanes=4), tok, reqs(), prefix_min_tokens=0
    )
    assert got_hit == got_plain


def test_pod_root_engine_broadcasts_copy_lane():
    """RootControlEngine.copy_lane must broadcast OP_COPY_LANE before the
    root-side call (a silent __getattr__ forward would desync the pod),
    and worker_loop must replay it."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_COPY_LANE,
        ControlPlane,
        RootControlEngine,
        worker_loop,
    )

    sent = []

    class _Plane(ControlPlane):
        def _bcast(self, pkt):
            sent.append(np.array(pkt))
            return pkt

    class _Inner:
        n_lanes = 2
        copied = None

        def copy_lane(self, src, dst):
            self.copied = (src, dst)

    inner = _Inner()
    root = RootControlEngine(inner, _Plane(n_lanes=2, chunk=8))
    root.copy_lane(0, 1)
    assert inner.copied == (0, 1)
    assert len(sent) == 1
    # header: [magic, version, op, lane, n, start_pos]
    assert list(sent[0][2:6]) == [OP_COPY_LANE, 0, 0, 1]
    root.copy_lane(1, 1)  # no-op: nothing broadcast, nothing dispatched
    assert len(sent) == 1

    # worker side replays the header operands
    class _WEngine:
        copied = None

        def copy_lane(self, src, dst):
            self.copied = (src, dst)

    from tests.test_multihost import _ScriptedPlane
    from distributed_llama_multiusers_tpu.parallel.multihost import OP_STOP

    weng = _WEngine()
    plane = _ScriptedPlane([OP_COPY_LANE, OP_STOP])
    # _ScriptedPlane packs (magic, version, op, 0, 2, 0); patch the copy
    # packet's operands (lane=src at header slot 3, start_pos=dst at 5)
    plane._pkts[0][3] = 1  # src
    plane._pkts[0][5] = 0  # dst
    worker_loop(weng, plane)
    assert weng.copied == (1, 0)


# ---------------------------------------------------------------------------
# Paged KV pool + ref-counted cross-request prefix tree (runtime/kvpool.py):
# prefix reuse becomes a refcount bump on SHARED physical pages (zero HBM
# copies — copy_lane is refused on paged engines), divergence is a single-
# page copy-on-write, finished sessions park so resident sessions exceed
# lanes, and the whole thing is pinned byte-identical to the contiguous
# layout. Pool bookkeeping is pure host/stdlib, so the unit tests below run
# without a backend; the byte-identity pins use the real engine.
# ---------------------------------------------------------------------------


def test_kvpool_cow_at_divergent_block():
    """Full shared blocks map to the SAME physical pages (refcount bump);
    the first divergent block is served by exactly one single-page COW
    into the new lane's private page."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=16, page_size=4, n_lanes=2)
    a = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]
    start, blocks, copies, _sw = pool.admit(0, a, reserve_tokens=12,
                                       min_share_tokens=4)
    assert (start, copies) == (0, [])
    pool.commit(0, a + [11, 12])  # 3 full blocks enter the tree
    pool.finish(0, park=True)  # parked: pages stay resident + refcounted

    # b shares block 0 exactly and diverges INSIDE block 1 (after 5, 6)
    b = [1, 2, 3, 4, 5, 6, 99, 100, 101]
    start, blocks2, copies, _sw = pool.admit(1, b, reserve_tokens=12,
                                        min_share_tokens=4)
    assert start == 6  # 4 tokens by refcount + 2 by copy-on-write
    assert blocks2[0] == blocks[0]  # full block: same physical page
    assert blocks2[1] != blocks[1]  # divergent block: private page
    assert copies == [(blocks[1], blocks2[1])]  # ONE single-page copy
    s = pool.stats()
    assert s["pool_cow_copies"] == 1
    assert s["pool_prefix_admits"] == 1
    assert s["pool_prefix_tokens_shared"] == 6


def test_kvpool_refcount_zero_page_reuse():
    """finish(park=False) drains every refcount: all pages return to the
    free list, their tree nodes die with them (no stale sharing), and the
    next admission recycles the same physical pages."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=4, page_size=4, n_lanes=2, max_parked=4)
    toks = [1, 2, 3, 4, 5, 6]
    _, blocks, _, _sw = pool.admit(0, toks, reserve_tokens=8)
    pool.commit(0, [1, 2, 3, 4, 5, 6, 7, 8])
    pool.finish(0, park=False)  # failure path: nothing parks
    assert pool.pages_free() == 4
    # the tree nodes died with their pages: identical content shares 0
    start2, blocks2, _, _sw = pool.admit(1, toks, reserve_tokens=8,
                                    min_share_tokens=1)
    assert start2 == 0
    assert sorted(blocks2) == sorted(blocks)  # same physical pages, reused
    pool.release(1)

    # park=True pins the registered blocks instead; drop_parked frees them
    pool.admit(0, toks, reserve_tokens=8)
    pool.commit(0, [1, 2, 3, 4, 5, 6, 7, 8])
    pool.finish(0, park=True)
    assert pool.parked_sessions() == 1
    assert pool.pages_free() == 2  # 2 registered blocks stay resident
    assert pool.drop_parked() == 1
    assert pool.pages_free() == 4


def test_kvpool_exhaustion_evicts_parked_then_sheds():
    """An admission the free list cannot serve first LRU-evicts parked
    sessions (drop-rebuild); only a pool pinned by ACTIVE lanes raises
    the typed PoolExhausted the scheduler maps to a retryable 429."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import (
        KVPagePool,
        PoolExhausted,
    )

    pool = KVPagePool(n_pages=4, page_size=4, n_lanes=2, max_parked=4)
    pool.admit(0, [1, 2, 3, 4, 5], reserve_tokens=8)
    pool.commit(0, [1, 2, 3, 4])
    pool.finish(0, park=True)  # 1 registered page parked, tail freed
    assert pool.parked_sessions() == 1

    # needs 4 pages, 3 free: the parked session is evicted, not shed
    pool.admit(1, list(range(10, 25)), reserve_tokens=16)
    assert pool.parked_sessions() == 0
    assert pool.stats()["pool_parked_evicted"] == 1

    # pool now pinned by the ACTIVE lane 1: this one must shed, typed
    with pytest.raises(PoolExhausted) as ei:
        pool.admit(0, [1, 2, 3], reserve_tokens=16)
    assert ei.value.pages_needed == 4
    assert ei.value.pages_free == 0
    assert ei.value.pages_total == 4
    assert pool.stats()["pool_exhausted_sheds"] == 1


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_paged_table_updates_keep_mesh_sharding(loaded):
    """Table replacements must carry the cache's replicated NamedSharding
    on a mesh: a bare jnp.asarray leaf changes the compiled programs'
    input aval — every warmed step family recompiles per admission on a
    single-host tp mesh, and a multi-process pod fails outright with
    incompatible devices. Streams must also match the mesh-free paged
    engine exactly."""
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine

    config, params, tok = loaded
    mesh = make_mesh(MeshPlan(tp=2))
    engine = InferenceEngine(config, params, n_lanes=2,
                             prefill_buckets=(8,), paged_kv=True,
                             kv_page_size=8, mesh=mesh)
    want_sh = engine.cache.table.sharding
    warmup_engine(engine, spec=False)  # includes the COW page copy
    ndim = engine.cache.table.ndim
    assert engine.cache.table.sharding.is_equivalent_to(want_sh, ndim)
    start = engine.paged_admit(0, list(range(2, 12)), 14)
    assert start == 0
    assert engine.cache.table.sharding.is_equivalent_to(want_sh, ndim)
    engine.paged_finish(0, park=False)
    assert engine.cache.table.sharding.is_equivalent_to(want_sh, ndim)

    plain = InferenceEngine(config, params, n_lanes=2,
                            prefill_buckets=(8,), paged_kv=True,
                            kv_page_size=8)
    streams = []
    for eng in (engine, plain):
        sched = ContinuousBatchingScheduler(eng, tok)
        sched.start()
        try:
            r = Request(prompt="mesh paged parity", max_tokens=6,
                        temperature=0.0)
            sched.submit(r)
            r.future.result(timeout=120)
            assert r.error is None, r.error
            streams.append(list(r.generated_tokens))
        finally:
            sched.stop()
    assert streams[0] == streams[1]


def test_warmup_compiles_paged_cow_program(loaded):
    """warmup_engine pre-compiles the single-page COW copy on paged
    engines: the first divergent-block admission runs mid-chain on the
    scheduler loop, where a lazy XLA compile would stall every lane
    behind the dispatch (the warmup contract every other step family
    already has)."""
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine

    config, params, _ = loaded
    engine = InferenceEngine(config, params, n_lanes=2,
                             prefill_buckets=(8,), paged_kv=True,
                             kv_page_size=8)
    warmup_engine(engine, spec=False)
    assert engine._copy_page_fn._cache_size() == 1
    # and the warmup copy left lane 0's table in its initial unmapped
    # state (page 0 onto itself moved zeros over zeros)
    assert int(np.asarray(engine.cache.table).max()) == engine.kvpool.n_pages
    # releasing a lane that never mapped anything (the exhaustion-shed
    # reject path) dispatches NO device-side table update
    t0 = engine.cache.table
    engine.paged_finish(0)
    assert engine.cache.table is t0


def test_kvpool_unservable_reservation_is_not_retryable():
    """A reservation structurally larger than the whole pool (an
    explicitly undersized --kv-pool-pages) raises ValueError — the
    scheduler's request-scoped validation class — not the retryable
    PoolExhausted: a 429 would have the client back off and re-probe
    forever, each probe destructively evicting parked prefixes. The
    check fires BEFORE eviction, so parked sessions survive."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=3, page_size=4, n_lanes=2,
                      blocks_per_lane=8, max_parked=4)
    pool.admit(0, [1, 2, 3, 4, 5], reserve_tokens=8)  # 2 pages
    pool.commit(0, [1, 2, 3, 4])
    pool.finish(0, park=True)
    assert pool.parked_sessions() == 1

    # needs 4 pages, pool holds 3 total: no eviction could ever serve it
    with pytest.raises(ValueError, match="pool holds 3 total"):
        pool.admit(1, [9, 9, 9], reserve_tokens=16)
    # the probe evicted nothing and shed nothing (it is not load)
    assert pool.parked_sessions() == 1
    assert pool.stats()["pool_parked_evicted"] == 0
    assert pool.stats()["pool_exhausted_sheds"] == 0

    # a servable reservation still works afterwards, sharing the parked
    # prefix untouched by the failed probe
    start, _, _, _sw = pool.admit(1, [1, 2, 3, 4, 5], reserve_tokens=8,
                             min_share_tokens=4)
    assert start == 4

    # explicit invalid geometry dies in validation, never a silent
    # fallback: 0/negative pool_pages and non-positive page sizes
    with pytest.raises(ValueError):
        KVPagePool.for_seq_len(64, 2, pool_pages=0)
    with pytest.raises(ValueError):
        KVPagePool.for_seq_len(64, 2, page_size=0)


def test_kvpool_repark_identical_chain_occupies_one_lru_slot():
    """A client replaying the same prompt must not flood the parked LRU
    with duplicate holders of the same pages: each re-park refreshes
    the existing entry's recency, so other users' parked prefixes are
    not evicted by one repetitive session."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=16, page_size=4, n_lanes=2, max_parked=2)
    other = [9, 8, 7, 6, 5]
    pool.admit(0, other, reserve_tokens=8)
    pool.commit(0, [9, 8, 7, 6])
    pool.finish(0, park=True)  # the prefix a repeat client must not evict

    toks = [1, 2, 3, 4, 5]
    for _ in range(4):  # would overflow max_parked=2 without dedupe
        start, _, _, _sw = pool.admit(0, toks, reserve_tokens=8,
                                 min_share_tokens=4)
        pool.commit(0, [1, 2, 3, 4])
        pool.finish(0, park=True)
    s = pool.stats()
    assert s["pool_parked_sessions"] == 2  # other + ONE repeat slot
    assert s["pool_parked_evicted"] == 0
    assert s["pool_parked_pages"] == 2  # one page each, held once
    # both prefixes still serve copy-free
    start, _, _, _sw = pool.admit(1, other, reserve_tokens=8,
                             min_share_tokens=4)
    assert start == 4
    start, _, _, _sw = pool.admit(0, toks, reserve_tokens=8,
                             min_share_tokens=4)
    assert start == 4


def test_kvpool_eviction_skips_zero_yield_parked_sessions():
    """The eviction pass must not destroy park entries that can free
    nothing: an admission sharing session A's parked prefix pins those
    pages, so evicting A relieves zero pressure — and if the sharing
    request later failed (park=False), the hot prefix would vanish from
    the tree even though evicting only B sufficed."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=6, page_size=4, n_lanes=2, max_parked=4)
    a = list(range(1, 9))  # 2 full blocks
    pool.admit(0, a + [99], reserve_tokens=9)
    pool.commit(0, a)
    pool.finish(0, park=True)  # A (LRU-oldest): 2 pages parked
    b = list(range(11, 19))
    pool.admit(0, b + [99], reserve_tokens=9)
    pool.commit(0, b)
    pool.finish(0, park=True)  # B: 2 more pages parked; free = 2

    # shares A's 2 blocks and needs 3 fresh pages (free = 2): A is
    # pinned by this very admission (zero-yield), so the LRU pass must
    # skip it and evict only B
    start, _, _, _sw = pool.admit(1, a + list(range(30, 37)),
                             reserve_tokens=17, min_share_tokens=4)
    assert start == 8
    s = pool.stats()
    assert s["pool_parked_evicted"] == 1  # B only
    assert pool.parked_sessions() == 1  # A survives the pressure
    # and A still serves a copy-free hit afterwards
    pool.release(1)
    start, _, _, _sw = pool.admit(1, a + [99], reserve_tokens=9,
                             min_share_tokens=4)
    assert start == 8


def test_kvpool_shed_does_not_drain_parked_sessions():
    """An admission that would shed EVEN AFTER full parked eviction must
    shed without evicting: otherwise every retrying 429 client drains
    the parked prefix cache on each probe, holding the hit rate at zero
    for as long as the pool stays pinned by active lanes."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import (
        KVPagePool,
        PoolExhausted,
    )

    pool = KVPagePool(n_pages=4, page_size=4, n_lanes=2, max_parked=4)
    # lane 0 stays ACTIVE pinning 2 pages
    pool.admit(0, [1, 2, 3, 4, 5], reserve_tokens=8)
    # lane 1 parks one sharable page (its tail frees)
    pool.admit(1, [9, 9, 9, 9, 9], reserve_tokens=8)
    pool.commit(1, [9, 9, 9, 9])
    pool.finish(1, park=True)
    assert pool.parked_sessions() == 1
    assert pool.pages_free() == 1

    # needs 4 pages; free(1) + evictable(1) = 2 < 4: must shed WITHOUT
    # touching the parked session
    with pytest.raises(PoolExhausted):
        pool.admit(1, [7, 7, 7], reserve_tokens=16)
    assert pool.parked_sessions() == 1
    assert pool.stats()["pool_parked_evicted"] == 0

    # an admission eviction CAN serve still evicts and succeeds
    pool.admit(1, [7, 7, 7], reserve_tokens=8)  # needs 2: 1 free + 1 evictable
    assert pool.parked_sessions() == 0
    assert pool.stats()["pool_parked_evicted"] == 1


def test_kvpool_duplicate_content_pages_freed_not_parked():
    """Two lanes admit the same novel prompt concurrently (neither
    committed yet, so no sharing): commit() keeps the FIRST lane's node
    for the duplicate chain, so the second lane's page backs no tree
    node and no future walk can reach it — finish(park=True) must free
    it, not park dead residency that LRU-evicts genuinely sharable
    sessions under pressure."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=8, page_size=4, n_lanes=2, max_parked=4)
    toks = [1, 2, 3, 4, 5]
    pool.admit(0, toks, reserve_tokens=8)
    pool.admit(1, toks, reserve_tokens=8)  # concurrent: nothing to share
    pool.commit(0, [1, 2, 3, 4])  # registers block 0
    pool.commit(1, [1, 2, 3, 4])  # duplicate: lane 0's node wins
    pool.finish(0, park=True)
    pool.finish(1, park=True)
    s = pool.stats()
    # lane 1 had nothing sharable to park: no session entry, its
    # duplicate page went back to the free list
    assert s["pool_parked_sessions"] == 1
    assert s["pool_parked_pages"] == 1
    assert pool.pages_free() == 7
    # and the survivor still serves copy-free follow-ups
    start, _, _, _sw = pool.admit(0, toks, reserve_tokens=8,
                             min_share_tokens=4)
    assert start == 4


def test_kvpool_parked_pages_count_distinct_pages():
    """pool_parked_pages is real pool occupancy: N parked sessions
    sharing the same physical prefix page pin it ONCE, not once per
    holder — otherwise the pages-per-resident-session bench metric
    could never show overlap."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=8, page_size=4, n_lanes=2)
    a = [1, 2, 3, 4, 5]
    pool.admit(0, a, reserve_tokens=8)
    pool.commit(0, [1, 2, 3, 4])
    pool.finish(0, park=True)
    # second session shares the SAME block-0 page then extends the
    # chain (an identical chain would dedupe into one LRU slot)
    b = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    pool.admit(1, b, reserve_tokens=12, min_share_tokens=4)
    pool.commit(1, [1, 2, 3, 4, 5, 6, 7, 8])
    pool.finish(1, park=True)
    s = pool.stats()
    assert s["pool_parked_sessions"] == 2
    # block-0's physical page has TWO park holders but counts once
    assert s["pool_parked_pages"] == 2
    assert pool.drop_parked() == 2
    assert pool.stats()["pool_parked_pages"] == 0
    assert pool.pages_free() == 8  # every ref drained back to the pool


def test_kvpool_eviction_cannot_free_matched_shared_pages():
    """Review-caught: admit() matched its shared prefix pages BEFORE
    taking refs on them, so the parked-session eviction an oversubscribed
    admission triggers could free (and re-pop as fresh!) the very pages
    the admission was about to share — one physical page mapped at two
    block indices of the same lane. The shared refs are now taken before
    eviction: the LRU pass skips pages the admission pinned and evicts
    the next session instead."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=4, page_size=4, n_lanes=2, max_parked=4)
    a = [1, 2, 3, 4, 5, 6, 7]  # 7 prompt + 1 reserved slot = 2 pages
    _, a_blocks, _, _sw = pool.admit(0, a, reserve_tokens=8)
    pool.commit(0, a + [8])  # both blocks full: both register + park
    pool.finish(0, park=True)  # LRU-oldest; sole holder of a's 2 pages
    b = [9, 10, 11, 12, 13, 14, 15]
    pool.admit(0, b, reserve_tokens=8)
    pool.commit(0, b + [16])
    pool.finish(0, park=True)  # pool now full: 2 parked sessions
    assert pool.pages_free() == 0

    # c shares a's both blocks and needs 2 fresh pages: eviction must
    # free b's pages (a's are pinned by this very admission), and the
    # mapping must stay one-physical-page-per-block
    c = a + [8, 17]
    start, c_blocks, _, _sw = pool.admit(1, c, reserve_tokens=16,
                                    min_share_tokens=4)
    assert start == 8
    assert c_blocks[:2] == a_blocks  # shared by refcount, still alive
    assert len(set(c_blocks)) == len(c_blocks)  # no page mapped twice
    assert not set(c_blocks) & set(pool._free)  # nothing mapped AND free


def test_kvpool_below_threshold_admit_resets_tree_tip():
    """Review-caught: the below-sharing-threshold reset cleared the
    matched pages but left the tree-walk key as the lane's registration
    tip, so commit() registered the lane's block 0 UNDER the matched
    chain — a later prompt genuinely starting chain+chain would then
    share a page whose KV was computed at the wrong positions. The tip
    must reset to root with the rest."""
    from distributed_llama_multiusers_tpu.runtime.kvpool import KVPagePool

    pool = KVPagePool(n_pages=16, page_size=4, n_lanes=2)
    blk = [1, 2, 3, 4]
    pool.admit(0, blk + [5], reserve_tokens=8)
    pool.commit(0, blk)  # chain root -> blk registered
    pool.finish(0, park=True)

    # matches blk (start would be 4) but 4 < min_share_tokens=6: admits
    # fully private — and must register its own blocks from the ROOT
    pool.admit(1, blk + [9], reserve_tokens=8, min_share_tokens=6)
    pool.commit(1, blk + [9, 9, 9, 9])
    pool.finish(1, park=True)

    # a prompt that REALLY starts blk+blk may share only the first blk:
    # with the stale tip, lane 1's block 0 (KV at positions 0..3) sat in
    # the tree as the chain's SECOND block and start came back 8
    start, _, copies, _sw = pool.admit(0, blk + blk + [7], reserve_tokens=12,
                                  min_share_tokens=4)
    assert start == 4
    assert copies == []  # blk's sibling run is below any COW win


def _mock_run(engine, prompts, max_tokens=8, sequential=True):
    """Drive the scheduler over the mock engine; returns token streams."""
    sched = ContinuousBatchingScheduler(
        engine, _CharTokenizer(engine.config.vocab_size),
        prefix_min_tokens=4,
    )
    sched.start()
    try:
        out = []
        reqs = [Request(prompt=p, max_tokens=max_tokens, temperature=0.0)
                for p in prompts]
        if sequential:
            for r in reqs:
                sched.submit(r)
                r.future.result(timeout=60)
        else:
            for r in reqs:
                sched.submit(r)
            for r in reqs:
                r.future.result(timeout=60)
        for r in reqs:
            assert r.error is None, r.error
            out.append(list(r.generated_tokens))
        return out
    finally:
        sched.stop()


def test_paged_oversubscription_parks_sessions_beyond_lanes():
    """Scheduler-level oversubscription without a backend (MockAsyncEngine
    paged + content_keyed mode drives the REAL pool bookkeeping): 6
    sessions over 2 lanes with a shared system prompt — streams are
    byte-identical to the non-paged mock, later admissions share the
    prefix by refcount (copy-free: the paged engine has no copy_lane at
    all), and every finished session parks, so resident sessions exceed
    2x the lane count."""
    from distributed_llama_multiusers_tpu.utils.testing import MockAsyncEngine

    system = "sys: answer tersely. "
    prompts = [system + f"user question {i}" for i in range(6)]

    plain = MockAsyncEngine(n_lanes=2, max_chunk=8, content_keyed=True)
    want = _mock_run(plain, prompts)

    paged = MockAsyncEngine(n_lanes=2, max_chunk=8, content_keyed=True,
                            paged=True, kv_page_size=4)
    got = _mock_run(paged, prompts)
    assert got == want  # byte-identical across the layout swap

    s = paged.kvpool.stats()
    assert s["pool_prefix_admits"] >= 5  # sessions 2..6 all shared
    assert s["pool_exhausted_sheds"] == 0
    # resident (parked) sessions exceed 2x lanes: the oversubscription
    # lever — bounded by journal bytes, not HBM
    assert s["pool_parked_sessions"] >= 4
    assert paged.stats.prefix_hits >= 5
    assert paged.stats.pipeline_flushes == 0


def test_paged_pool_exhaustion_sheds_typed_429():
    """A request whose reservation cannot be served even after parked
    eviction sheds with AdmissionRejected("pool_exhausted"): HTTP 429 +
    Retry-After, request-scoped (the other lane keeps serving and the
    breaker stays closed)."""
    from distributed_llama_multiusers_tpu.serving.qos import AdmissionRejected
    from distributed_llama_multiusers_tpu.utils.testing import MockAsyncEngine

    engine = MockAsyncEngine(n_lanes=2, max_chunk=8, content_keyed=True,
                             paged=True, kv_page_size=16, kv_pool_pages=4,
                             kv_max_parked=0)
    sched = ContinuousBatchingScheduler(
        engine, _CharTokenizer(engine.config.vocab_size),
        prefix_min_tokens=4,
    )
    sched.start()
    try:
        # A reserves the whole pool: 21 prompt + 42 + 1 tokens = 4 pages
        a = Request(prompt="x" * 21, max_tokens=42, temperature=0.0)
        b = Request(prompt="y" * 21, max_tokens=42, temperature=0.0)
        sched.submit(a)
        sched.submit(b)
        with pytest.raises(AdmissionRejected) as ei:
            b.future.result(timeout=60)
        assert ei.value.reason == "pool_exhausted"
        assert ei.value.http_status == 429
        assert ei.value.retry_after_s > 0
        # request-scoped containment: A is unaffected by B's shed
        a.future.result(timeout=60)
        assert a.error is None
        assert len(a.generated_tokens) == 42
    finally:
        sched.stop()
    assert engine.kvpool.stats()["pool_exhausted_sheds"] == 1


def test_paged_engine_refuses_copy_lane(loaded):
    """copy_lane is the contiguous layout's primitive; on a paged engine
    prefix sharing is a refcount bump and a whole-lane HBM copy must be
    impossible to reach."""
    config, params = loaded[0], loaded[1]
    eng = InferenceEngine(config, params, n_lanes=2, prefill_buckets=(8,),
                          paged_kv=True, kv_page_size=16)
    with pytest.raises(RuntimeError, match="paged"):
        eng.copy_lane(0, 1)


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_paged_streams_byte_identical_vs_contiguous_churn(loaded):
    """THE paged pin: the same churn (sequential shared-prefix requests,
    then a concurrent mixed batch) over a paged engine and a contiguous
    engine produces byte-identical token streams, with the paged run
    serving the shared prefix copy-free by refcount plus one single-page
    COW at the divergent block, and zero pipeline flushes."""
    config, params, tok = loaded
    system = "aa bb cc dd ee ff gg hh "

    def drive(eng):
        sched = ContinuousBatchingScheduler(eng, tok)
        sched.start()
        try:
            out = []
            # sequential: B admits after A finished, sharing A's prefix
            for tail in ("11", "22"):
                r = Request(prompt=system + tail, max_tokens=8,
                            temperature=0.0)
                sched.submit(r)
                r.future.result(timeout=300)
                assert r.error is None, r.error
                out.append(list(r.generated_tokens))
            # churn: concurrent mixed batch (shared + unrelated)
            batch = [
                Request(prompt=system + "33", max_tokens=8, temperature=0.0),
                Request(prompt="zz unrelated", max_tokens=6, temperature=0.0),
            ]
            for r in batch:
                sched.submit(r)
            for r in batch:
                r.future.result(timeout=300)
                assert r.error is None, r.error
                out.append(list(r.generated_tokens))
            return out
        finally:
            sched.stop()

    cont = drive(_engine(config, params))
    paged_eng = InferenceEngine(config, params, n_lanes=2,
                                prefill_buckets=(8,), paged_kv=True,
                                kv_page_size=16)
    paged = drive(paged_eng)
    assert paged == cont  # byte-identical across the layout swap

    s = paged_eng.pool_stats()
    assert s["pool_prefix_admits"] >= 1  # shared prefix served copy-free
    assert s["pool_cow_copies"] >= 1  # divergence inside a shared block
    assert s["pool_exhausted_sheds"] == 0
    assert paged_eng.stats.prefix_hits >= 1
    assert paged_eng.stats.pipeline_flushes == 0  # steady churn: no flush


def test_paged_park_drop_journal_rebuild_byte_identical(loaded, tmp_path):
    """The drop-rebuild determinism pin (what makes parking safe): a
    finished session's pages are dropped under pressure and its next
    activity rebuilds by re-prefilling the journaled (prompt, resolved
    seed) — byte-identical to the never-dropped run. The journal's admit
    record carries everything the rebuild needs."""
    from distributed_llama_multiusers_tpu.serving import (
        RequestJournal,
        read_journal,
    )

    config, params, tok = loaded
    prompt = "aa bb cc dd ee ff gg hh 11"
    seed = 1234

    def one(sched):
        r = Request(prompt=prompt, max_tokens=8, temperature=0.8, seed=seed)
        sched.submit(r)
        r.future.result(timeout=300)
        assert r.error is None, r.error
        return list(r.generated_tokens)

    # reference: a fresh paged engine, no parking history
    ref_eng = InferenceEngine(config, params, n_lanes=2,
                              prefill_buckets=(8,), paged_kv=True,
                              kv_page_size=16)
    sched = ContinuousBatchingScheduler(ref_eng, tok)
    sched.start()
    try:
        ref = one(sched)
    finally:
        sched.stop()

    jpath = str(tmp_path / "journal.bin")
    journal = RequestJournal(jpath, fsync=False)
    eng = InferenceEngine(config, params, n_lanes=2, prefill_buckets=(8,),
                          paged_kv=True, kv_page_size=16)
    sched = ContinuousBatchingScheduler(eng, tok, journal=journal)
    sched.start()
    try:
        assert one(sched) == ref  # warm-up run; its session parks
        assert eng.kvpool.parked_sessions() >= 1
        # pressure: drop every parked session's pages (the LRU-eviction
        # path an oversubscribed admission takes)
        assert eng.kvpool.drop_parked() >= 1
        assert eng.pool_stats()["pool_parked_evicted"] >= 1
        # next activity rebuilds from scratch — byte-identical
        assert one(sched) == ref
    finally:
        sched.stop()
        journal.close()

    # the journal holds the rebuild inputs: resolved tokens + seed
    img = read_journal(jpath)
    entries = list(img.entries.values())
    assert len(entries) == 2
    for e in entries:
        assert e.prompt == prompt
        assert e.tokens == tok.encode(prompt)
        assert e.seed == seed
        assert e.finished


def test_paged_three_tier_residency_byte_identical(loaded):
    """Tiered-residency determinism pin: one seeded request replayed
    with its prefix served from each residency tier — resident-parked
    (refcount bump), host-RAM swapped (batched host->device copy behind
    a sha256 re-verify), and dropped (re-prefill rebuild) — produces
    byte-identical streams, all equal to a contiguous engine that never
    paged at all. This is what makes the swap tier safe to enable: the
    tier only moves WHERE bytes live, never what they are."""
    config, params, tok = loaded
    prompt = "aa bb cc dd ee ff gg hh 11"
    seed = 1234

    def one(sched):
        r = Request(prompt=prompt, max_tokens=8, temperature=0.8, seed=seed)
        sched.submit(r)
        r.future.result(timeout=300)
        assert r.error is None, r.error
        return list(r.generated_tokens)

    # contiguous reference: the layout-swap baseline
    ref_eng = _engine(config, params)
    sched = ContinuousBatchingScheduler(ref_eng, tok)
    sched.start()
    try:
        ref = one(sched)
    finally:
        sched.stop()

    eng = InferenceEngine(config, params, n_lanes=2, prefill_buckets=(8,),
                          paged_kv=True, kv_page_size=16,
                          kv_host_bytes=64 << 20)
    sched = ContinuousBatchingScheduler(eng, tok)
    sched.start()
    try:
        assert one(sched) == ref  # cold prefill; the session parks
        assert eng.kvpool.parked_sessions() >= 1
        assert one(sched) == ref  # tier 0: resident-parked refcount reuse
        # tier 1: evict the parked pages to host RAM, then reactivate
        assert sched.run_device_op(lambda: eng.swap_out_parked()) >= 1
        s = eng.pool_stats()
        assert s["swap_outs"] >= 1 and s["pool_host_pages"] >= 1
        assert one(sched) == ref  # swap-in (hash-verified host copy)
        assert eng.pool_stats()["swap_ins"] >= 1
        # tier 2: drop everything, host tier included — rebuild path
        eng.kvpool.drop_parked()
        eng.kvpool.host_tier.clear()
        assert one(sched) == ref  # re-prefill rebuild
        assert eng.stats.pipeline_flushes == 0
    finally:
        sched.stop()


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_prefix_reuse_survives_idle_lane_decode_steps(loaded):
    """Round-5 code-review finding: every decode step scatters a KV write
    for EVERY lane; idle/finished lanes used to point at position 0,
    clobbering slot 0 of exactly the caches prefix admission wants to
    reuse. Idle lanes now write at seq_len (dropped). Scenario: A
    finishes, B keeps decoding (each step would have corrupted A's
    slot 0), then C reuses A's prefix — C's stream must equal a cold
    run's."""
    config, params, tok = loaded
    system = "aa bb cc dd ee ff gg hh "

    def make(mt, tail):
        return Request(prompt=system + tail, max_tokens=mt, temperature=0.0)

    def run(eng, **kw):
        sched = ContinuousBatchingScheduler(eng, tok, **kw)
        sched.start()
        try:
            a, b = make(2, "11"), make(30, "22")
            sched.submit(a)
            sched.submit(b)
            a.future.result(timeout=300)  # A done; B decodes on (idle A lane)
            c = make(8, "11")  # same prompt as A: prefix-hits A's lane
            sched.submit(c)
            c.future.result(timeout=300)
            b.future.result(timeout=300)
            assert all(r.error is None for r in (a, b, c))
            return list(c.generated_tokens)
        finally:
            sched.stop()

    warm_engine = _engine(config, params, n_lanes=2)
    got = run(warm_engine)
    assert warm_engine.stats.prefix_hits >= 1
    cold = run(_engine(config, params, n_lanes=2), prefix_min_tokens=0)
    assert got == cold
