"""`dllama-api` entry point: the multi-user HTTP server
(reference: src/dllama-api.cpp:388-411), backed by the continuous-batching
scheduler instead of the fork's serialized accept loop."""

from __future__ import annotations

import os
import signal
import threading

from ..server import ApiServer
from ..tokenizer import template_type_from_name
from .args import build_parser
from .runtime_setup import honor_cpu_platform_env, load_stack, log, make_scheduler


def main(argv=None) -> None:
    honor_cpu_platform_env()
    args = build_parser("dllama-api", api=True).parse_args(argv)
    config, params, tokenizer, engine = load_stack(args)
    scheduler = make_scheduler(engine, tokenizer, args)
    template_type = template_type_from_name(args.chat_template)
    model_name = os.path.basename(args.model or "dllama")
    # resumable SSE (serving/resume.py): live with --reconnect-grace > 0;
    # journal recovery registers its resumed streams here too
    registry = None
    grace = getattr(args, "reconnect_grace", 0.0) or 0.0
    if grace > 0:
        from ..serving import StreamRegistry

        registry = StreamRegistry(grace_s=grace)
        log("🔁", f"SSE reconnect grace: {grace:.0f}s "
                  "(GET /v1/stream/<id> + Last-Event-ID)")
    # crash recovery (serving/recovery.py): replay the journal's
    # in-flight set through the normal admission path, paced behind the
    # circuit breaker; resumed streams reattach through the registry
    recovery = None
    if getattr(args, "recover_journal", False) and args.journal_path:
        from ..serving import recover_scheduler

        recovery = recover_scheduler(
            scheduler, args.journal_path, registry=registry
        )
        n = len(recovery.entries)
        log("📓", f"Journal recovery: {n} incomplete request(s) replaying"
                  + ("" if registry is not None or n == 0 else
                     " (no --reconnect-grace: regenerating without "
                     "stream reattach)"))
    server = ApiServer(scheduler, tokenizer, model_name=model_name,
                       template_type=template_type, resume=registry,
                       replica_id=getattr(args, "replica_id", None),
                       role=getattr(args, "role", "mixed"))
    httpd = server.serve(host=args.host, port=args.port)
    log("⭐", f"Server listening on {args.host}:{args.port} "
              f"({engine.n_lanes} lanes, role {server.role})")

    def _sigterm(*_):
        # rolling-restart signal: flip /health + shed NEW submissions
        # IMMEDIATELY (load balancers route away while the accept loop is
        # still up), then stop the accept loop from a helper thread — the
        # drain protocol in the finally below serves out in-flight work,
        # flushes the journal, and sheds stragglers with retryable 503s.
        # No out-of-band drain call needed: SIGTERM IS the drain trigger.
        log("⭐", "SIGTERM: draining (health 503, admissions shedding)")
        scheduler._draining.set()
        # dlint: ok[condvar] shutdown() must come from another thread (serve_forever runs on THIS one) and returns once the accept loop stops; nothing joins a signal-handler helper
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    def _sigint(*_):
        log("⭐", "Shutting down")
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigint)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # drain WHILE the server still answers — the accept loop restarts in
        # a helper thread so /health serves 503 and new submissions shed with
        # 503 + Retry-After (load balancers route away) instead of new
        # connections hanging in the accept backlog for the whole window.
        # drain() owns the whole shutdown protocol, including force-stop on
        # timeout — a second stop() here would only re-join a thread drain
        # already dealt with (and re-raise over drain's own failure report
        # when that thread is wedged in a hung device dispatch).
        # dlint: ok[condvar] httpd.shutdown() in the finally ends serve_forever; the helper only spans the drain window
        accept_loop = threading.Thread(target=httpd.serve_forever, daemon=True)
        accept_loop.start()
        try:
            log("⭐", "Draining in-flight requests (30s window)")
            if recovery is not None:
                recovery.stop()  # no new replays into a draining server
            scheduler.drain(timeout=30.0)
        finally:
            httpd.shutdown()
            if registry is not None:
                registry.close()
            if scheduler.journal is not None:
                # drain() already flushed via stop(); close the writer
                # and the file so the journal's tail is durable
                scheduler.journal.close()
            if args.trace_path:
                # the drained server's span ring as a Perfetto-loadable
                # artifact (same document GET /trace served live)
                try:
                    scheduler.telemetry.dump_trace(args.trace_path)
                    log("⭐", f"Trace written to {args.trace_path}")
                except OSError as e:
                    log("⚠️", f"trace dump failed: {e}")


if __name__ == "__main__":
    main()
