"""Resumable SSE streams: bounded per-request delta buffers + reattach.

The transport half of crash-durable serving (serving/journal.py,
serving/recovery.py). Every streamed delta carries a TOKEN INDEX (the
count of consumed tokens when the delta was produced — the SSE ``id:``
line), and a :class:`StreamRelay` buffers the ``(index, delta)`` pairs
between the scheduler's emit and the HTTP pump. That one indirection
buys both halves of resumption:

- **live reconnect** — a client that lost its connection re-attaches
  within the ``--reconnect-grace`` window (``GET /v1/stream/<id>`` with
  ``Last-Event-ID``); the relay replays the buffered deltas with index >
  Last-Event-ID and continues live. The request keeps generating while
  detached (today's cancel-on-disconnect applies only when the grace
  window is 0, the default); the grace reaper cancels it if nobody
  returns.
- **crash recovery** — recovery re-admits the request and registers a
  fresh relay: the ENTIRE regenerated stream buffers (``base=0``) and
  the reconnecting client's ``Last-Event-ID`` picks the resume point,
  so the resumed stream is byte-identical — zero lost, zero duplicated
  tokens. The journaled watermark is deliberately NOT used to
  fast-forward: it trails the dead server's transport writes, and a
  delta written to a socket send buffer the moment of the crash never
  reached the client — discarding up to the watermark would turn that
  client's honest reattach into a gap. (``base`` still serves relays
  built over an explicitly known-delivered prefix, e.g. in tests.)

The buffer is BOUNDED (``capacity`` deltas) — but eviction only ever
reclaims DELIVERED deltas (kept past delivery so a reconnect at a lower
``Last-Event-ID`` can replay them). An undelivered delta is never
evicted out from under a slow-but-connected client: past capacity the
undelivered tail backpressures into memory exactly like the unbounded
(capacity 0) form, bounded by ``max_tokens`` and the registry's grace
reaper. A client reattaching behind the evicted (delivered) horizon
gets a typed ``("gap", ...)`` item — the server fails the resume closed
with a restart-required error instead of silently skipping tokens.
"""

from __future__ import annotations

import bisect
import threading
import time

from ..analysis import leakcheck
from ..lockcheck import make_lock

DEFAULT_RELAY_CAPACITY = 4096


class StreamRelay:
    """One request's resumable delta buffer.

    Producer: the scheduler thread (``Request.on_delta`` wrapper pushes
    ``(token_index, text)``; the future's done-callback pushes the finish
    signal). Consumer: at most one HTTP pump at a time — ``attach()``
    hands out a generation token and supersedes the previous consumer,
    so a reconnect cleanly kicks a zombie socket still blocked in
    ``next_after``.
    """

    # dlint guarded-by declaration (analysis/lock_check.py): buffer and
    # consumer state move only under _lock (directly or via the _cv
    # Condition built over it) — pushed by the scheduler thread, drained
    # by HTTP pump threads.
    _dlint_guarded_by = {
        ("_lock", "_cv"): (
            "_rl_index", "_rl_deltas", "_rl_evicted_to", "_rl_done",
            "_rl_gen", "_rl_pushed", "_rl_sent",
        ),
    }

    def __init__(self, request_id: int, base: int = 0,
                 capacity: int = DEFAULT_RELAY_CAPACITY):
        """``capacity`` <= 0 keeps NO replay window — the no-reconnect
        default path uses that to match the plain delta queue it
        replaced (delivered deltas freed immediately; reattach is
        impossible there anyway); ``capacity`` > 0 is for
        registry-managed relays, where it caps the DELIVERED replay
        window kept around for reconnects. Undelivered deltas are exempt
        either way — a slow-but-connected client backpressures into
        memory, nothing it has not seen is ever dropped."""
        self.request_id = int(request_id)
        self.base = int(base)  # indices <= base were already delivered
        self.capacity = int(capacity)
        self._lock = make_lock("StreamRelay._lock")
        self._cv = threading.Condition(self._lock)
        # parallel ascending lists (indices pushed in consume order):
        # bisect over _rl_index finds a consumer's next delta in O(log n)
        # instead of rescanning the buffer per delta
        self._rl_index: list[int] = []
        self._rl_deltas: list[str] = []
        # highest index ever evicted from the buffer (base counts: deltas
        # <= base are never buffered — they were delivered pre-crash)
        self._rl_evicted_to = int(base)
        # highest index handed to a consumer (base counts: pre-crash
        # tokens were delivered) — the eviction floor
        self._rl_sent = int(base)
        self._rl_done = False
        self._rl_gen = 0  # consumer generation (reconnect supersedes)
        self._rl_pushed = 0  # deltas accepted (fast-forwarded ones excluded)

    # -- producer side (scheduler thread) ------------------------------------

    def push(self, index: int, text: str) -> None:
        """One emitted delta. Indices <= base are dropped — that is the
        crash-recovery fast-forward: the regenerated stream re-produces
        the delivered prefix and the relay swallows it."""
        if index <= self.base:
            return
        with self._cv:
            self._rl_index.append(int(index))
            self._rl_deltas.append(text)
            self._rl_pushed += 1
            if self.capacity > 0 and len(self._rl_index) > self.capacity:
                # reclaim DELIVERED deltas only (<= _rl_sent): the
                # capacity bound is on the reconnect-replay window, never
                # on the undelivered tail a slow-but-connected client is
                # still owed. Batch slice-del with capacity//4 slack so
                # the amortized per-push cost stays O(1) on the scheduler
                # thread (a pop(0) per token would memmove the whole
                # buffer every push once full).
                k = min(
                    bisect.bisect_right(self._rl_index, self._rl_sent),
                    len(self._rl_index) - self.capacity + self.capacity // 4,
                )
            elif self.capacity <= 0:
                # no replay window at all (the default no-reconnect
                # path): a delivered delta can never be asked for again,
                # so free it now — the buffer holds only the undelivered
                # backlog, like the plain delta queue this replaced
                k = bisect.bisect_right(self._rl_index, self._rl_sent)
            else:
                k = 0
            if k > 0:
                if self._rl_index[k - 1] > self._rl_evicted_to:
                    self._rl_evicted_to = self._rl_index[k - 1]
                del self._rl_index[:k]
                del self._rl_deltas[:k]
            self._cv.notify_all()

    def finish(self) -> None:
        """The request's future resolved (any outcome); wake consumers.
        Idempotent — safe as a done-callback plus explicit calls."""
        with self._cv:
            self._rl_done = True
            self._cv.notify_all()

    # -- consumer side (HTTP pump threads) -----------------------------------

    def attach(self) -> int:
        """Claim the consumer slot; the previous consumer's next
        ``next_after`` returns ``("superseded",)`` and it unwinds."""
        with self._cv:
            self._rl_gen += 1
            self._cv.notify_all()
            return self._rl_gen

    def next_after(self, last_index: int, timeout: float, gen: int):
        """The next item for a consumer that has seen deltas up to
        ``last_index``:

        - ``("delta", index, text)`` — the next buffered delta;
        - ``("gap", evicted_to)`` — deltas after ``last_index`` were
          evicted; byte-identical resumption is impossible, fail closed;
        - ``("done",)`` — no more deltas will come (future resolved);
        - ``("superseded",)`` — another consumer attached; unwind;
        - ``None`` — nothing within ``timeout`` (stall signal).
        """
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                if gen != self._rl_gen:
                    return ("superseded",)
                if last_index < self._rl_evicted_to:
                    return ("gap", self._rl_evicted_to)
                i = bisect.bisect_right(self._rl_index, last_index)
                if i < len(self._rl_index):
                    idx = self._rl_index[i]
                    if idx > self._rl_sent:
                        self._rl_sent = idx
                    return ("delta", idx, self._rl_deltas[i])
                if self._rl_done:
                    return ("done",)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def counts(self) -> tuple[int, int]:
        """(deltas accepted, buffered now) — test/stats surface."""
        with self._lock:
            return self._rl_pushed, len(self._rl_index)


class _Entry:
    __slots__ = ("req", "relay", "kind", "detached_at", "finished_at")

    def __init__(self, req, relay, kind):
        self.req = req
        self.relay = relay
        self.kind = kind  # "chat" | "completion" | None
        self.detached_at: float | None = None  # client gone since (monotonic)
        self.finished_at: float | None = None  # future done since (monotonic)


class StreamRegistry:
    """request_id -> live :class:`StreamRelay` map with the grace reaper.

    Entries survive a client disconnect for ``grace_s`` seconds (the
    ``--reconnect-grace`` window): while detached the request keeps
    generating into its bounded relay; a reattach clears the timer; an
    expiry cancels the request (freeing its lane) and drops the relay.
    Finished entries linger the same window so a client that lost its
    connection just before the terminal chunk can still fetch the tail.
    """

    # dlint guarded-by declaration (analysis/lock_check.py): the entry
    # map and reaper state move only under _lock (or the _cv over it) —
    # touched by HTTP threads, the recovery thread, and the reaper.
    _dlint_guarded_by = {
        ("_lock", "_cv"): (
            "_rg_entries", "_rg_closed", "_rg_expired_cancels",
            "_rg_reattaches",
        ),
    }

    # dlint resource-lifecycle declaration (analysis/resourcemodel.py):
    # ``register`` indexes an entry only the request's RESOLVED future
    # (reaper done-rule) or an explicit ``discard`` can remove — a shed
    # between register and submit with no discard leaks the entry
    # forever, the exact PR 10 bug class. Checked by resource-balance;
    # orphans witnessed at close() (analysis/leakcheck.py).
    _dlint_acquires = {"stream-entry": ("register",)}
    _dlint_releases = {"stream-entry": ("discard", "close")}

    def __init__(self, grace_s: float, relay_capacity: int = DEFAULT_RELAY_CAPACITY):
        if grace_s <= 0:
            raise ValueError("StreamRegistry needs a positive grace window")
        self.grace_s = float(grace_s)
        self.relay_capacity = int(relay_capacity)
        self._lock = make_lock("StreamRegistry._lock")
        self._cv = threading.Condition(self._lock)
        self._rg_entries: dict[int, _Entry] = {}
        self._rg_closed = False
        self._rg_expired_cancels = 0  # grace expiries that cancelled work
        self._rg_reattaches = 0
        self._thread = threading.Thread(
            target=self._reaper, name="resume-reaper", daemon=True
        )
        self._thread.start()

    # -- registration --------------------------------------------------------

    def register(self, req, kind: str | None = None,
                 base: int = 0) -> StreamRelay:
        """Create and index the request's relay (base = journal watermark
        for recovered requests, 0 for fresh streams) and hook the
        future's done-callback to the finish signal."""
        relay = StreamRelay(req.id, base=base, capacity=self.relay_capacity)
        with self._cv:
            self._rg_entries[int(req.id)] = _Entry(req, relay, kind)
        req.future.add_done_callback(lambda _f: relay.finish())
        return relay

    def attach(self, request_id: int):
        """Reattach a reconnecting client: returns ``(req, relay, kind,
        gen)`` — gen already claimed — or ``None`` for an unknown/expired
        stream. Clears the detach timer."""
        with self._cv:
            entry = self._rg_entries.get(int(request_id))
            if entry is None:
                return None
            entry.detached_at = None
            self._rg_reattaches += 1
            req, relay, kind = entry.req, entry.relay, entry.kind
        return req, relay, kind, relay.attach()

    def detach(self, request_id: int) -> None:
        """The consumer disconnected: start the grace timer (the request
        keeps generating; the reaper cancels on expiry)."""
        with self._cv:
            entry = self._rg_entries.get(int(request_id))
            if entry is not None and entry.detached_at is None:
                entry.detached_at = time.monotonic()
                self._cv.notify_all()

    def discard(self, request_id: int) -> None:
        """Drop an entry whose request never entered service (shed at
        submit, abandoned by the recovery replay): nothing will ever
        resolve its future or detach it, so the sweep's done/detached
        rules alone would leak it forever."""
        with self._cv:
            self._rg_entries.pop(int(request_id), None)

    def contains(self, request_id: int) -> bool:
        """Non-mutating existence probe (no timer/generation changes) —
        the migration endpoint's id-collision check."""
        with self._lock:
            return int(request_id) in self._rg_entries

    def depth(self) -> int:
        with self._lock:
            return len(self._rg_entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "resume_streams_live": len(self._rg_entries),
                "resume_reattaches": self._rg_reattaches,
                "resume_expired_cancels": self._rg_expired_cancels,
            }

    # -- reaper --------------------------------------------------------------

    def _sweep(self, now: float) -> list:
        """Collect expired entries under the lock; cancellation happens
        OUTSIDE it (never invoke request machinery under a registry
        lock)."""
        to_cancel = []
        with self._cv:
            for rid in list(self._rg_entries):
                entry = self._rg_entries[rid]
                done = entry.req.future.done()
                if done and entry.finished_at is None:
                    entry.finished_at = now
                if done and now - entry.finished_at > self.grace_s:
                    del self._rg_entries[rid]
                elif (
                    not done
                    and entry.detached_at is not None
                    and now - entry.detached_at > self.grace_s
                ):
                    del self._rg_entries[rid]
                    to_cancel.append(entry.req)
                    self._rg_expired_cancels += 1
        return to_cancel

    def _reaper(self) -> None:
        interval = max(0.05, min(self.grace_s / 4.0, 1.0))
        while True:
            with self._cv:
                if self._rg_closed:
                    return
                self._cv.wait(interval)
                if self._rg_closed:
                    return
            for req in self._sweep(time.monotonic()):
                req.cancel()

    def close(self, timeout: float | None = 5.0) -> None:
        # resource-leak witness (analysis/leakcheck.py): close runs after
        # the scheduler stopped, and a stopped scheduler resolved every
        # future it ever saw — an entry whose future is still pending
        # belongs to a request that NEVER entered service and was never
        # discarded (the PR 10 shed-path leak class); no reaper rule can
        # ever collect it. Live attached/finished streams all have done
        # futures by now and are NOT orphans.
        with self._cv:
            orphans = sum(
                1
                for e in self._rg_entries.values()
                if not e.req.future.done()
            )
            self._rg_closed = True
            self._cv.notify_all()
        self._thread.join(timeout)
        leakcheck.check_drained(
            "stream registry close", {"stream_entries": orphans}
        )
