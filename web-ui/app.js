// Minimal chat client for the dllama-api server (reference: web-ui/app.js —
// reads the fork's `generated_text`; this one streams via SSE and falls back
// to the non-streaming field).
const API = (location.search.match(/api=([^&]+)/) || [])[1] || "http://localhost:9990";
const log = document.getElementById("log");
const form = document.getElementById("form");
const input = document.getElementById("input");
const send = document.getElementById("send");
const status = document.getElementById("status");
const history = [];

fetch(`${API}/v1/models`).then(r => r.json())
  .then(d => { status.textContent = `model: ${d.data[0].id} @ ${API}`; })
  .catch(() => { status.textContent = `server not reachable at ${API}`; });

function bubble(cls, text) {
  const div = document.createElement("div");
  div.className = `msg ${cls}`;
  div.textContent = text;
  log.appendChild(div);
  div.scrollIntoView();
  return div;
}

form.addEventListener("submit", async (e) => {
  e.preventDefault();
  const text = input.value.trim();
  if (!text) return;
  input.value = "";
  send.disabled = true;
  bubble("user", text);
  history.push({ role: "user", content: text });
  const out = bubble("assistant", "…");
  try {
    const resp = await fetch(`${API}/v1/chat/completions`, {
      method: "POST",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify({ messages: history, max_tokens: 256, temperature: 0.7, stream: true }),
    });
    const reader = resp.body.getReader();
    const decoder = new TextDecoder();
    let buf = "", full = "";
    out.textContent = "";
    for (;;) {
      const { done, value } = await reader.read();
      if (done) break;
      buf += decoder.decode(value, { stream: true });
      let idx;
      while ((idx = buf.indexOf("\n\n")) >= 0) {
        const line = buf.slice(0, idx).trim();
        buf = buf.slice(idx + 2);
        if (!line.startsWith("data: ")) continue;
        const payload = line.slice(6);
        if (payload === "[DONE]") continue;
        const obj = JSON.parse(payload);
        if (obj.generated_text !== undefined) full = obj.generated_text;
        const delta = obj.choices?.[0]?.delta?.content;
        if (delta) { full += delta; out.textContent = full; out.scrollIntoView(); }
      }
    }
    out.textContent = full || out.textContent;
    history.push({ role: "assistant", content: full });
  } catch (err) {
    out.textContent = `error: ${err}`;
  } finally {
    send.disabled = false;
    input.focus();
  }
});
