"""Generate tiny random-weight `.m` / `.t` files for tests and benchmarks.

These go through the real writers, so every test exercises the same binary
path a converted HF checkpoint would (tensor order: src/llm.cpp:447-483).
"""

from __future__ import annotations

import numpy as np

from ..quants.codec import FloatType, quantize_q40, quantize_q80
from .model_file import ArchType, HiddenAct, ModelHeader, RopeType, write_model_header
from .tokenizer_file import TokenizerData, write_tokenizer_file


def tiny_header(
    dim: int = 64,
    hidden_dim: int = 128,
    n_layers: int = 2,
    n_heads: int = 4,
    n_kv_heads: int = 2,
    vocab_size: int = 128,
    seq_len: int = 64,
    weight_type: int = FloatType.Q40,
    rope_type: int = RopeType.LLAMA,
    rope_theta: float = 10000.0,
    n_experts: int = 0,
    n_active_experts: int = 0,
    qkv_bias: int = 0,
) -> ModelHeader:
    h = ModelHeader(
        qkv_bias=qkv_bias,
        version=0,
        arch_type=ArchType.LLAMA,
        dim=dim,
        hidden_dim=hidden_dim,
        n_layers=n_layers,
        n_heads=n_heads,
        n_kv_heads=n_kv_heads,
        n_experts=n_experts,
        n_active_experts=n_active_experts,
        vocab_size=vocab_size,
        seq_len=seq_len,
        orig_seq_len=seq_len,
        hidden_act=HiddenAct.SILU,
        rope_theta=rope_theta,
        weight_type=weight_type,
        rope_type=rope_type,
    )
    if rope_type == RopeType.LLAMA3_1:
        h.rope_scaling_factor = 8.0
        h.rope_scaling_low_freq_factor = 1.0
        h.rope_scaling_high_freq_factor = 4.0
        h.rope_scaling_orig_max_seq_len = seq_len
    return h


def _write_tensor(f, x: np.ndarray, float_type: int) -> None:
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if float_type == FloatType.F32:
        f.write(x.astype("<f4").tobytes())
    elif float_type == FloatType.F16:
        f.write(x.astype("<f2").tobytes())
    elif float_type == FloatType.Q40:
        f.write(quantize_q40(x).tobytes())
    elif float_type == FloatType.Q80:
        f.write(quantize_q80(x, mode="converter").tobytes())
    else:
        raise ValueError(float_type)


def write_synthetic_model(path: str, header: ModelHeader, seed: int = 0, scale: float = 0.02) -> None:
    """Random-normal weights, written through the real quantizers."""
    rng = np.random.default_rng(seed)
    wt = header.weight_type
    dim, hidden, kv_dim, vocab = header.dim, header.hidden_dim, header.kv_dim, header.vocab_size

    def rand(shape):
        return rng.standard_normal(shape, dtype=np.float32) * scale

    with open(path, "wb") as f:
        write_model_header(f, header)
        _write_tensor(f, rand((vocab, dim)), FloatType.F32)
        for _ in range(header.n_layers):
            _write_tensor(f, rand((dim, dim)), wt)  # q
            if header.qkv_bias:
                _write_tensor(f, rand((dim,)), FloatType.F32)  # bq
            _write_tensor(f, rand((kv_dim, dim)), wt)  # k
            if header.qkv_bias:
                _write_tensor(f, rand((kv_dim,)), FloatType.F32)  # bk
            _write_tensor(f, rand((kv_dim, dim)), wt)  # v
            if header.qkv_bias:
                _write_tensor(f, rand((kv_dim,)), FloatType.F32)  # bv
            _write_tensor(f, rand((dim, dim)), wt)  # wo
            if header.n_experts > 0:
                _write_tensor(f, rand((header.n_experts, dim)), FloatType.F32)  # router
                for _ in range(header.n_experts):
                    _write_tensor(f, rand((hidden, dim)), wt)  # w3 up
                    _write_tensor(f, rand((hidden, dim)), wt)  # w1 gate
                    _write_tensor(f, rand((dim, hidden)), wt)  # w2 down
            else:
                _write_tensor(f, rand((hidden, dim)), wt)  # w1 gate
                _write_tensor(f, rand((dim, hidden)), wt)  # w2 down
                _write_tensor(f, rand((hidden, dim)), wt)  # w3 up
            _write_tensor(f, 1.0 + rand((dim,)), FloatType.F32)  # rms att
            _write_tensor(f, 1.0 + rand((dim,)), FloatType.F32)  # rms ffn
        _write_tensor(f, 1.0 + rand((dim,)), FloatType.F32)  # final rms
        _write_tensor(f, rand((vocab, dim)), wt)  # wcls


LLAMA3_CHAT_TEMPLATE = (
    "{% for message in messages %}<|start_header_id|>{{ message['role'] }}"
    "<|end_header_id|>\n\n{{ message['content'] }}<|eot_id|>{% endfor %}"
)


def write_synthetic_tokenizer(path: str, vocab_size: int = 128) -> TokenizerData:
    """A byte-level tokenizer: regular vocab = single bytes + a few merges,
    then BOS/EOS/header specials (regular/special split at bos_id, matching
    the reference's assumption, src/tokenizer.cpp:137-139)."""
    vocab: list[bytes] = []
    scores: list[float] = []
    # keep it small: printable ASCII + whitespace (real tokenizers carry all
    # 256 byte-fallback tokens; chat templates need \n)
    base = [b"\t", b"\n", b"\r"] + [bytes([b]) for b in range(32, 127)]
    merges = [b"he", b"ll", b"hell", b"hello", b"wo", b"rl", b"worl", b"world", b"lo "]
    for t in base:
        vocab.append(t)
        scores.append(0.0)
    for i, t in enumerate(merges):
        vocab.append(t)
        scores.append(float(i + 1))
    bos_id = len(vocab)
    vocab.append(b"<|begin_of_text|>")
    scores.append(0.0)
    eot_id = len(vocab)
    vocab.append(b"<|eot_id|>")
    scores.append(0.0)
    vocab.append(b"<|start_header_id|>")
    scores.append(0.0)
    vocab.append(b"<|end_header_id|>")
    scores.append(0.0)
    while len(vocab) < vocab_size:
        vocab.append(b"<|reserved_%d|>" % len(vocab))
        scores.append(0.0)
    data = TokenizerData(
        vocab=vocab[:vocab_size],
        scores=scores[:vocab_size],
        bos_id=bos_id,
        eos_token_ids=[eot_id],
        chat_template=LLAMA3_CHAT_TEMPLATE,
    )
    with open(path, "wb") as f:
        write_tokenizer_file(f, data)
    return data
