"""Grammar-constrained decoding (grammar/): schema -> token DFA,
device-vs-host mask parity, zero-flush coexistence under churn, journal
replay determinism for constrained streams, and the typed-400 surface.

The compiled automaton is byte-level EXACT by construction (host mirror
and device tables are the same arrays), so most invariants here are
checkable without an accelerator; the real-engine tests pin the device
half (slab upload + masked sampling inside the compiled step families).
"""

import json
import random
import threading
import urllib.request

import numpy as np
import pytest

from distributed_llama_multiusers_tpu.grammar import (
    GrammarAutomaton,
    GrammarError,
    GrammarSlab,
    GrammarSlabFull,
    canonical_key,
    compile_automaton,
    validate_response_format,
)
from distributed_llama_multiusers_tpu.runtime.scheduler import (
    ContinuousBatchingScheduler,
    Request,
)
from distributed_llama_multiusers_tpu.serving import (
    RequestJournal,
    entry_from_admit_record,
    read_journal,
)
from distributed_llama_multiusers_tpu.utils.testing import (
    ByteJsonTokenizer,
    MockAsyncEngine,
)

# byte-level vocab: ids 1..256 = bytes 0..255, 0 = BOS, 257 = EOS — the
# token closure then IS the character machine, so walks are readable
BYTE_TABLE = [None] + [bytes([i]) for i in range(256)] + [None]
EOS = 257

SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string"},
        "age": {"type": "integer"},
        "tags": {"type": "array", "items": {"type": "string"}},
        "mood": {"enum": ["happy", "sad", 3, None]},
    },
    "required": ["name", "mood"],
}
SCHEMA_RF = {"type": "json_schema", "json_schema": {"name": "t", "schema": SCHEMA}}


def _walk(auto, rng, pieces, maxlen=3000):
    """Random grammar-legal walk to EOS; every prefix is device-legal by
    construction, so the decoded bytes must parse as the grammar claims."""
    s, out = auto.start, b""
    nvoc = len(pieces)
    for _ in range(maxlen):
        legal = [t for t in range(nvoc) if auto.is_legal(s, t)]
        assert legal, f"dead end at state {s} after {out[:60]!r}"
        if auto.is_legal(s, nvoc - 1) and rng.random() < 0.3:
            t = nvoc - 1  # take EOS when the grammar allows it
        elif rng.random() < 0.5:
            t = legal[rng.randrange(min(8, len(legal)))]
        else:
            t = legal[rng.randrange(len(legal))]
        if t == nvoc - 1:
            return out
        out += pieces[t]
        s = auto.next_state(s, t)
    raise AssertionError(f"walk did not terminate: {out[:80]!r}")


# -- automaton unit tests ----------------------------------------------------


def test_json_object_walks_parse_and_nest_bounded():
    auto = compile_automaton(
        {"type": "json_object"}, BYTE_TABLE, [EOS], max_depth=3
    )
    rng = random.Random(0)

    def jdepth(o):
        if isinstance(o, dict):
            return 1 + max((jdepth(v) for v in o.values()), default=0)
        if isinstance(o, list):
            return 1 + max((jdepth(v) for v in o), default=0)
        return 0

    max_depth_seen = 0
    for _ in range(25):
        txt = _walk(auto, rng, BYTE_TABLE)
        obj = json.loads(txt.decode("utf-8", errors="replace"))
        assert isinstance(obj, dict)
        max_depth_seen = max(max_depth_seen, jdepth(obj))
    assert max_depth_seen <= 3  # bounded nesting is enforced, not advisory


def test_json_object_rejects_non_object_start():
    auto = compile_automaton({"type": "json_object"}, BYTE_TABLE, [EOS])
    # from the start state only ws and '{' open; a bare string/number is
    # NOT a legal json_object response
    assert auto.is_legal(auto.start, 1 + ord("{"))
    assert not auto.is_legal(auto.start, 1 + ord('"'))
    assert not auto.is_legal(auto.start, 1 + ord("1"))
    assert not auto.is_legal(auto.start, EOS)  # empty response illegal


def test_schema_walks_conform():
    auto = compile_automaton(SCHEMA_RF, BYTE_TABLE, [EOS])
    rng = random.Random(1)
    saw_optional = False
    for _ in range(60):
        obj = json.loads(
            _walk(auto, rng, BYTE_TABLE).decode("utf-8", errors="replace")
        )
        assert set(obj) <= {"name", "age", "tags", "mood"}
        assert "name" in obj and "mood" in obj  # required enforced
        assert isinstance(obj["name"], str)
        assert obj["mood"] in ("happy", "sad", 3, None)  # enum exact
        if "age" in obj:
            assert isinstance(obj["age"], int)  # integer: no frac/exp
            saw_optional = True
        if "tags" in obj:
            assert all(isinstance(x, str) for x in obj["tags"])
    assert saw_optional  # optional properties are reachable, not dead


def test_schema_required_blocks_close():
    """'}' is illegal until every required property was emitted: walk
    '{' then check the close byte's mask bit directly."""
    auto = compile_automaton(SCHEMA_RF, BYTE_TABLE, [EOS])
    s = auto.next_state(auto.start, 1 + ord("{"))
    assert not auto.is_legal(s, 1 + ord("}"))
    # the key trie only admits declared property names: 'n' (name) ok
    # at the first position, 'z' never starts any property
    s2 = auto.next_state(s, 1 + ord('"'))
    assert auto.is_legal(s2, 1 + ord("n"))
    assert not auto.is_legal(s2, 1 + ord("z"))


def test_multibyte_pieces_walk_through():
    """BPE-style multi-byte pieces (the real-tokenizer regime): a piece
    is legal iff its WHOLE byte string walks the machine — '{\"' jumps
    straight into key position, 'true' is one hop, and an illegal-suffix
    piece is masked out even though its prefix is fine."""
    pieces = [None, b"{", b"}", b'"', b":", b",", b'{"', b'":',
              b"true", b"false", b"null", b"ab", b"1", b"23",
              b'}{',  # structurally illegal ('}' then '{'), but legal
              # STRING CONTENT — the closure must distinguish per state
              None]
    eos = len(pieces) - 1
    auto = compile_automaton({"type": "json_object"}, pieces, [eos])
    assert auto.is_legal(auto.start, 6)  # '{"' opens object + key
    assert not auto.is_legal(auto.start, 14)  # '}{' illegal at start...
    in_key = auto.next_state(auto.start, 6)  # ...but inside a string
    assert auto.is_legal(in_key, 14)  # it is plain content bytes
    rng = random.Random(2)
    for _ in range(40):
        obj = json.loads(_walk(auto, rng, pieces, maxlen=4000).decode())
        assert isinstance(obj, dict)


def test_eos_only_in_accepting_states():
    auto = compile_automaton({"type": "json_object"}, BYTE_TABLE, [EOS])
    s = auto.next_state(auto.start, 1 + ord("{"))
    assert not auto.is_legal(s, EOS)  # open object: cannot stop
    s = auto.next_state(s, 1 + ord("}"))
    assert auto.is_legal(s, EOS)  # value complete: EOS legal
    # trailing whitespace keeps the accepting state
    s2 = auto.next_state(s, 1 + ord(" "))
    assert auto.is_legal(s2, EOS)


def test_compile_cache_and_canonical_key():
    a1 = compile_automaton({"type": "json_object"}, BYTE_TABLE, [EOS])
    a2 = compile_automaton({"type": "json_object"}, BYTE_TABLE, [EOS])
    assert a1 is a2  # (vocab, schema) cache hit
    assert canonical_key({"type": "json_object"}) == canonical_key(
        {"type": "json_object"}
    )
    assert canonical_key(SCHEMA_RF) != canonical_key(
        {"type": "json_object"}
    )


def test_malformed_schemas_raise_typed_errors():
    bad = [
        "json_object",  # not an object
        {"type": "grammar"},  # unknown kind
        {"type": "json_schema"},  # no schema
        {"type": "json_schema", "json_schema": {"schema": {"type": "x"}}},
        {"type": "json_schema", "json_schema": {
            "schema": {"type": "object", "properties": {"a": {"type": "string"}},
                       "required": ["b"]}}},  # required names undeclared prop
        {"type": "json_schema", "json_schema": {
            "schema": {"type": "object", "properties": {"a": {"type": "string"}},
                       "additionalProperties": True}}},
        {"type": "json_schema", "json_schema": {"schema": {"enum": [[1, 2]]}}},
    ]
    for rf in bad:
        with pytest.raises(GrammarError):
            validate_response_format(rf)
        assert issubclass(GrammarError, ValueError)  # -> typed 400


def test_dead_end_tokenizer_rejected():
    """A vocab that cannot CLOSE a string (no '\"' piece reachable from
    string content) dead-ends mid-generation — the compiler must refuse
    at admission, not strand a lane on an all--inf mask."""
    # '"x' opens a key and adds content, but no piece can CLOSE a
    # string: the machine livelocks inside the key forever
    broken = [None, b"{", b"}", b'"x', b"a", None]
    with pytest.raises(GrammarError):
        compile_automaton({"type": "json_object"}, broken,
                          [len(broken) - 1])
    # a vocab missing ':' strands the colon state the same way
    no_colon = [None, b"{", b"}", b'"', b"a", None]
    with pytest.raises(GrammarError):
        compile_automaton({"type": "json_object"}, no_colon,
                          [len(no_colon) - 1])
    # sanity: add ':' and ',' and the same shape compiles
    ok = [None, b"{", b"}", b'"', b":", b",", b"a", None]
    compile_automaton({"type": "json_object"}, ok, [len(ok) - 1])


# -- slab ---------------------------------------------------------------------


def test_slab_refcount_park_evict_and_full():
    a_obj = compile_automaton({"type": "json_object"}, BYTE_TABLE, [EOS])
    a_sch = compile_automaton(SCHEMA_RF, BYTE_TABLE, [EOS])
    slab = GrammarSlab(258, n_states=a_obj.n_states + a_sch.n_states + 2)
    h1 = slab.attach(a_obj)
    h2 = slab.attach(a_sch)
    h3 = slab.attach(a_obj)
    assert h1.base == h3.base != h2.base
    v = slab.version
    slab.detach(a_obj.key)
    slab.detach(a_obj.key)  # refcount 0: parks, tables stay resident
    assert slab.version == v
    h4 = slab.attach(a_obj)  # re-attach is a dict hit at the SAME base
    assert h4.base == h1.base and slab.version == v
    slab.detach(a_obj.key)
    slab.detach(a_sch.key)
    # a third DISTINCT schema that cannot fit evicts parked entries
    a3 = compile_automaton(
        {"type": "json_schema",
         "json_schema": {"schema": {"enum": ["x", "y"]}}},
        BYTE_TABLE, [EOS],
    )
    h5 = slab.attach(a3)
    assert slab.resolve(h5.start_state)[0] is a3
    # live schemas exhausting the slab shed retryably (NOT a 400)...
    tiny = GrammarSlab(258, n_states=a_sch.n_states + 2)
    tiny.attach(a_sch)  # live (refs 1)
    with pytest.raises(GrammarSlabFull):
        tiny.attach(a3)
    # ...while a schema too big for an EMPTY slab is a schema error (400)
    with pytest.raises(GrammarError):
        GrammarSlab(258, n_states=8).attach(a_obj)


def test_slab_free_state_mask_all_ones():
    slab = GrammarSlab(258)
    masks, keys, nxt, dflt = slab.arrays()
    assert int(masks[0].min()) == 0xFFFFFFFF  # FREE: everything legal
    assert int(dflt[0]) == 0  # and it self-loops


# -- mocked churn: constrained + plain coexist, zero flushes -----------------


def _mock_stack(**kw):
    tok = ByteJsonTokenizer()
    eng = MockAsyncEngine(n_lanes=4, vocab=258, speculative=True,
                          content_keyed=True, **kw)
    eng.grammar_init(tok.token_table(), tok.eos_token_ids)
    return tok, eng


def test_churn_constrained_and_plain_zero_flush():
    """THE coexistence pin: greedy + sampled, constrained (json_object
    AND json_schema) + unconstrained lanes churning through the fused
    pipelined chain — every constrained completion parses (schema
    conformity included) and pipeline_flushes stays 0."""
    tok, eng = _mock_stack()
    sched = ContinuousBatchingScheduler(eng, tok, prefix_min_tokens=0)
    sched.start()
    try:
        reqs = []
        for k in range(12):
            rf = [{"type": "json_object"}, None, SCHEMA_RF, None][k % 4]
            reqs.append(sched.submit(Request(
                prompt=f"user {k} asks", max_tokens=800, seed=k,
                temperature=0.0 if k % 3 else 0.7,
                response_format=rf,
            )))
        outs = [r.future.result(timeout=120) for r in reqs]
    finally:
        sched.stop()
    for k, (r, o) in enumerate(zip(reqs, outs)):
        assert r.finish_reason == "stop", (k, r.finish_reason)
        if r.response_format is None:
            continue
        obj = json.loads(o)
        assert isinstance(obj, dict), (k, o)
        if r.response_format is SCHEMA_RF:
            assert "name" in obj and "mood" in obj
            assert set(obj) <= {"name", "age", "tags", "mood"}
    s = eng.stats.snapshot()
    assert s["pipeline_flushes"] == 0
    assert s["grammar_lanes"] == 6
    assert s["grammar_masked_steps"] > 0
    assert s["fused_steps"] > 0  # admissions rode the chain


def test_constrained_stream_identical_across_paths():
    """A constrained stream is a pure function of (prompt, seed, schema):
    the pipelined/fused run and the fully synchronous run (pipelining,
    multi-step and speculation off) emit byte-identical text."""
    def run(**kw):
        tok, eng = _mock_stack()
        sched = ContinuousBatchingScheduler(
            eng, tok, prefix_min_tokens=0, **kw
        )
        sched.start()
        try:
            req = sched.submit(Request(
                prompt="same prompt", max_tokens=800, seed=7,
                response_format=SCHEMA_RF,
            ))
            return req.future.result(timeout=60)
        finally:
            sched.stop()

    fast = run()
    slow = run(pipelined=False, multi_step=0, speculative=False)
    assert fast == slow and json.loads(fast)


def test_grammar_slab_exhaustion_sheds_retryably():
    from distributed_llama_multiusers_tpu.serving import AdmissionRejected

    tok = ByteJsonTokenizer()
    eng = MockAsyncEngine(n_lanes=2, vocab=258)
    eng.grammar_init(tok.token_table(), tok.eos_token_ids)
    # slab fits ONE json_object automaton and nothing more
    a_obj = compile_automaton(
        {"type": "json_object"}, tok.token_table(), [257]
    )
    from distributed_llama_multiusers_tpu.grammar.slab import GrammarSlab

    eng.grammar_slab = GrammarSlab(258, n_states=a_obj.n_states + 4)
    sched = ContinuousBatchingScheduler(eng, tok, prefix_min_tokens=0)
    sched.start()
    try:
        ok = sched.submit(Request(
            prompt="a", max_tokens=2000, seed=1,
            response_format={"type": "json_object"},
        ))
        shed = sched.submit(Request(
            prompt="b", max_tokens=50, seed=2, response_format=SCHEMA_RF,
        ))
        with pytest.raises(AdmissionRejected) as exc:
            shed.future.result(timeout=60)
        assert exc.value.reason == "grammar_slab_full"
        ok.cancel()
        ok.future.result(timeout=60)
    finally:
        sched.stop()


def test_engine_without_grammar_rejects_with_400_class():
    tok = ByteJsonTokenizer()
    eng = MockAsyncEngine(n_lanes=2, vocab=258)  # no grammar_init
    sched = ContinuousBatchingScheduler(eng, tok)
    sched.start()
    try:
        req = sched.submit(Request(
            prompt="x", max_tokens=4,
            response_format={"type": "json_object"},
        ))
        with pytest.raises(ValueError):
            req.future.result(timeout=60)
    finally:
        sched.stop()


# -- journal replay / migration ticket ---------------------------------------


def test_constrained_replay_byte_identical_through_journal(tmp_path):
    """Kill a constrained stream mid-flight; the journal's admit record
    (prompt, RESOLVED seed, response_format) regenerates it on a FRESH
    scheduler byte-identically — the crash-durability contract extends
    to structured output."""
    # uninterrupted reference
    tok, eng = _mock_stack()
    sched = ContinuousBatchingScheduler(eng, tok, prefix_min_tokens=0)
    sched.start()
    try:
        ref_req = sched.submit(Request(
            prompt="journal me", max_tokens=800, seed=11,
            response_format=SCHEMA_RF,
        ))
        ref = ref_req.future.result(timeout=60)
    finally:
        sched.stop()
    assert json.loads(ref)

    # crash run: journal the admission, cancel mid-flight (the journal
    # keeps no finish record for a crash — cancel writes one, so read
    # the image BEFORE the finish lands by snapshotting the admit)
    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, progress_every=1, fsync=False)
    tok2, eng2 = _mock_stack()
    sched2 = ContinuousBatchingScheduler(
        eng2, tok2, prefix_min_tokens=0, journal=journal
    )
    sched2.start()
    try:
        crash_req = sched2.submit(Request(
            prompt="journal me", max_tokens=800, seed=11,
            response_format=SCHEMA_RF,
        ))
        while not crash_req.generated_tokens:
            pass  # spin: admitted + first token out
        journal.flush()
        img = read_journal(p)
        assert img.entries[crash_req.id].response_format == SCHEMA_RF
    finally:
        sched2.stop()
    journal.close()

    # replay on a THIRD scheduler (fresh lanes, fresh slab) from the
    # journaled entry — the scheduler's own recovery materialization
    tok3, eng3 = _mock_stack()
    sched3 = ContinuousBatchingScheduler(eng3, tok3, prefix_min_tokens=0)
    sched3.start()
    try:
        entry = img.entries[crash_req.id]
        re_req = sched3.build_recovered_request(entry)
        assert re_req.response_format == SCHEMA_RF
        sched3.submit(re_req)
        replayed = re_req.future.result(timeout=60)
    finally:
        sched3.stop()
    assert replayed == ref  # byte-identical across the crash


def test_migration_ticket_carries_response_format():
    """The fleet migration ticket (export_session's admit wire record)
    round-trips response_format through entry_from_admit_record — a
    constrained stream migrated to another replica rebuilds the same
    automaton from (prompt, seed, schema)."""
    tok, eng = _mock_stack()
    sched = ContinuousBatchingScheduler(eng, tok, prefix_min_tokens=0)
    sched.start()
    try:
        req = sched.submit(Request(
            prompt="migrate me", max_tokens=400, seed=3,
            response_format={"type": "json_object"},
        ))
        while not req.generated_tokens:
            pass
        ticket = sched.export_session(req.id)
        assert ticket is not None
        assert ticket["response_format"] == {"type": "json_object"}
        entry = entry_from_admit_record(ticket)
        assert entry.response_format == {"type": "json_object"}
        assert entry.seed == int(ticket["seed"])
        req.cancel()
        req.future.result(timeout=60)
    finally:
        sched.stop()


def test_router_forwards_response_format_untouched():
    """Fleet passthrough: the router re-serializes the parsed body for
    upstream — response_format must survive byte-for-byte (it proxies
    whole bodies, never a field allowlist)."""
    body = {"prompt": "x", "response_format": SCHEMA_RF, "max_tokens": 4}
    # the router's forwarding encode (fleet/router.py route()): the
    # upstream body is json.dumps(body) of the PARSED body — assert the
    # round trip preserves the schema subtree exactly
    assert json.loads(json.dumps(body))["response_format"] == SCHEMA_RF


def test_property_order_is_semantic():
    """Property declaration order is load-bearing (keys emit in that
    order): two schemas differing only in property order are DIFFERENT
    grammars — distinct cache/slab keys, distinct masks — and the pod
    broadcast must preserve the order (a sorted serialization would
    have workers compile the reordered grammar at the root's base)."""
    import json as _json

    ab = {"type": "json_schema", "json_schema": {"schema": {
        "type": "object",
        "properties": {"a": {"type": "string"}, "b": {"type": "integer"}},
        "required": ["a", "b"]}}}
    ba = {"type": "json_schema", "json_schema": {"schema": {
        "type": "object",
        "properties": {"b": {"type": "integer"}, "a": {"type": "string"}},
        "required": ["a", "b"]}}}
    assert canonical_key(ab) != canonical_key(ba)
    a1 = compile_automaton(ab, BYTE_TABLE, [EOS])
    a2 = compile_automaton(ba, BYTE_TABLE, [EOS])
    assert a1 is not a2 and not np.array_equal(a1.masks, a2.masks)
    # the first key byte after '{"' differs: 'a' for ab, 'b' for ba
    s1 = a1.next_state(a1.next_state(0, 1 + ord("{")), 1 + ord('"'))
    s2 = a2.next_state(a2.next_state(0, 1 + ord("{")), 1 + ord('"'))
    assert a1.is_legal(s1, 1 + ord("a")) and not a1.is_legal(s1, 1 + ord("b"))
    assert a2.is_legal(s2, 1 + ord("b")) and not a2.is_legal(s2, 1 + ord("a"))
    # broadcast round trip preserves the order (root compile == worker
    # compile on the SAME automaton)
    canon = validate_response_format(ba)
    replayed = _json.loads(_json.dumps(canon))
    a3 = compile_automaton(replayed, BYTE_TABLE, [EOS])
    assert a3.key == a2.key and np.array_equal(a3.masks, a2.masks)


def test_canonical_response_format_round_trips():
    """validate_response_format must be idempotent: pod roots broadcast
    the CANONICAL form ({"type":"json_schema","schema":...}) and every
    worker re-validates it before compiling — a canonical form the
    validator rejects would desync the pod on every json_schema
    admission."""
    canon = validate_response_format(SCHEMA_RF)
    assert validate_response_format(canon) == canon
    canon2 = validate_response_format({"type": "json_object"})
    assert validate_response_format(canon2) == canon2
    # the two vocab-table shapes that a bare tag byte would collide
    from distributed_llama_multiusers_tpu.grammar.automaton import (
        vocab_fingerprint,
    )

    assert vocab_fingerprint([b"a\x01b"]) != vocab_fingerprint(
        [b"a", b"b"]
    )


def test_op_grammar_packet_replays_attach_and_detach():
    """OP_GRAMMAR round-trips a schema broadcast (attach) and a key
    broadcast (detach) through the control-plane packet into the
    worker's grammar calls — including multi-fragment schemas."""
    import numpy as np

    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    calls = []

    class _Eng:
        n_lanes = 2
        SPEC_DRAFT = 3

        def grammar_attach(self, rf):
            calls.append(("attach", rf))

        def grammar_detach(self, key):
            calls.append(("detach", key))

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self, chunk):
            super().__init__(n_lanes=2, chunk=chunk)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    import json as _json

    canon = validate_response_format(SCHEMA_RF)
    blob = _json.dumps(canon).encode()  # ORDER-PRESERVING (the pod rule)
    # a TINY chunk forces multi-fragment accumulation on the worker
    plane = _Plane(chunk=8)
    plane.send_grammar(blob)
    plane.send_grammar(b"somekey123", detach=True)
    plane.send_stop()
    assert len(sent) > 3  # the schema really did fragment

    replay = iter(sent)

    class _ReplayPlane:
        def recv(self):
            pkt = next(replay)
            mh.ControlPlane.validate(pkt)
            return pkt

        def slot(self, pkt, i, n):
            return plane.slot(pkt, i, n)

    mh.worker_loop(_Eng(), _ReplayPlane())
    assert calls == [("attach", canon), ("detach", "somekey123")]
    # the replayed canonical form re-validates AND compiles identically
    # (the root's broadcast-then-worker-compile contract)
    a_root = compile_automaton(SCHEMA_RF, BYTE_TABLE, [EOS])
    a_worker = compile_automaton(calls[0][1], BYTE_TABLE, [EOS])
    assert a_worker.key == a_root.key
    assert np.array_equal(a_worker.masks, a_root.masks)


# -- real engine: device mask parity + constrained generation ----------------


@pytest.fixture(scope="module")
def real_stack(tmp_path_factory):
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats import load_model_header
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        tiny_header,
        write_synthetic_model,
    )
    from distributed_llama_multiusers_tpu.models import load_params_from_m
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine

    # the shared tiny model's 128-token vocab cannot hold the byte-level
    # tokenizer (258 ids): bake a one-off model whose vocab does
    d = tmp_path_factory.mktemp("grammar_model")
    path = str(d / "model.m")
    write_synthetic_model(path, tiny_header(vocab_size=320), seed=0)
    h = load_model_header(path)
    config, params = load_params_from_m(path, h, dtype=jnp.float32)
    tok = ByteJsonTokenizer()
    assert config.vocab_size >= tok.vocab_size
    engine = InferenceEngine(config, params, n_lanes=2,
                             prefill_buckets=(8, 16))
    engine.grammar_init(tok.token_table(), tok.eos_token_ids)
    return config, engine, tok


def test_device_tables_match_host_mirror(real_stack):
    """Mask parity per (state, vocab) and transition parity per (state,
    legal token): the uploaded device slab decodes back to EXACTLY the
    compiled automaton — the enforcement path and the replay mirror are
    the same function."""
    _, engine, tok = real_stack
    handle = engine.grammar_attach(SCHEMA_RF)
    auto = handle.automaton
    try:
        masks_dev, keys_dev, next_dev, dflt_dev = (
            np.asarray(a) for a in engine._gtab()
        )
        V = engine.config.vocab_size
        base = handle.base
        for s in range(auto.n_states):
            row = masks_dev[base + s]
            bits = np.unpackbits(
                row.view(np.uint8), bitorder="little"
            )[:V]
            want = np.zeros(V, np.uint8)
            legal = auto.legal_ids(s)
            want[legal] = 1
            assert np.array_equal(bits, want), f"mask mismatch state {s}"
            # transition parity via the device rule: sorted-edge lookup
            # with default fallback == the host mirror's next_state
            for t in legal:
                key = (base + s) * V + int(t)
                j = int(np.searchsorted(keys_dev, key))
                if j < len(keys_dev) and int(keys_dev[j]) == key:
                    got = int(next_dev[j])
                else:
                    got = int(dflt_dev[base + s])
                assert got == base + auto.next_state(s, int(t))
        # FREE state stays all-ones after the upload
        assert int(masks_dev[0].min()) == 0xFFFFFFFF
    finally:
        engine.grammar_detach(handle.key)


def test_real_engine_constrained_generation_valid_json(real_stack):
    """End to end through the REAL compiled step families: a constrained
    greedy request over the tiny model emits schema-valid JSON (the
    random-weight model knows nothing about JSON — the on-device mask is
    doing all the work), while an unconstrained twin on the same batch
    keeps its plain stream."""
    _, engine, tok = real_stack
    before = engine.stats.snapshot()
    sched = ContinuousBatchingScheduler(
        engine, tok, prefix_min_tokens=0, multi_step=4
    )
    sched.start()
    try:
        rf = {"type": "json_schema",
              "json_schema": {"schema": {"enum": ["happy", "sad", 3]}}}
        con = sched.submit(Request(
            prompt="feelings?", max_tokens=40, response_format=rf,
        ))
        plain = sched.submit(Request(prompt="feelings?", max_tokens=8))
        out = con.future.result(timeout=300)
        plain_out = plain.future.result(timeout=300)
    finally:
        sched.stop()
    assert json.loads(out) in ("happy", "sad", 3)
    assert isinstance(plain_out, str)
    stats = engine.stats.snapshot()  # deltas: the fixture engine is shared
    assert stats["grammar_lanes"] - before["grammar_lanes"] == 1
    assert stats["grammar_masked_steps"] > before["grammar_masked_steps"]


# -- HTTP surface -------------------------------------------------------------


@pytest.fixture(scope="module")
def http_server():
    from distributed_llama_multiusers_tpu.server import ApiServer

    tok, eng = _mock_stack()
    sched = ContinuousBatchingScheduler(eng, tok, prefix_min_tokens=0)
    sched.start()
    api = ApiServer(sched, tok, model_name="grammar-test")
    httpd = api.serve(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()
    sched.stop()


def _post(url, body, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_completion_json_mode(http_server):
    status, body = _post(http_server + "/v1/completions", {
        "prompt": "give me json", "max_tokens": 800,
        "response_format": {"type": "json_object"},
    })
    assert status == 200
    assert isinstance(json.loads(body["generated_text"]), dict)


def test_http_chat_json_schema(http_server):
    status, body = _post(http_server + "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "fill the form"}],
        "max_tokens": 800, "response_format": SCHEMA_RF,
    })
    assert status == 200
    obj = json.loads(body["choices"][0]["message"]["content"])
    assert "name" in obj and "mood" in obj


def test_http_400_on_malformed_schema(http_server):
    for bad in (
        {"type": "yaml_mode"},
        {"type": "json_schema", "json_schema": {"schema": {"type": "no"}}},
        ["json_object"],
    ):
        status, body = _post(http_server + "/v1/completions", {
            "prompt": "x", "max_tokens": 4, "response_format": bad,
        })
        assert status == 400, (bad, body)
        assert "error" in body
    # /stats still serves the grammar counters
    with urllib.request.urlopen(http_server + "/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert "grammar_lanes" in stats and "grammar_masked_steps" in stats
    assert "grammar_schemas_installed" in stats
