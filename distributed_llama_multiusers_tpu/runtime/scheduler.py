"""Multi-user request queue + continuous-batching scheduler.

The capability the MultiUsers fork exists for (src/Request.hpp,
src/app.cpp:314-402): N concurrent requests dynamically join and leave a
shared batched decode loop. The reference's loop has five defects documented
in SURVEY.md §2.3; this implementation is the corrected design:

  (a) full prompt prefill (bucketed chunks), not just token[0]
  (b) per-lane position vectors — no shared positionPipe overwrite
  (c) per-lane KV cache slots — no cross-request corruption
  (d) clean shutdown via stop() — the loop thread joins
  (e) streaming decode through per-lane StreamDecoder + EosDetector

Flow: HTTP/CLI threads push Request objects into the queue (by default a
serving.QosQueue — bounded admission, priority classes, per-user
deficit-round-robin fair share; the bare RequestQueue FIFO remains for
strict reference-parity use); the scheduler thread drains the queue into
free lanes (prefill), then advances ALL active lanes one token per
engine.decode() step, sampling per-lane, emitting stream deltas, and
fulfilling each request's future on EOS / max_tokens. Deadlines
(serving/deadlines.py) bound queue wait and generation wall-clock;
drain() (serving/drain.py) is the graceful-shutdown counterpart to stop().
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable

import numpy as np

from ..serving import (
    AdmissionRejected,
    CircuitBreaker,
    DeadlinePolicy,
    Priority,
    QosQueue,
    RequestJournal,
    StepWatchdog,
    admit_record,
    budget_expired,
    drain_scheduler,
    queue_expired,
)
from ..analysis import jitcheck, leakcheck
from ..lockcheck import make_lock
from ..serving.watchdog import deadline_from_env
from ..telemetry import Telemetry
from ..tokenizer import EosDetector, EosResult, Sampler, Tokenizer, TokenizerChatStops
from ..utils import faults
from ..utils.seeds import fresh_seed
from .engine import DEFAULT_TOPP
from .kvpool import PoolExhausted
from .spec import NgramDraftIndex


class EngineFailure(RuntimeError):
    """Engine-scoped serving failure, resolved onto a request's future by
    the containment layer. Carries the ``request_id`` so the HTTP 500
    body / terminal SSE error chunk can name it — the future's exception
    is all the transport layer sees."""

    def __init__(self, message: str, request_id: int | None = None):
        self.request_id = request_id
        super().__init__(message)


def classify_failure(e: BaseException) -> str:
    """Failure containment classification (the supervised loop's rule):

    - ``"request"`` — per-request input errors: tokenization, empty
      prompts, per-lane validation. The ``ValueError`` family by
      convention (every engine-side argument check raises it). Fails
      only that request (``finish_reason="error"``); the engine is fine.
    - ``"engine"`` — everything an engine dispatch/consume/transfer can
      raise (XLA ``RESOURCE_EXHAUSTED``, transfer errors, injected
      faults): the pipeline flushes, affected lanes fail, lane state
      resets, and the loop keeps serving behind the circuit breaker.

    ``AdmissionRejected`` (the paged pool's exhaustion shed) is request-
    scoped despite being a RuntimeError: the pool being pinned by active
    lanes is LOAD, not engine failure — the client gets the retryable
    429/503 shape ``submit()`` sheds with, and the breaker stays closed.
    """
    if isinstance(e, AdmissionRejected):
        return "request"
    return "request" if isinstance(e, ValueError) else "engine"


class RequestState(Enum):
    QUEUED = 0
    PROMPT_PROCESSING = 1
    GENERATING = 2
    DONE = 3
    FAILED = 4


_req_ids = itertools.count(1)
# guards the counter-object SWAP in ensure_request_id_floor against the
# dataclass default_factory draws on HTTP threads: an unlocked
# read-then-replace could let a fresh request draw from the old counter
# an id the new counter re-issues later (two live requests, one id)
_req_ids_lock = threading.Lock()


def _next_request_id() -> int:
    with _req_ids_lock:
        return next(_req_ids)


def fresh_request_id() -> int:
    """A new unique id from the shared counter — public surface for the
    fleet migration endpoint, which REMAPS an injected session whose
    original id collides with a live request on this replica (every
    replica numbers from 1, so same-id-live collisions across a fleet
    are routine; see server/http.py _admin_migrate)."""
    return _next_request_id()


def ensure_request_id_floor(min_used_id: int) -> None:
    """Advance the shared request-id counter past ``min_used_id`` —
    recovery (serving/recovery.py) re-admits crashed requests under
    their ORIGINAL ids (the SSE reattach key), and fresh requests
    admitted after a recovery must never collide with them."""
    global _req_ids
    with _req_ids_lock:
        nxt = next(_req_ids)
        _req_ids = itertools.count(max(nxt, int(min_used_id) + 1))


@dataclass
class Request:
    """One generation request (mirror of the fork's Request, src/Request.hpp:21-36,
    with correct per-request sampling/stop config)."""

    prompt: str
    max_tokens: int = 128
    temperature: float = 0.0
    topp: float = DEFAULT_TOPP
    seed: int | None = None
    stop: list[str] = field(default_factory=list)
    add_bos: bool = True
    add_special_tokens: bool = True
    # QoS identity (serving/qos.py): fair-share key + admission class
    user_id: str = ""
    priority: int = Priority.NORMAL
    # per-request deadline overrides (serving/deadlines.py); None = policy
    queue_timeout_s: float | None = None
    budget_s: float | None = None
    # structured output (grammar/): the request's response_format —
    # {"type": "json_object"} or {"type": "json_schema", ...} — compiled
    # into a token-level automaton at admission and enforced on device;
    # None = unconstrained. Journaled (and carried by fleet migration
    # tickets) so replay rebuilds the identical automaton from
    # (prompt, seed, schema).
    response_format: dict | None = None
    # crash-durable serving (serving/journal.py): which API route built
    # this request ("chat" | "completion" | None) — journaled so a
    # recovered stream renders the right SSE chunk shape on reattach —
    # and whether this request IS a journal replay (re-admitted under
    # its original id with its original resolved seed)
    api_kind: str | None = None
    recovered: bool = False
    # fleet trace context (telemetry/tracectx.py), "tid-sid" wire form:
    # accepted from the client/router X-DLlama-Trace header, journaled
    # with the admit record and carried by migration tickets, so spans
    # on every replica a request touches share one trace_id
    trace: str | None = None
    id: int = field(default_factory=_next_request_id)
    state: RequestState = RequestState.QUEUED
    future: Future = field(default_factory=Future)
    on_delta: Callable[[str], None] | None = None  # streaming callback
    # filled by the scheduler
    generated_text: str = ""
    generated_tokens: list[int] = field(default_factory=list)
    n_prompt_tokens: int = 0
    error: str | None = None
    finish_reason: str | None = None  # "stop" | "length" | "cancelled" | "timeout"
    submitted_at: float | None = None  # monotonic, stamped by submit()/push()
    admitted_at: float | None = None  # monotonic, stamped at lane claim
    # telemetry (telemetry/): per-request latency record attached at
    # submit, and the summary dict (ttft_s, tbt p50/p95, queued_s, ...)
    # produced at finish — the SAME object the HTTP layer attaches to
    # completion responses and the JSON request log line carries
    tel: object = None
    summary: dict | None = None
    _cancelled: threading.Event = field(default_factory=threading.Event)

    def cancel(self) -> None:
        """Ask the scheduler to stop generating (e.g. client disconnected);
        the lane frees at the next decode step."""
        self._cancelled.set()


class RequestQueue:
    """Thread-safe FIFO handoff (mirror of RequestQueue, src/Request.hpp:39-64)."""

    def __init__(self):
        self._q: "queue.Queue[Request]" = queue.Queue()

    def push(self, request: Request) -> None:
        self._q.put(request)

    def pop(self, timeout: float | None = None) -> Request | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def empty(self) -> bool:
        """Advisory emptiness (racy by nature): the scheduler uses it to
        decide whether a multi-step decode would delay an admission."""
        return self._q.empty()

    def drain(self) -> list[Request]:
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    def remove_if(self, predicate) -> list[Request]:
        """Remove and return every queued request matching ``predicate``
        (same contract as QosQueue.remove_if) — the scheduler's deadline
        sweep and the submit()/drain() race both need targeted removal,
        on this queue no less than on the QoS one."""
        with self._q.mutex:
            q = self._q.queue
            out = [r for r in q if predicate(r)]
            for r in out:
                q.remove(r)
        return out


def _common_prefix_len(a, b) -> int:
    """Length of the longest common leading run of two token lists."""
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


@dataclass
class _Lane:
    request: Request | None = None
    pos: int = 0  # next write position
    next_token: int = 0  # token to feed at pos
    sampler: Sampler | None = None
    eos: EosDetector | None = None
    decoder: object = None
    pending: list[int] = field(default_factory=list)  # unprocessed prompt tail
    seed: int = 0
    host_exact: bool = False  # route this lane through the host Sampler
    # speculation state: committed (prompt + consumed) token history with
    # an O(1) prompt-lookup draft probe (runtime/spec.py)
    drafter: NgramDraftIndex = field(default_factory=NgramDraftIndex)
    # grammar-constrained decoding (grammar/): the attached slab handle
    # (None = unconstrained) and the HOST MIRROR of the lane's automaton
    # state — absolute slab id, advanced by every emitted token the host
    # consumes. Exact on the sync paths; one step behind on the
    # pipelined chain (where the device carry is authoritative and the
    # mirror only steers draft pre-filtering).
    grammar: object = None
    g_state: int = 0


# Historical routing boundary, kept for the sampler-parity test grid and
# the docs: requests at/above these used to fall back to the host Sampler
# because the old on-device sampler truncated to top-`device_topk` logits,
# which a near-1.0 top-p or a very high temperature defeats. The device
# sampler is now EXACT (full-vocab sort → cumsum → nucleus mask,
# engine.py _sample_lane), so no request routes host-exact on numerics
# grounds anymore — `host_sampling=True` (bit-exact reference xorshift
# semantics, one [vocab] f32 transfer per token) is the only remaining
# host-exact path, and steady-state serving never reads logits back.
HOST_EXACT_TOPP = 0.99
HOST_EXACT_TEMP = 1.5


class ContinuousBatchingScheduler:
    # dlint guarded-by declaration (analysis/lock_check.py): the pending
    # device-op list moves only under its lock — appended by admin/HTTP
    # threads (run_device_op), drained by the batching loop.
    _dlint_guarded_by = {
        ("_device_ops_lock",): ("_device_ops",),
    }

    # dlint resource-lifecycle declaration (analysis/resourcemodel.py):
    # the live-session mirror. ``_mirror_admit`` (in _start_request)
    # publishes the migration ticket; every request that reached a lane
    # must pass ``_mirror_finish`` (_finish or _fail_request) or the
    # mirror grows one dead ticket per request. Checked by
    # resource-balance; counted at stop() by the leak witness
    # (analysis/leakcheck.py, DLLAMA_LEAKCHECK=1).
    _dlint_acquires = {"session-record": ("_mirror_admit",)}
    _dlint_releases = {"session-record": ("_mirror_finish",)}

    # dlint device-affinity declaration: the batching-loop closure grows
    # from here by same-class ``self.X()`` calls — methods in it may
    # call the engine's ``_dlint_device_affine`` surface directly; every
    # other thread goes through run_device_op().
    _dlint_loop_roots = ("_run",)

    def __init__(
        self,
        engine,
        tokenizer: Tokenizer,
        queue_: RequestQueue | None = None,
        eos_padding: tuple[int, int] = (2, 2),
        host_sampling: bool = False,
        speculative: bool = True,
        prefix_min_tokens: int = 16,
        multi_step: int = 8,
        deadlines: DeadlinePolicy | None = None,
        pipelined: bool = True,
        fused_prefill: bool = True,
        telemetry: Telemetry | None = None,
        breaker: CircuitBreaker | None = None,
        step_deadline_s: float | None = None,
        watchdog_fatal: bool = False,
        journal: RequestJournal | None = None,
    ):
        """``host_sampling=True`` routes sampled lanes through the bit-exact
        host Sampler (reference xorshift semantics, one [vocab] f32 transfer
        per token); the default samples on device inside the compiled decode
        step, transferring only the 4-byte token per lane.

        ``speculative=False`` disables prompt-lookup speculative decoding
        (greedy-lane draft verification); it is otherwise used automatically
        whenever the engine supports it.

        ``prefix_min_tokens`` gates prefix caching: a new request whose
        prompt shares at least that many leading tokens with the tokens
        already resident in some lane's KV cache (including finished
        lanes — their KV stays until overwritten) skips prefilling the
        shared prefix via ``engine.copy_lane``. 0 disables.

        ``multi_step``: when the batch is in steady-state decode (no prompt
        chunks pending, no admissions queued, no drafts to verify, no
        host-exact-sampling lane), run up to this many decode steps in ONE
        device dispatch (``engine.decode_multi``) — token streams identical
        to single stepping, but per-token host dispatch overhead divided by
        the horizon (the dominant serving cost through a high-latency
        device link). Stops/EOS are applied retroactively; a cancel or a
        new admission takes effect at the next horizon boundary. 0 or 1
        disables.

        ``pipelined`` (default on, engines with ``pipeline_depth > 1``
        only): in steady-state decode with no drafts to verify, dispatch
        step k+1 from the engine's ON-DEVICE token carry while step k's
        host readback (detokenize, stream deltas, stop/EOS/deadline
        checks) runs one step behind, overlapped with the device — the
        synchronous dispatch→block→consume cycle leaves the accelerator
        idle for the whole host half. Token streams are byte-identical to
        the synchronous path (the device feed rule applies the same
        where(temp==0, greedy, sampled) select with the same
        fold_in(seed, pos) draws). Speculation drafts, host-exact lanes,
        a queued admission, or a prefill force a flush back to the
        synchronous path.

        ``fused_prefill`` (default on; engines with
        ``supports_fused_prefill`` and pipelining active only): admissions
        no longer flush the pipelined chain. A queued request claims a
        free lane inside the live chain and its prompt chunks ride FUSED
        prefill+decode dispatches (``engine.decode_prefill_fused``): one
        device program advances every generating lane one token AND
        consumes one bounded chunk, so decode lanes never stall behind an
        admission and ``pipeline_flushes`` stays 0 under steady churn.
        Streams remain byte-identical to the synchronous path (the fused
        program's decode half is the pipelined step's math verbatim; the
        prefill half is ``prefill_chunk``'s). Host-exact admissions are
        the one kind that still flushes (they read full logits every
        step). Off: the pre-fused behavior — an admission exits the chain
        to the synchronous admit+prefill path.

        ``deadlines`` (serving/deadlines.py): server-wide queue-wait
        timeout and wall-clock generation budget; expired requests finish
        with ``finish_reason="timeout"`` (queued ones without ever taking a
        lane, active ones at the next loop iteration, freeing their lane).
        Defaults to a policy with both limits disabled; per-request
        overrides on ``Request`` apply either way.

        The default queue is a :class:`~..serving.qos.QosQueue` (unbounded
        unless the caller passes a capacity-bounded one): per-user
        deficit-round-robin fair share and priority classes replace the
        seed's bare FIFO.

        ``telemetry`` (telemetry/): the span tracer + metrics registry +
        JSON logger hub this scheduler stamps request lifecycles and step
        slices into; a default hub is built when the caller passes none
        (host-side only, bounded ring — always on). The server exposes it
        at ``GET /metrics`` / ``GET /trace``; the bench reports its
        percentiles. Span stamping never happens inside the pipelined
        dispatch half (dlint ``pipeline-sync`` pins that): pipelined step
        slices are recorded by the consume half, one step behind.

        ``breaker`` (serving/breaker.py): the circuit breaker the
        supervised loop feeds — N consecutive engine-scoped failures flip
        ``/health`` unhealthy and ``submit()`` sheds with 503 +
        Retry-After until a half-open probe succeeds. Always present
        (a default is built when the caller passes none).

        ``step_deadline_s`` (serving/watchdog.py): when > 0, a watchdog
        thread trips if a blocking engine step (sync decode, prefill
        chunk, lagged pipeline consume) makes no progress within the
        deadline — tripping the breaker and aborting the chain
        single-host, crashing the process deliberately on a pod
        (``watchdog_fatal=True``) so ``jax.distributed`` peer-failure
        detection surfaces the hang. ``None`` reads
        ``DLLAMA_STEP_DEADLINE``; 0 disables.

        ``journal`` (serving/journal.py): the crash-durable request
        journal — every admission writes an admit record (prompt tokens,
        sampler params with the RESOLVED seed, QoS class, deadlines) and
        every ending a finish record, via the journal's background
        writer thread; delivery watermarks are written by the transport
        layer (server/http.py) AFTER each delta reaches the client. On
        restart, serving/recovery.py replays the incomplete set
        byte-identically. ``None`` (the default) disables journaling
        entirely — the ``--journal-path`` flag wires one up."""
        self.engine = engine
        self.tokenizer = tokenizer
        self.queue = queue_ or QosQueue()
        self.deadlines = deadlines or DeadlinePolicy()
        self.telemetry = telemetry or Telemetry()
        # queue-wait histogram source: the queue's own pop-time measurement
        # when it offers one (reconciles with queue_popped exactly), else
        # observed at lane-claim time
        self._observe_wait_at_claim = not self.telemetry.bind_queue(self.queue)
        self.eos_padding = eos_padding
        self.host_sampling = host_sampling
        self.speculative = speculative
        self.prefix_min_tokens = prefix_min_tokens
        self.multi_step = multi_step
        self.pipelined = pipelined
        self.fused_prefill = fused_prefill
        self._lanes = [_Lane() for _ in range(engine.n_lanes)]
        # tokens whose KV each lane's cache currently holds at slots
        # [0, len): survives request finish (the KV physically remains),
        # reset when a new request claims the lane
        self._lane_kv: list[list[int]] = [[] for _ in range(engine.n_lanes)]
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._thread: threading.Thread | None = None
        # device ops posted by admin threads (disagg page export/import),
        # executed by the batching loop at its next step boundary — the
        # one point where engine.cache is the live chain output and the
        # next dispatch has not yet donated it (run_device_op)
        self._device_ops: list = []
        self._device_ops_lock = make_lock(
            "ContinuousBatchingScheduler._device_ops_lock"
        )
        # failure containment (serving/breaker.py, serving/watchdog.py):
        # the supervised loop's admission gate + stall detector
        self.breaker = breaker or CircuitBreaker()
        deadline = deadline_from_env(step_deadline_s)
        self.watchdog = (
            StepWatchdog(
                deadline, on_trip=self._on_watchdog_trip,
                fatal=watchdog_fatal,
            )
            if deadline > 0
            else None
        )
        # watchdog -> loop signal: abort the pipelined chain at the next
        # host-side opportunity (a slow-but-alive step returns eventually;
        # the chain must not keep extending behind it)
        self._wd_abort = threading.Event()
        # engine-scoped containment rounds (loop thread writes, /stats
        # reads; single GIL-atomic int bump like the timeout counters)
        self.engine_failures = 0
        # crash durability (serving/journal.py, serving/recovery.py):
        # the request journal (None = off) and, after a --recover-journal
        # restart, the replay coordinator whose counters /stats merges
        self.journal = journal
        self.recovery = None
        # fleet migration (serving/journal.admit_record, fleet/migrate.py):
        # the live-session mirror of each admitted request's journal admit
        # record — journal-independent, so a replica without --journal-path
        # can still export a migration ticket. Entries are built whole on
        # the loop thread and assigned/popped with single-key dict ops
        # (GIL-atomic); export_session reads whole entries from HTTP
        # threads. Bounded by n_lanes: records exist only while the
        # request holds a lane.
        self._session_records: dict[int, tuple[dict, Request]] = {}
        self._chat_stops = TokenizerChatStops(tokenizer)
        self._prefill_rr = 0  # round-robin cursor over admitting lanes
        # deadline enforcement counters (loop thread writes, /stats reads;
        # int += is a single atomic-enough bump under the GIL)
        self.queue_timeouts = 0
        self.budget_timeouts = 0
        self._last_sweep = 0.0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()  # restartable: a stop()ed scheduler can start again
        self._draining.clear()
        self._wd_abort.clear()
        # chaos harness: DLLAMA_FAULTS arms the process-global fault plan
        # (utils/faults.py) — one env read, idempotent, no-op otherwise
        faults.maybe_arm_from_env()
        if self.watchdog is not None:
            self.watchdog.start()
        self._thread = threading.Thread(target=self._run, name="batching-loop", daemon=True)
        self._thread.start()
        # one structured line deployments verify serving config from
        # (the engine-side twin — mesh shape, buckets warmed — comes from
        # warmup_engine)
        engine = self.engine
        self.telemetry.startup_log(
            "scheduler_start",
            n_lanes=engine.n_lanes,
            pipeline_depth=getattr(engine, "pipeline_depth", 0),
            pipelined=self.pipelined,
            fused_prefill=self._fused_ok(),
            multi_step=self.multi_step,
            speculative=self.speculative,
            # drafts verify INSIDE the chain only while the ring lag is
            # <= 1 (the host's carry candidate aligns one step behind):
            # true at the default depth 2; deeper rings trade in-chain
            # speculation for extra overlap — surfaced here so the
            # trade-off is visible in logs, not silent
            spec_in_chain=bool(
                self._spec_pl_ok()
                and self.pipelined
                and getattr(self.engine, "pipeline_depth", 0) == 2
            ),
            prefix_min_tokens=self.prefix_min_tokens,
            # compile stability: True once warmup_engine armed the
            # recompile witness (analysis/jitcheck.py) — the normal
            # make_scheduler order warms before start(), so a False here
            # means this scheduler is serving UNWARMED programs and
            # every first dispatch will compile mid-request
            jitcheck_armed=jitcheck.armed(),
            queue_capacity=getattr(self.queue, "capacity", None),
            queue_timeout_s=self.deadlines.queue_timeout_s,
            request_budget_s=self.deadlines.request_budget_s,
            breaker_threshold=self.breaker.threshold,
            step_deadline_s=(
                self.watchdog.deadline_s if self.watchdog is not None else 0
            ),
            faults_armed=faults.armed(),
        )

    def stop(self) -> None:
        """Clean shutdown — the reference's loop never terminates (defect (d)).
        Raises if the loop thread outlives the join timeout (a hung device
        dispatch): silently dropping the reference would leak a live thread
        still mutating lanes and the KV cache."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=30)
            if thread.is_alive():
                raise RuntimeError(
                    "batching loop failed to stop within 30s; thread is still "
                    "alive (likely a hung device dispatch) and still owns the "
                    "lanes — not dropping the reference"
                )
            self._thread = None
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.journal is not None:
            # barrier, not close: the journal outlives scheduler restarts
            # (its creator — runtime_setup / the test — owns closing it)
            self.journal.flush()
        # resource-leak witness (analysis/leakcheck.py): the loop joined
        # and _resolve_exit settled every lane, so every count below is
        # zero on a clean stop — anything held is an acquire whose
        # release lost an exit path. Counted always; raises under
        # DLLAMA_LEAKCHECK=1.
        leakcheck.check_drained("scheduler stop", self.leak_counts())

    def leak_counts(self) -> dict[str, int]:
        """Authoritative live counts for every resource kind this
        scheduler owns (the declared _dlint_acquires surfaces): lane-held
        KV pages, session-mirror tickets, open journal marks, pending
        device ops. The leak witness's drain snapshot — also surfaced on
        /stats as ``resources_live`` between drains."""
        counts = {"session_records": len(self._session_records)}
        with self._device_ops_lock:
            counts["device_ops"] = len(self._device_ops)
        pool_stats = getattr(self.engine, "pool_stats", None)
        if callable(pool_stats):
            pstats = pool_stats() or {}
            counts["kv_lane_pages"] = int(
                pstats.get("pool_pages_in_use", 0)
            )
            # host-page kind (tiered residency): swap-outs the pool
            # staged but no engine drain has taken to the host tier —
            # a non-zero drained count means an eviction path lost its
            # drain call and the pages' payloads leaked in limbo
            counts["kv_swap_pending"] = int(
                pstats.get("pool_swap_pending", 0)
            )
        if self.journal is not None:
            counts["journal_marks"] = int(
                self.journal.stats().get("journal_open_marks", 0)
            )
        return counts

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown (serving/drain.py): stop admitting — submit()
        sheds with AdmissionRejected("draining") and /health flips to 503 —
        let queued + active work finish or hit its deadline, then join the
        loop thread. Returns True on a clean drain; on ``timeout`` the
        remainder is force-cancelled (every future still resolves)."""
        return drain_scheduler(self, timeout)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def submit(self, request: Request) -> Request:
        if self._draining.is_set():
            self._shed_draining()
        if not self.breaker.allow():
            # engine unhealthy (open circuit): shed BEFORE the queue so a
            # broken engine degrades into fast 503s + Retry-After instead
            # of a backlog of clients waiting on an engine that cannot
            # serve them. Half-open probes pass through here.
            note = getattr(self.queue, "note_rejection", None)
            if note is not None:
                note("breaker_open")
            raise AdmissionRejected(
                "breaker_open", retry_after_s=self.breaker.retry_after_s()
            )
        if request.submitted_at is None:
            request.submitted_at = time.monotonic()
        # attach the lifecycle record BEFORE the push: the loop thread may
        # pop and admit this request before push() even returns here
        self.telemetry.on_submit(request)
        try:
            self.queue.push(request)
        except AdmissionRejected:
            request.submitted_at = None  # rejected: never entered the queue
            raise
        if self._draining.is_set():
            # raced with drain(): the flag flipped during the push, so the
            # loop may already have taken its exit snapshot without seeing
            # this request. Pull it back out and shed; if it's already gone,
            # the loop popped it and will serve it normally.
            remove_if = getattr(self.queue, "remove_if", None)
            if remove_if is not None and remove_if(lambda r: r is request):
                request.submitted_at = None
                self._shed_draining()
        return request

    def _shed_draining(self) -> None:
        note = getattr(self.queue, "note_rejection", None)
        if note is not None:
            note("draining")  # drain-shed load shows up in /stats too
        raise AdmissionRejected("draining", retry_after_s=5.0)

    def build_recovered_request(self, entry) -> Request:
        """Materialize a journal entry (serving/journal.JournalEntry)
        back into a Request for deterministic replay — called by
        serving/recovery.py, which stays runtime-free. The ORIGINAL
        request id is kept (it is the SSE reattach key) and the fresh-id
        counter advances past it so post-recovery admissions never
        collide; the journaled RESOLVED seed rides in ``seed``, so the
        lane re-derives the identical ``fold_in(seed, pos)`` stream the
        crashed process was sampling."""
        ensure_request_id_floor(entry.request_id)
        return Request(
            prompt=entry.prompt,
            max_tokens=entry.max_tokens,
            temperature=entry.temperature,
            topp=entry.topp,
            seed=entry.seed,
            stop=list(entry.stop),
            add_bos=entry.add_bos,
            add_special_tokens=entry.add_special_tokens,
            user_id=entry.user,
            priority=entry.priority,
            queue_timeout_s=entry.queue_timeout_s,
            budget_s=entry.budget_s,
            response_format=entry.response_format,
            api_kind=entry.kind,
            recovered=True,
            trace=entry.trace,
            id=entry.request_id,
        )

    def export_session(self, request_id: int) -> dict | None:
        """Export a live session's migration ticket (fleet/migrate.py,
        ``GET /admin/session/<id>``): its admit wire record — prompt
        tokens, sampler params with the RESOLVED seed, QoS class,
        deadlines (serving/journal.admit_record) — plus a ``watermark``
        (tokens consumed so far, informational: the migration target
        re-buffers from 0 and the client's ``Last-Event-ID`` picks the
        resume point). ``None`` for unknown/finished requests — only an
        ADMITTED request has a resolved seed to regenerate from; queued
        ones are re-sent by the router, not migrated."""
        got = self._session_records.get(int(request_id))
        if got is None:
            return None
        rec, req = got
        out = dict(rec)
        out["watermark"] = len(req.generated_tokens)
        return out

    def run_device_op(self, fn: Callable, timeout_s: float = 10.0):
        """Run ``fn()`` on the batching-loop thread at its next step
        boundary and return its result (exceptions re-raise here, with
        their original type). Device-touching admin work — the disagg
        page export/import (``export_kv_page`` / ``import_kv_page``) —
        must NOT run on the calling HTTP thread: the pipelined chain
        donates the cache pytree into every dispatch, so an admin-thread
        read of ``engine.cache`` mid-chain hits a deleted buffer, and a
        write would fork the pytree against the next dispatch. At the
        loop's step boundary the consume half has rebound the live
        arrays and nothing is in flight against them.

        Runs ``fn`` inline when the loop is not running (tests, a
        drained server — nothing to race) or when already ON the loop
        thread. Raises ``TimeoutError`` if the loop never reaches a
        boundary within ``timeout_s`` (wedged step; callers surface it
        as a typed admin error, the router falls back monolithic)."""
        thread = self._thread
        if (
            thread is None
            or not thread.is_alive()
            or threading.current_thread() is thread
        ):
            return fn()
        box: dict = {}
        done = threading.Event()
        with self._device_ops_lock:
            self._device_ops.append((fn, box, done))
        if not done.wait(timeout_s):
            raise TimeoutError(
                "device op timed out waiting for a scheduler step boundary"
            )
        if "error" in box:
            raise box["error"]
        return box.get("value")

    def _drain_device_ops(self) -> None:
        """Loop-thread half of :meth:`run_device_op`: execute pending
        device ops at the step boundary. Op exceptions land in the
        caller's box (re-raised on ITS thread) — never in the serving
        loop, so a bad bundle cannot trip engine containment."""
        while True:
            with self._device_ops_lock:
                if not self._device_ops:
                    return
                fn, box, done = self._device_ops.pop(0)
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                box["error"] = e
            finally:
                done.set()

    def export_session_pages(self, request_id: int) -> dict | None:
        """Export a live session's committed KV-page bundle (disagg/
        kvtransfer.py, ``GET /admin/kvpages/<id>``): the prompt's
        registered prefix chain out of the paged pool, each page's
        payload integrity-hashed. ``None`` for unknown/finished requests
        and on contiguous engines (there are no pages to ship — the
        hand-off degrades to ticket-only migration, which re-prefills).
        Only FULL committed blocks export (immutable by the pool's
        granularity rule), so the bytes are stable while this replica
        keeps decoding the session."""
        if getattr(self.engine, "kvpool", None) is None:
            return None
        got = self._session_records.get(int(request_id))
        if got is None:
            return None
        from ..disagg.kvtransfer import export_bundle

        rec, _req = got
        tokens = list(rec.get("tokens") or ())
        # through the loop thread: export_kv_page reads engine.cache,
        # which the in-flight pipelined chain donates (run_device_op)
        return self.run_device_op(
            lambda: export_bundle(self.engine.kvpool, self.engine, tokens)
        )

    # -- internals ----------------------------------------------------------

    def _free_lane_indices(self) -> list[int]:
        return [i for i, l in enumerate(self._lanes) if l.request is None]

    def _paged_commit(self, lane_idx: int) -> None:
        """Register lane ``lane_idx``'s newly completed FULL blocks into
        the paged pool's prefix tree (host dict walk, incremental — a
        no-op for contiguous engines and for unfinished blocks). Called
        wherever ``_lane_kv`` grows: commits only ever trail the
        committed watermark, so shared pages are never write targets."""
        if getattr(self.engine, "kvpool", None) is not None:
            self.engine.paged_commit(lane_idx, self._lane_kv[lane_idx])

    def _paged_release(self, lane_idx: int, park: bool) -> None:
        """Release a lane's pages at request end: ``park=True`` keeps its
        tree-registered blocks resident for copy-free follow-ups (the
        oversubscription lever — resident sessions outnumber lanes),
        ``park=False`` frees everything (failure path: contents are not
        trusted). No-op for contiguous engines."""
        if getattr(self.engine, "kvpool", None) is not None:
            self.engine.paged_finish(lane_idx, park=park)

    def _grammar_release(self, lane: _Lane) -> None:
        """Detach a lane's grammar at request end (the tables PARK in the
        slab for the next same-schema admission). Never raises —
        containment paths call this too."""
        if lane.grammar is not None:
            try:
                self.engine.grammar_detach(lane.grammar.key)
            except Exception:  # noqa: BLE001 — release must not throw
                pass

    def _g_adv(self, lane: _Lane, tok: int) -> None:
        """Advance a constrained lane's HOST automaton mirror by one
        emitted token — called exactly once per NEW emitted token, so the
        mirror equals the device carry on the sync paths and trails it by
        the ring lag on the pipelined chain (where it only steers draft
        pre-filtering; the device state is authoritative)."""
        if lane.grammar is not None:
            lane.g_state = lane.grammar.next_state(lane.g_state, tok)

    def _g_states_sync(self, active) -> tuple[np.ndarray | None, bool]:
        """(per-lane grammar-state vector, any-constrained flag) for a
        synchronous dispatch: the host mirror is exact here. None when no
        lane is constrained — the engine defaults to all-FREE."""
        constrained = [
            (i, l) for i, l in active if l.grammar is not None
        ]
        if not constrained:
            return None, False
        gs = np.zeros(self.engine.n_lanes, np.int32)
        for i, lane in constrained:
            gs[i] = lane.g_state
        return gs, True

    def _count_masked_step(self) -> None:
        with self.engine.stats.lock:
            self.engine.stats.grammar_masked_steps += 1

    def occupancy(self) -> tuple[int, int]:
        """(busy lanes, total lanes) — public surface for /stats."""
        return (
            sum(1 for l in self._lanes if l.request is not None),
            len(self._lanes),
        )

    def qos_stats(self) -> dict:
        """QoS counters for /stats: queue depth/wait/rejections (when the
        queue tracks them) plus deadline enforcement and drain state."""
        out = {
            "draining": self.draining,
            "queue_timeouts": self.queue_timeouts,
            "budget_timeouts": self.budget_timeouts,
            # failure containment: engine-scoped containment rounds, the
            # breaker state machine, and the watchdog (0 trips when off)
            "engine_failure_rounds": self.engine_failures,
        }
        out.update(self.breaker.stats())
        if self.watchdog is not None:
            out.update(self.watchdog.stats())
        # crash durability: journal write accounting and — after a
        # --recover-journal restart — the replay counters; every field
        # is bridged to /metrics as a dllama_stats_* gauge (plus the
        # delta-fed native counters in telemetry/hub.bridge_stats), so
        # the two endpoints reconcile field-for-field
        if self.journal is not None:
            out.update(self.journal.stats())
        if self.recovery is not None:
            out.update(self.recovery.stats())
        stats = getattr(self.queue, "stats", None)
        if callable(stats):
            out.update(stats())
        # paged KV pool pressure (occupancy, prefix sharing, COW,
        # park/evict = drop-rebuild, exhaustion sheds): every field lands
        # on /stats and is bridged to /metrics as a dllama_stats_* gauge
        pool = getattr(self.engine, "pool_stats", None)
        if callable(pool):
            out.update(pool())
        # grammar slab pressure (schemas installed/live, state occupancy):
        # bridged to /metrics as dllama_stats_* gauges like every field
        gram = getattr(self.engine, "grammar_stats", None)
        if callable(gram):
            out.update(gram())
        return out

    def _on_watchdog_trip(self, waited_s: float) -> None:
        """Watchdog callback (runs on the watchdog thread): a dispatched
        step made no progress within the deadline. Trip the breaker —
        /health flips unhealthy and new work sheds — and flag the
        pipelined chain to abort at its next host-side opportunity (a
        slow-but-alive step eventually returns; the chain must not keep
        extending behind it). On pods the watchdog itself then crashes
        the process (fatal=True) — deliberate death over silent desync."""
        self.breaker.trip(
            f"watchdog: no step progress within {waited_s:.1f}s"
        )
        self._wd_abort.set()
        self.telemetry.on_watchdog_trip(
            waited_s,
            fatal=self.watchdog.fatal if self.watchdog is not None else False,
        )

    def _resolve_unadmitted(self, req: Request, reason: str) -> None:
        """Finish a request that never claimed a lane (queue timeout, cancel
        while queued): empty text, typed finish_reason."""
        req.state = RequestState.DONE
        req.finish_reason = reason
        self.telemetry.on_unadmitted(req, reason)
        if not req.future.done():
            req.future.set_result(req.generated_text)

    def _shed_unadmitted(self, req: Request) -> None:
        """Fail a request the drain window flushed before it ever claimed a
        lane: the client got no service, so it must see a retryable 503
        (AdmissionRejected, same shape submit() sheds with) — resolving it
        as an empty 200 "cancelled" would read as the model's answer and
        never be retried."""
        req.state = RequestState.FAILED
        req.finish_reason = "cancelled"
        self.telemetry.on_unadmitted(req, "shed")
        if not req.future.done():
            req.future.set_exception(AdmissionRejected("draining", retry_after_s=5.0))

    def _mirror_admit(self, req: Request, admit_kw: dict) -> None:
        """Publish the live-session mirror entry (the fleet migration
        ticket). Loop thread only; entries are built whole and assigned
        with a single-key dict op (GIL-atomic) so export_session can
        read whole tickets from HTTP threads. The declared acquire half
        of the session-record lifecycle (_dlint_acquires)."""
        self._session_records[req.id] = (admit_record(**admit_kw), req)

    def _mirror_finish(self, req: Request) -> None:
        """Retire the mirror entry — the declared release half; idempotent
        (a drain force-cancel may race a normal finish)."""
        self._session_records.pop(req.id, None)

    def _fail_request(self, lane_idx: int, req: Request, error: str,
                      exc: BaseException | None = None) -> None:
        """Fail ONE request with ``finish_reason="error"`` and reclaim its
        lane: the request-scoped containment unit (also the per-lane body
        of engine-scoped containment). The lane's resident-KV map is
        DISCARDED — after a failed dispatch the cache contents are
        unknown, and prefix caching must never reuse garbage. The
        future's exception carries the request_id (EngineFailure) unless
        the original exception is more specific (a tokenizer ValueError
        maps to a 400, not a 500)."""
        req.state = RequestState.FAILED
        req.error = error
        req.finish_reason = "error"
        # failed contents are final: the session can no longer migrate
        self._mirror_finish(req)
        self._grammar_release(self._lanes[lane_idx])
        self._lanes[lane_idx] = _Lane()
        self._lane_kv[lane_idx] = []
        try:
            # paged: free the lane's pages WITHOUT parking — after a
            # failed dispatch the cache contents are unknown, and the
            # prefix tree must never serve garbage
            self._paged_release(lane_idx, park=False)
            self.engine.reset_lane(lane_idx)
        except Exception:  # noqa: BLE001 — containment must not throw
            pass
        self.telemetry.on_error(req, lane_idx, error)
        if not req.future.done():
            req.future.set_exception(
                exc if exc is not None
                else EngineFailure(error, request_id=req.id)
            )
        if self.journal is not None:
            # recorded after the future resolves, like _finish: a lost
            # "error" finish record merely re-runs the request on
            # recovery, which is always safe
            self.journal.record_finish(
                req.id, "error",
                phases=(req.summary or {}).get("phases"),
            )

    def _sweep_queue(self, now: float) -> None:
        """Resolve queued requests that expired or were cancelled while
        waiting — without this, a saturated server (no lane ever frees, so
        nothing is ever popped) would hold its backlog open forever.
        Throttled to ~20 Hz: the walk is O(queue depth) under the queue
        lock, far too costly to contend with submit() on every decode
        step, and 50ms of extra expiry/cancel latency is immaterial."""
        remove_if = getattr(self.queue, "remove_if", None)
        if remove_if is None:  # custom queue without removal: pop-time checks still apply
            return
        if self.queue.empty() or now - self._last_sweep < 0.05:
            return
        self._last_sweep = now
        for req in remove_if(
            lambda r: r._cancelled.is_set()
            or queue_expired(r, self.deadlines, now)
        ):
            if req._cancelled.is_set():
                self._resolve_unadmitted(req, "cancelled")
            else:
                self.queue_timeouts += 1
                self._resolve_unadmitted(req, "timeout")

    def _claim_next(self, free: list[int], wait_s: float = 0.0):
        """Pop ONE queued request and claim a lane for it — the shared
        admission body behind the synchronous ``_admit`` loop and the
        in-chain ``_claim_admissions``: cancel/expiry resolution at pop
        time, the ``admitted_at`` stamp, tokenize+seed via
        ``_start_request`` with its failure handling. Returns the claimed
        lane index, ``None`` when the pop found nothing (stop polling), or
        ``-1`` when the popped request was resolved without taking a lane
        (cancelled/expired/failed — keep popping)."""
        req = self.queue.pop(timeout=wait_s)
        if req is None:
            return None
        now = time.monotonic()
        if self._observe_wait_at_claim:
            # bare-FIFO fallback: observe at pop time like the QosQueue
            # observer does, cancelled/expired pops included, so both
            # queue kinds feed the histogram the same population
            self.telemetry.on_queue_pop(req, now)
        if req._cancelled.is_set():
            self._resolve_unadmitted(req, "cancelled")
            return -1
        if queue_expired(req, self.deadlines, now):
            self.queue_timeouts += 1
            self._resolve_unadmitted(req, "timeout")
            return -1
        req.admitted_at = now
        lane_idx = free.pop(0)
        self.telemetry.on_admit(req, lane_idx)
        try:
            self._start_request(lane_idx, req)
        except Exception as e:
            # tokenization / validation errors fail ONLY this request
            # (finish_reason="error", original exception preserved so the
            # HTTP layer can 400 a ValueError); an engine-scoped raise
            # (the prefix-cache lane copy is a device op) fails it too,
            # then propagates to the supervisor for full containment
            self._fail_request(lane_idx, req, str(e), exc=e)
            if classify_failure(e) == "engine":
                raise
            self.breaker.record_request_failure()
            return -1
        return lane_idx

    def _admit(self, wait_s: float = 0.0) -> None:
        free = self._free_lane_indices()
        while free:
            claimed = self._claim_next(free, wait_s)
            wait_s = 0.0  # only the first pop may park; the rest are polls
            if claimed is None:
                return

    def _start_request(self, lane_idx: int, req: Request) -> None:
        """Tokenize and claim a lane. Prompt processing itself happens one
        bucket per scheduler iteration in ``_prefill_step`` so concurrent
        decoding lanes are never stalled by a long admission prefill
        (VERDICT Weak #2; the reference stalls all lanes, src/app.cpp:360-366)."""
        req.state = RequestState.PROMPT_PROCESSING
        tokens = self.tokenizer.encode(
            req.prompt, add_bos=req.add_bos, add_special_tokens=req.add_special_tokens
        )
        if not tokens:
            raise ValueError("prefill needs at least one token (empty prompt)")
        max_ctx = self.engine.config.seq_len
        if len(tokens) >= max_ctx:
            # keep the tail (the reference just aborts; truncation serves better)
            tokens = tokens[-(max_ctx - req.max_tokens - 1) :] if max_ctx > req.max_tokens + 1 else tokens[-max_ctx + 1 :]
        req.n_prompt_tokens = len(tokens)

        # prefix caching. Paged engines (engine.kvpool set): admission
        # charges the lane's whole potential range in PAGES up front and
        # the pool's prefix tree serves shared leading blocks by refcount
        # bump on the SAME physical pages — zero HBM copies, plus at most
        # one single-page copy-on-write at the divergent block. Contiguous
        # engines: if some lane's resident KV (finished lanes included —
        # their cache persists until overwritten) shares a long enough
        # prompt prefix, copy that lane's KV (an HBM move, orders of
        # magnitude cheaper than prefill) and prefill only the tail. A
        # chat follow-up landing on its own previous lane hits with
        # src == dst, which copy_lane no-ops.
        start = 0
        if getattr(self.engine, "kvpool", None) is not None:
            # +1 reserves the slot the boundary token's own KV write needs
            # when generation runs to max_tokens exactly
            reserve = min(len(tokens) + req.max_tokens + 1, max_ctx)
            # per-request swap-in attribution (phases record): the
            # engine's cumulative swap_in_ms only moves inside THIS
            # paged_admit call on this loop thread, so the delta is
            # exactly the host-tier reactivation cost this admission paid
            swap_ms0 = float(getattr(self.engine, "swap_in_ms", 0.0) or 0.0)
            try:
                start = self.engine.paged_admit(
                    lane_idx, list(tokens), reserve,
                    min_share_tokens=self.prefix_min_tokens,
                )
                swap_ms1 = float(
                    getattr(self.engine, "swap_in_ms", 0.0) or 0.0
                )
                if swap_ms1 > swap_ms0:
                    self.telemetry.trace_of(req).swap_in_s = (
                        (swap_ms1 - swap_ms0) / 1e3
                    )
            except PoolExhausted as e:
                # typed retryable shed (the 429/503 + Retry-After shape
                # submit() sheds with), never a 500: a pool pinned by
                # active lanes is load, not engine failure. Counted on
                # the QoS rejection surface like every other shed reason
                # (queue_full/draining/breaker_open), so dashboards on
                # the rejection counters see paged-pool sheds too. The
                # tiered-residency distinction rides the reason string:
                # "host_tier_full" means the swap tier was enabled AND
                # at budget when the shed fired — the operator's lever
                # is --kv-host-bytes, not --kv-pool-pages.
                reason = (
                    "host_tier_full"
                    if getattr(e, "host_tier_full", False)
                    else "pool_exhausted"
                )
                note = getattr(self.queue, "note_rejection", None)
                if note is not None:
                    note(reason)
                raise AdmissionRejected(
                    reason, retry_after_s=1.0
                ) from e
        elif (
            self.prefix_min_tokens > 0
            and getattr(self.engine, "copy_lane", None) is not None
        ):
            best_lane, best_lcp = -1, 0
            for j, kv in enumerate(self._lane_kv):
                if not kv:
                    # discarded resident map (_fail_request after a failed
                    # dispatch, or a never-used lane): probing the dead
                    # entry is wasted work and must never win the scan
                    continue
                lcp = _common_prefix_len(tokens, kv)
                if lcp > best_lcp:
                    best_lane, best_lcp = j, lcp
            best_lcp = min(best_lcp, len(tokens) - 1)  # >= 1 token to prefill
            if best_lcp >= self.prefix_min_tokens:
                self.engine.copy_lane(best_lane, lane_idx,
                                      prefix_len=best_lcp)
                start = best_lcp
        if start > 0:  # one accounting site for both layouts
            self.telemetry.on_prefix_hit(req, start)
            with self.engine.stats.lock:
                self.engine.stats.prefix_hits += 1
                self.engine.stats.prefix_tokens_saved += start
        self._lane_kv[lane_idx] = list(tokens[:start])

        lane = self._lanes[lane_idx]
        lane.request = req
        lane.pos = start
        lane.pending = list(tokens[start:])
        lane.drafter = NgramDraftIndex(tokens)  # seed with the prompt
        # unseeded requests draw OS entropy (utils/seeds.py), not the wall
        # clock: two requests admitted in the same clock tick must not
        # sample identical streams, and NTP steps must not replay seeds
        lane.seed = (
            req.seed if req.seed is not None else fresh_seed()
        ) & 0xFFFFFFFF
        # the on-device sampler is full-vocab exact, so host-exact survives
        # only as the host_sampling=True escape hatch (bit-exact reference
        # xorshift streams); wide-nucleus/high-temp requests stay on device.
        # Constrained requests stay on device UNCONDITIONALLY: the grammar
        # mask lives inside the compiled step, and a host xorshift draw
        # over unmasked logits could emit an illegal token.
        lane.host_exact = self.host_sampling and req.response_format is None
        if lane.host_exact and req.temperature > 0.0:
            with self.engine.stats.lock:
                self.engine.stats.host_exact_lanes += 1
        # structured output (grammar/): compile + attach the automaton
        # BEFORE the admit record, so a schema that fails to compile
        # fails the request with no journal entry to resurrect. The
        # ValueError family (GrammarError, unsupported engine) is
        # request-scoped -> HTTP 400; a slab exhausted by live schemas
        # sheds retryably like the paged pool.
        if req.response_format is not None:
            from ..grammar.slab import GrammarSlabFull

            try:
                lane.grammar = self.engine.grammar_attach(
                    req.response_format
                )
            except GrammarSlabFull as e:
                note = getattr(self.queue, "note_rejection", None)
                if note is not None:
                    note("grammar_slab_full")
                raise AdmissionRejected(
                    "grammar_slab_full", retry_after_s=1.0
                ) from e
            lane.g_state = lane.grammar.start_state
        lane.sampler = Sampler(
            self.engine.config.vocab_size, req.temperature, req.topp, lane.seed
        )
        stops = list(req.stop) or self._chat_stops.stops
        lane.eos = EosDetector(
            self.tokenizer.eos_token_ids, stops, self.eos_padding[0], self.eos_padding[1]
        )
        lane.decoder = self.tokenizer.make_stream_decoder()
        # admit record LAST, with the RESOLVED seed (an unseeded request
        # just drew OS entropy into lane.seed): everything a deterministic
        # replay needs, and nothing is recorded for a request that failed
        # tokenization above (no admit record -> nothing to resurrect or
        # migrate). ONE kwargs set feeds both consumers — the journal's
        # on-disk record and the live-session mirror export_session serves
        # as the fleet migration ticket — so the two cannot drift.
        admit_kw = dict(
            request_id=req.id, prompt=req.prompt, tokens=list(tokens),
            max_tokens=req.max_tokens, temperature=req.temperature,
            topp=req.topp, seed=int(lane.seed), stop=list(req.stop),
            add_bos=req.add_bos,
            add_special_tokens=req.add_special_tokens,
            user=req.user_id, priority=int(req.priority),
            queue_timeout_s=req.queue_timeout_s, budget_s=req.budget_s,
            stream=req.on_delta is not None, kind=req.api_kind,
            response_format=req.response_format, trace=req.trace,
        )
        self._mirror_admit(req, admit_kw)
        if self.journal is not None:
            # the call only enqueues — the journal's writer thread does
            # the file I/O off this loop
            self.journal.record_admit(**admit_kw)

    def _prefill_step(self) -> bool:
        """Advance ONE admitting lane by one prompt bucket (round-robin).
        Returns True when a chunk was processed."""
        n = len(self._lanes)
        admitting = [
            i for i in range(n)
            if self._lanes[i].request is not None and self._lanes[i].pending
        ]
        if not admitting:
            return False
        # round-robin so several admitting prompts make progress together
        lane_idx = min(admitting, key=lambda i: (i - self._prefill_rr) % n)
        self._prefill_rr = (lane_idx + 1) % n
        lane = self._lanes[lane_idx]
        req = lane.request
        chunk = lane.pending[: self.engine.max_chunk()]
        t_chunk = time.perf_counter()
        wd = self.watchdog
        if wd is not None:
            wd.begin_step()
        try:
            logits, greedy, sampled = self.engine.prefill_chunk(
                lane_idx, chunk, lane.pos,
                temp=0.0 if lane.host_exact else req.temperature,
                topp=req.topp, seed=lane.seed,
                # boundary token (the first generated one, on the final
                # chunk) samples under the automaton's start-state mask
                g_state=lane.g_state,
            )
        except Exception as e:
            # request-scoped (chunk validation, the ValueError family):
            # fail this request only; engine-scoped (a dispatch raise):
            # propagate to the supervisor, which flushes the pipeline and
            # fails every affected lane — this one included
            if classify_failure(e) == "engine":
                raise
            self._fail_request(lane_idx, req, str(e), exc=e)
            self.breaker.record_request_failure()
            return True
        finally:
            if wd is not None:
                wd.step_done()
        self.breaker.record_success()
        self.telemetry.on_prefill_chunk(req, lane_idx, t_chunk, len(chunk))
        lane.pos += len(chunk)
        lane.pending = lane.pending[len(chunk):]
        self._lane_kv[lane_idx].extend(chunk)  # committed: prefix-cacheable
        self._paged_commit(lane_idx)
        if lane.pending:
            return True
        # prompt complete: pick the first generated token
        if req.temperature == 0.0:
            first = int(greedy)
        elif lane.host_exact:
            # dlint: ok[host-sync] host-exact lane: one [n,vocab] f32 batch at prompt end, counted by all_logits
            first = lane.sampler.sample(self.engine.all_logits(logits))
        else:
            first = int(sampled)  # sampled inside the compiled prefill step
        lane.next_token = first
        self._g_adv(lane, first)
        req.state = RequestState.GENERATING
        return True

    def _consume(self, lane_idx: int, lane: _Lane, tok: int) -> bool:
        """Emit one generated token on a lane: stream-decode, EOS/stop
        detection, delta callbacks, position advance, length check. Returns
        False when the lane finished (EOS or length — or failed: a
        detokenize/EOS/delta raise is request-scoped, failing only this
        request while the batch keeps decoding)."""
        req = lane.request
        try:
            return self._consume_inner(lane_idx, lane, req, tok)
        except Exception as e:  # noqa: BLE001 — request-scoped by construction
            # everything in here is host-side per-request work (stream
            # decoder, EOS detector, delta callback): a raise of ANY type
            # says nothing about engine health, so it fails this request
            # only — no classification needed
            self._fail_request(lane_idx, req, str(e), exc=e)
            self.breaker.record_request_failure()
            return False

    def _consume_inner(self, lane_idx: int, lane: _Lane, req: Request,
                       tok: int) -> bool:
        req.generated_tokens.append(tok)
        # per-token stamp: first token observes TTFT, later ones the
        # inter-token gap (multi-step/spec bursts land near-zero gaps —
        # that IS when their stream deltas reach the client)
        self.telemetry.on_token(req)
        self._lane_kv[lane_idx].append(tok)  # its KV write is committed
        self._paged_commit(lane_idx)
        lane.drafter.append(tok)
        piece = lane.decoder.decode(tok)
        result = lane.eos.append(tok, piece)
        if result == EosResult.EOS:
            self._finish(lane_idx, req)
            return False
        if result == EosResult.NOT_EOS:
            delta = lane.eos.get_delta()
            if delta:
                req.generated_text += delta
                if req.on_delta:
                    req.on_delta(delta)
            lane.eos.reset()
        # MAYBE_EOS: hold back
        lane.pos += 1
        if (
            len(req.generated_tokens) >= req.max_tokens
            or lane.pos >= self.engine.config.seq_len
        ):
            self._finish(lane_idx, req, reason="length")
            return False
        return True

    def _multi_horizon(self, active, prefilled: bool) -> int:
        """How many decode steps to chain in one device dispatch (0/1 =
        plain single step). Multi-step is correct only in steady-state
        decode: no prompt chunk was processed this iteration (no lane is
        admitting), nothing is queued (unlike the fused pipelined path,
        ``decode_multi`` cannot carry a prompt chunk, so an admission
        would wait out the whole horizon — the queue check stays even
        though the pipelined gate dropped it), and no active lane needs
        host-exact sampling (it reads full logits every step). The horizon
        is capped by the longest-remaining lane and bucketed to powers of
        two so at most log2(multi_step) programs ever compile."""
        if self.multi_step <= 1 or prefilled:
            return 0
        if not getattr(self.engine, "supports_multi_step", False):
            return 0
        if not self.queue.empty():
            return 0
        if any(l.host_exact and l.request.temperature > 0 for _, l in active):
            return 0
        rem = 0
        for _, lane in active:
            req = lane.request
            rem = max(rem, min(
                req.max_tokens - len(req.generated_tokens),
                self.engine.config.seq_len - lane.pos,
            ))
        from .spec import pow2_floor

        p = pow2_floor(min(self.multi_step, rem))
        return p if p > 1 else 0

    def _fused_ok(self) -> bool:
        """Fused prefill+decode admissions available: the flag is on, the
        engine compiles the fused step family, and pipelining is live."""
        return (
            self.fused_prefill
            and self.pipelined
            and getattr(self.engine, "supports_fused_prefill", False)
            and getattr(self.engine, "supports_pipelined", False)
            and getattr(self.engine, "pipeline_depth", 0) >= 2
        )

    def _spec_pl_ok(self) -> bool:
        """Speculation rides the pipelined chain (the zero-flush path): the
        engine compiles the in-chain verify family and speculation is on.
        When False (engine without the family, or speculative=False), the
        pre-zero-flush behavior applies: a draft hit flushes to the
        synchronous spec path."""
        return (
            self.speculative
            and getattr(self.engine, "SPEC_DRAFT", 0) > 0
            and getattr(self.engine, "supports_speculative", False)
            and getattr(self.engine, "supports_spec_pipelined", False)
        )

    def _drafts_pending(self, live: dict) -> bool:
        """Host-side probe: does any GENERATING greedy lane's history draft?
        A hit is a pipeline flush condition ONLY for engines without the
        in-chain verify family (``_spec_pl_ok`` False) — there the sync
        spec path emits >1 token per forward and wins. Lanes still
        mid-admission (their first token not yet consumed) are skipped:
        their ``next_token`` is not set."""
        spec_k = (
            getattr(self.engine, "SPEC_DRAFT", 0)
            if self.speculative
            and getattr(self.engine, "supports_speculative", False)
            else 0
        )
        if spec_k <= 0:
            return False
        seq_len = self.engine.config.seq_len
        return any(
            lane.request.state == RequestState.GENERATING
            and lane.request.temperature == 0.0
            and seq_len - lane.pos - 1 > 0
            and lane.drafter.draft(lane.next_token, spec_k)
            for lane in live.values()
        )

    def _pipeline_ok(self, active, prefilled: bool = False) -> bool:
        """Gate for the pipelined path. The unconditional steady-state
        terms: engine support, a ring depth that actually buys a lag, and
        no host-exact-sampling lane (it reads full logits every step).
        With fused prefill, a queued admission or a pending prompt chunk
        no longer disqualifies — the chain itself claims lanes and streams
        their chunks through fused dispatches, so admission never exits
        steady state. Without it, the pre-fused conditions apply (nothing
        queued, no admitting lane, no chunk processed this iteration).
        Drafts are the caller's business: when the speculative probe
        produced any, the spec path runs instead."""
        if not self.pipelined or prefilled:
            return False
        if not getattr(self.engine, "supports_pipelined", False):
            return False
        if getattr(self.engine, "pipeline_depth", 0) < 2:
            return False
        # ALL occupied lanes, not just the generating ones: a host-exact
        # request still mid-admission (claimed by the sync _admit) must
        # keep the whole batch on the synchronous path too — its boundary
        # token needs the full-logits host sampler, which neither the
        # fused prefill nor the pipelined decode ever reads back
        if any(
            l.request is not None
            and l.host_exact
            and l.request.temperature > 0
            for l in self._lanes
        ):
            return False
        if self._fused_ok():
            return True
        if not self.queue.empty():
            return False
        return not any(
            l.request is not None and l.pending for l in self._lanes
        )

    def _claim_admissions(self, admitting: dict) -> bool:
        """Claim queued requests into free lanes WITHOUT leaving the chain
        (the fused-prefill admission path): pop, stamp, tokenize, seed the
        lane — host work only (plus the async prefix-cache lane copy) —
        and hand the lane to the dispatch half, which streams its chunks
        through fused dispatches. Returns False when a claimed lane needs
        the synchronous path (host-exact sampling reads full logits every
        step), the one admission kind that still flushes; the sync loop
        picks its pending chunks up after the drain."""
        free = self._free_lane_indices()
        if not free or self.queue.empty():
            return True
        # claim time is a real decode-lane stall only when the ring is
        # empty (nothing dispatched for the device to chew on meanwhile)
        stalled = self.engine.pipeline_inflight() == 0
        t0 = time.perf_counter()
        ok = True
        while free:
            claimed = self._claim_next(free)
            if claimed is None:
                break
            if claimed < 0:
                continue
            lane = self._lanes[claimed]
            if lane.host_exact and lane.request.temperature > 0:
                ok = False  # needs the sync path: flush after this claim
                break
            admitting[claimed] = lane
            self.telemetry.on_fused_admit(lane.request)
        if stalled:
            with self.engine.stats.lock:
                self.engine.stats.admission_stall_s += (
                    time.perf_counter() - t0
                )
        return ok

    def _pipeline_dispatch(self, live: dict, admitting: dict, feed,
                           spec_ok: bool = False):
        """Dispatch half of the pipelined loop: queue the next decode step
        from host-side lane METADATA only — sampling params, the ``-1``
        carried-position sentinel for live lanes (their write positions
        ride the DEVICE carry: a spec verify step advances each lane by
        its own accept count, which the host only learns one step behind),
        and, when an admitting lane has prompt chunks pending, ONE bounded
        chunk for ONE lane (round-robin, the sync ``_prefill_step`` rule)
        piggybacked on the SAME dispatch via ``engine.decode_prefill_fused``.

        When ``spec_ok`` (speculation rides the chain, no spec step
        already in flight, ring lag <= 1), the dispatch also probes each
        GENERATING greedy lane's n-gram index — a pure host-side lookup,
        no device value is touched — and ships up to SPEC_DRAFT+1 draft
        candidates with the dispatch (``engine.decode_spec_pipelined`` /
        ``decode_spec_prefill_fused`` when a chunk rides too). Candidate 0
        is the host's guess at the device's carry token (the index is one
        step behind — the consume half's own lag); the device verifies it
        before counting the rest, so a stale probe costs acceptance, never
        correctness, and the chain NEVER flushes for a draft hit.

        The tokens stay on device (``feed=None`` selects the engine's
        carry); nothing in here may read a device value back, or the whole
        overlap dies — machine-checked by dlint's pipeline-sync.

        Returns ``(fused_info, spec_drafted)``: ``fused_info`` is
        ``(lane_idx, lane, final, n_chunk)`` for a chunk-carrying dispatch
        (None otherwise); ``spec_drafted`` is ``{lane_idx: True}`` for
        lanes whose shipped drafts can accept (None for a non-spec step —
        the consume half needs it to interpret the packed readback and to
        scope the acceptance counters to drafted lanes). Chunk bookkeeping
        — ``lane.pos``, ``lane.pending``, ``_lane_kv`` — commits here at
        DISPATCH time: the chunk's KV writes execute in dispatch order
        whether or not the step's outputs are ever consumed, so the
        resident-KV map stays truthful even for a request cancelled
        mid-prompt."""
        engine = self.engine
        n_lanes = engine.n_lanes
        seq_len = engine.config.seq_len
        reseed = feed is not None
        # idle/finished lanes park at seq_len: the mode="drop" KV scatter
        # discards their junk writes (same rule as the sync loop). An
        # admitting lane parks there too — its REAL writes this step are
        # the fused chunk's, not the decode half's. Live lanes read the
        # device position carry (-1) except on a reseed, where the ring is
        # empty and the host's committed positions are exact.
        positions = np.full(n_lanes, seq_len, np.int32)
        temps = np.zeros(n_lanes, np.float32)
        topps = np.full(n_lanes, DEFAULT_TOPP, np.float32)
        seeds = np.zeros(n_lanes, np.uint32)
        # grammar states ride the dispatch like positions: -1 = the
        # device carry (authoritative in-chain), host mirror on a reseed
        # (ring empty: the mirror is exact), 0 = FREE for idle/admitting
        # lanes (an admitting lane's constraint enters via p_g below)
        g_any = any(
            l.grammar is not None
            for l in (*live.values(), *admitting.values())
        )
        gs = np.zeros(n_lanes, np.int32) if g_any else None
        for i, lane in live.items():
            positions[i] = min(lane.pos, seq_len) if reseed else -1
            if gs is not None:
                gs[i] = lane.g_state if reseed else -1
            temps[i] = lane.request.temperature
            topps[i] = lane.request.topp
            seeds[i] = lane.seed
        if g_any:
            self._count_masked_step()
        # draft probe (host-side n-gram lookup over committed history +
        # the last known fed token; legal here by construction — dlint's
        # pipeline-sync pins that nothing below syncs a device value)
        drafts = draft_len = None
        drafted: dict[int, bool] = {}
        if spec_ok:
            spec_k = engine.SPEC_DRAFT
            for i, lane in live.items():
                req = lane.request
                if (
                    req.state != RequestState.GENERATING
                    or req.temperature != 0.0
                    or seq_len - lane.pos - 1 <= 0
                ):
                    continue
                nt = lane.next_token
                if reseed:
                    # ring empty: nt IS this dispatch's feed — ship it as
                    # candidate 0 (the carry gate passes trivially)
                    d = [nt] + lane.drafter.draft(nt, spec_k)
                    if lane.grammar is not None and len(d) > 1:
                        # pre-filter through the host mirror (exact here:
                        # nt is already counted in it) — a draft the mask
                        # would reject is simply not proposed
                        d = d[: 1 + lane.grammar.filter_prefix(
                            lane.g_state, d[1:]
                        )]
                else:
                    # one step behind: nt fed the in-flight step; its
                    # output is the carry, so the probe's first
                    # continuation IS the carry candidate
                    d = lane.drafter.draft(nt, spec_k + 1)
                    if lane.grammar is not None and d:
                        # mirror trails the device by the in-flight step;
                        # filtering from it is approximate — harmless
                        # (device verification is exact), it only avoids
                        # shipping obviously illegal candidates
                        d = d[: lane.grammar.filter_prefix(
                            lane.g_state, d
                        )]
                if len(d) >= 2:  # candidate 0 alone cannot accept anything
                    if drafts is None:
                        drafts = np.zeros((n_lanes, spec_k + 1), np.int32)
                        draft_len = np.zeros(n_lanes, np.int32)
                    drafts[i, : len(d)] = d
                    draft_len[i] = len(d)
                    drafted[i] = True
        target = None
        if admitting:
            # round-robin over admitting lanes so several prompts make
            # progress together, one chunk per dispatch
            target = min(
                admitting, key=lambda i: (i - self._prefill_rr) % n_lanes
            )
            self._prefill_rr = (target + 1) % n_lanes
        if target is None:
            if drafts is None:
                engine.decode_pipelined(positions, temps, topps, seeds,
                                        tokens=feed, g_states=gs)
                return None, None
            engine.decode_spec_pipelined(
                positions, drafts, draft_len, temps, topps, seeds,
                tokens=feed, g_states=gs,
            )
            return None, drafted
        lane = admitting[target]
        req = lane.request
        chunk = lane.pending[: engine.max_chunk()]
        # the admitting lane's boundary token samples under its
        # automaton's START state (== lane.g_state until its first
        # emission); junk for mid-prompt chunks, decisive on the final one
        p_g = lane.g_state if lane.grammar is not None else 0
        if drafts is None:
            engine.decode_prefill_fused(
                positions, temps, topps, seeds,
                p_lane=target, chunk=chunk, p_start=lane.pos,
                p_temp=0.0 if lane.host_exact else req.temperature,
                p_topp=req.topp, p_seed=lane.seed,
                tokens=feed, g_states=gs, p_g=p_g,
            )
        else:
            # the full composition: an admitting chunk and a spec verify
            # step share one dispatch
            engine.decode_spec_prefill_fused(
                positions, drafts, draft_len, temps, topps, seeds,
                p_lane=target, chunk=chunk, p_start=lane.pos,
                p_temp=0.0 if lane.host_exact else req.temperature,
                p_topp=req.topp, p_seed=lane.seed,
                tokens=feed, g_states=gs, p_g=p_g,
            )
        lane.pos += len(chunk)
        lane.pending = lane.pending[len(chunk):]
        self._lane_kv[target].extend(chunk)  # committed: prefix-cacheable
        self._paged_commit(target)
        return (
            (target, lane, not lane.pending, len(chunk)),
            drafted if drafts is not None else None,
        )

    def _pipeline_consume(self, live: dict, entry: tuple) -> None:
        """Consume half, one step behind: block on the oldest in-flight
        step's packed token readback and run the host work the synchronous
        loop does inline — stream decode, EOS/stop, cancel/budget checks —
        while the younger dispatches keep the device busy. ``entry`` is
        ``(step_lanes, fused, t_dispatch, spec_drafted)`` recorded AT
        DISPATCH TIME: ``step_lanes`` pairs each live lane index with its
        lane OBJECT — the identity check skips both lanes that finished at
        an earlier consumed step AND lanes already reclaimed by a NEW
        request while this step was still in flight (either way the
        column is junk, and its in-flight KV writes die under the
        overwrite-before-readable rule). ``fused`` is the dispatch half's
        ``(lane_idx, lane, final, n_chunk)`` for a chunk-carrying step,
        whose extra readback column (row, for a spec pack) carries the
        chunk's boundary token pair: on the FINAL chunk that token is the
        request's first generated token, committed here exactly one step
        behind — the same point the synchronous path would have read it.
        ``spec_drafted`` (None for a plain step) marks the step as a spec
        verify: the readback is ``decode_spec``'s (emitted, n_emit) pack,
        each live lane commits a VARIABLE-LENGTH accept — next_token + the
        accepted drafts, exactly the sync spec path's feed sequence — and
        drafted lanes feed the acceptance counters (consumed-only, and
        only when the lane actually fed tokens: a lane cancelled mid-draft
        must not count a lane-step with zero emitted, which would push the
        bench acceptance ratio below its [1, K+1] class). ``t_dispatch``
        is the step's dispatch stamp: the telemetry slice spans dispatch
        -> this lagged readback, recorded HERE (the consume half) so the
        dispatch half stays span-free (dlint pipeline-sync)."""
        wd = self.watchdog
        if wd is not None:
            wd.begin_step()
        try:
            out_a, out_b = self.engine.pipeline_consume()
        finally:
            if wd is not None:
                wd.step_done()
        self.breaker.record_success()
        now = time.monotonic()
        step_lanes, fused, t_dispatch, spec_drafted = entry
        is_spec = spec_drafted is not None
        self.telemetry.on_pipelined_step(
            t_dispatch, fused, kind="spec_pipelined" if is_spec else "pipelined"
        )
        if is_spec:
            emitted, n_emit = out_a, out_b
        else:
            greedy_np, sampled_np = out_a, out_b
        for i, lane in step_lanes:
            if live.get(i) is not lane:
                continue  # finished earlier (or lane reclaimed): junk column
            req = lane.request
            if req._cancelled.is_set():
                self._finish(i, req, reason="cancelled")
                live.pop(i)
                continue
            if budget_expired(req, self.deadlines, now):
                self.budget_timeouts += 1
                self._finish(i, req, reason="timeout")
                live.pop(i)
                continue
            if is_spec:
                # variable-length commit: next_token + the accepted drafts
                # (the plain-decode stream, per the verification identity);
                # the model's token after the accepted prefix becomes the
                # new pending token — the sync spec path's rule verbatim
                cnt = int(n_emit[i])
                if lane.grammar is not None:
                    # catch the host mirror up by the whole lagged window
                    for t in emitted[i, :cnt]:
                        self._g_adv(lane, int(t))
                seq = [lane.next_token] + [
                    int(t) for t in emitted[i, : cnt - 1]
                ]
                alive = True
                n_fed = 0
                for t in seq:
                    n_fed += 1
                    if not self._consume(i, lane, t):
                        alive = False
                        break
                if spec_drafted.get(i) and n_fed:
                    with self.engine.stats.lock:
                        self.engine.stats.spec_lane_steps += 1
                        self.engine.stats.spec_emitted += n_fed
                        acc = cnt - 1  # the device's accept count
                        self.engine.stats.spec_accept_hist[acc] = (
                            self.engine.stats.spec_accept_hist.get(acc, 0)
                            + 1
                        )
                if not alive:
                    live.pop(i)
                    continue
                lane.next_token = int(emitted[i, cnt - 1])
                continue
            if not self._consume(i, lane, lane.next_token):
                live.pop(i)
                continue
            # the token this lane fed into the NEXT in-flight step — the
            # on-device feed rule, reconstructed for host bookkeeping
            if req.temperature == 0.0:
                lane.next_token = int(greedy_np[i])
            else:
                lane.next_token = int(sampled_np[i])
            self._g_adv(lane, lane.next_token)
        if fused is not None:
            i, lane, final, _n_chunk = fused
            if final and live.get(i) is lane:
                # prompt complete: adopt the boundary token as the first
                # generated token (greedy at temp 0, fused-sampled else —
                # host-exact admissions never take the fused path) and go
                # GENERATING. The lane already joined the dispatch half's
                # live set when its final chunk went out; the carry fed it
                # on device, and the NEXT consumed step emits this token.
                # Spec packs carry the boundary pair in the extra ROW's
                # first two columns; token packs in the extra COLUMN.
                req = lane.request
                if is_spec:
                    b_greedy = int(emitted[-1, 0])
                    b_sampled = int(emitted[-1, 1])
                else:
                    b_greedy = int(greedy_np[-1])
                    b_sampled = int(sampled_np[-1])
                lane.next_token = (
                    b_greedy if req.temperature == 0.0 else b_sampled
                )
                # mirror: start state advanced by the boundary emission
                self._g_adv(lane, lane.next_token)
                req.state = RequestState.GENERATING

    def _run_pipelined(self, active) -> None:
        """Steady-state pipelined decode: keep the ring at ``pipeline_depth``
        dispatched steps, consuming the oldest one step behind — step k's
        detokenize/stream/stop work overlaps step k+1's device execution
        instead of serializing ahead of it.

        With fused prefill (the default), admission is part of steady
        state, not an exit: a queued request claims a free lane in-chain
        (``_claim_admissions``), its prompt chunks ride fused dispatches
        (``_pipeline_dispatch``), and when the final chunk goes out the
        lane joins the decode half fed by the on-device carry — the chain
        never breaks and ``pipeline_flushes`` stays 0 under churn.

        Speculation is part of steady state too (the zero-flush tentpole):
        when the engine compiles the in-chain verify family
        (``_spec_pl_ok``), a greedy lane whose history drafts ships its
        candidates WITH the dispatch (``decode_spec_pipelined``, or the
        chunk-carrying ``decode_spec_prefill_fused``) and the consume half
        commits the variable-length accept one step behind — speculation's
        extra tokens MULTIPLY with the overlap instead of aborting it.
        Probing is gated to dispatches whose ring lag is <= 1 with no
        other spec step in flight: past that the host's one-step-behind
        carry candidate cannot align, so drafts would verify-and-miss
        (correct but pointless).

        Exits by DRAINING the remaining in-flight steps through the normal
        consume path (their tokens are valid — no generated token is ever
        discarded for a live lane) when a flush condition appears: stop(),
        a draft hit on an engine WITHOUT the in-chain verify family, a
        host-exact admission (host_sampling mode reads full logits every
        step, so the sync path must run it), a queued admission with fused
        prefill OFF, or every lane finishing. An exit with lanes still
        live counts as a pipeline flush in the engine stats."""
        engine = self.engine
        depth = max(2, int(getattr(engine, "pipeline_depth", 2)))
        fused = self._fused_ok()
        spec_chain = self._spec_pl_ok()
        live: dict[int, _Lane] = dict(active)
        # lanes still streaming prompt chunks (sync-admitted leftovers on
        # entry; in-chain claims join via _claim_admissions)
        admitting: dict[int, _Lane] = {}
        if fused:
            admitting = {
                i: l for i, l in enumerate(self._lanes)
                if l.request is not None and l.pending and i not in live
            }
            for l in admitting.values():
                # sync-admitted leftovers joining the chain: their
                # remaining chunks ride fused dispatches too
                self.telemetry.on_fused_admit(l.request)
        feed = np.zeros(engine.n_lanes, np.int32)
        for i, lane in live.items():
            feed[i] = lane.next_token
        # (live lanes, fused info, dispatch stamp, spec-drafted set) per
        # dispatch — positions no longer tracked host-side: they ride the
        # device carry (spec accept counts are only known one step behind)
        meta: deque = deque()
        host_feed = True  # first dispatch reseeds the chain from host tokens
        dispatched_any = False
        # both entry gates (_run's early fused entry and the post-spec
        # branch) just probed the drafters; skip the duplicate probe on
        # the first iteration of the hot loop
        probe_drafts = False
        while True:
            now = time.monotonic()
            # queued cancels/expiries must not wait out a long chain
            # (throttled internally to ~20 Hz)
            self._sweep_queue(now)
            # an admitting request cancelled/expired mid-prompt: stop
            # streaming its chunks; the in-flight ones are junk-KV-safe
            for i in [
                j for j, l in admitting.items()
                if l.request._cancelled.is_set()
                or budget_expired(l.request, self.deadlines, now)
            ]:
                lane = admitting.pop(i)
                if lane.request._cancelled.is_set():
                    self._finish(i, lane.request, reason="cancelled")
                else:
                    self.budget_timeouts += 1
                    self._finish(i, lane.request, reason="timeout")
            flush = (
                self._stop.is_set()
                or self._wd_abort.is_set()  # watchdog: abort the chain
                or (not live and not admitting)
            )
            if not flush and fused:
                # a claimed lane whose chunks cannot ride the chain (a
                # host-exact admission): only the synchronous path can
                # serve it — keep flushing until it does. Checked every
                # iteration, not just at claim time, so the lane is never
                # starved behind a long-lived chain.
                flush = any(
                    l.request is not None
                    and l.pending
                    and i not in admitting
                    and i not in live
                    for i, l in enumerate(self._lanes)
                )
            if not flush and not self.queue.empty():
                if fused:
                    # admissions ride the chain; only a host-exact claim
                    # still needs the synchronous path
                    flush = not self._claim_admissions(admitting)
                else:
                    flush = True
            if not flush and probe_drafts and not spec_chain:
                # engines without the in-chain verify family: a draft hit
                # still flushes to the synchronous spec path
                flush = self._drafts_pending(live)
            probe_drafts = True  # entry gates probed already; re-check
            # from the second iteration on (new tokens land per consume)
            while not flush and engine.pipeline_inflight() < depth:
                # dispatch stamp taken HERE, not inside the dispatch half:
                # the consume half pairs it with the lagged readback into
                # the step's trace slice (no tracer call — no lock, no
                # sync — ever runs inside _pipeline_dispatch itself)
                t_d = time.perf_counter()
                # spec drafts align only at ring lag <= 1 with no other
                # spec step in flight (the host's carry candidate is one
                # step behind — see _pipeline_dispatch)
                spec_ok = (
                    spec_chain
                    and engine.pipeline_inflight() <= 1
                    and not any(m[3] is not None for m in meta)
                )
                fused_info, spec_drafted = self._pipeline_dispatch(
                    live, admitting, feed if host_feed else None, spec_ok
                )
                host_feed = False
                dispatched_any = True
                meta.append(
                    (tuple(live.items()), fused_info, t_d, spec_drafted)
                )
                if fused_info is not None and fused_info[2]:
                    # final chunk dispatched: the lane joins the decode
                    # half from the NEXT dispatch — the device carry holds
                    # both its first token AND its position (set by the
                    # fused program), no host round-trip involved
                    i, lane, _, _ = fused_info
                    admitting.pop(i)
                    live[i] = lane
            if engine.pipeline_inflight() == 0:
                break
            self._pipeline_consume(live, meta.popleft())
        if (live or admitting) and dispatched_any:
            # cut short with lanes still generating or admitting: an actual
            # flush (the natural all-lanes-finished drain is not)
            self.telemetry.on_flush(len(live), len(admitting))
            with engine.stats.lock:
                engine.stats.pipeline_flushes += 1
        engine.pipeline_flush()  # ring already drained; drops the carry

    def _finish(self, lane_idx: int, req: Request, reason: str = "stop") -> None:
        req.state = RequestState.DONE
        req.finish_reason = reason
        # the migration ticket dies with the session: a finished request
        # has nothing left to move (routers fetch their ticket at stream
        # start, so a drain/stop force-cancel popping this is fine)
        self._mirror_finish(req)
        delta = self._lanes[lane_idx].eos.get_delta()
        if delta:
            req.generated_text += delta
            if req.on_delta:
                req.on_delta(delta)
        self._grammar_release(self._lanes[lane_idx])
        self._lanes[lane_idx] = _Lane()
        # paged: the finished session PARKS — its tree-registered blocks
        # stay resident (refcounted, LRU-bounded) so chat follow-ups and
        # same-prompt admissions share copy-free; its non-sharable tail
        # frees now. This is how resident sessions exceed lanes.
        self._paged_release(lane_idx, park=True)
        self.engine.reset_lane(lane_idx)
        # summary/spans/log line BEFORE the future resolves: the HTTP
        # thread reads req.summary the moment result() returns
        self.telemetry.on_finish(req, lane_idx, reason)
        if not req.future.done():
            req.future.set_result(req.generated_text)
        if self.journal is not None:
            # a deliberate ending (stop/length/cancel/timeout) is final:
            # the finish record keeps a later --recover-journal restart
            # from resurrecting this request. A CRASH writes no finish
            # records — that absence IS the journal's in-flight set.
            # Recorded LAST — after the held-back tail delta and the
            # future resolution — because the two crash windows are
            # asymmetric: a finish record that never lands just re-runs
            # the request on recovery (the client's Last-Event-ID filter
            # dedups), while a finish record durable BEFORE the tail
            # reached the transport would make the tail unrecoverable.
            # The phases dict produced by on_finish rides along: the
            # journal's finish record carries the same latency
            # attribution the completion response does.
            self.journal.record_finish(
                req.id, reason,
                phases=(req.summary or {}).get("phases"),
            )

    def _run(self) -> None:
        """Supervised outer loop (failure containment, the ISSUE 8
        tentpole — the analogue of the reference's supervised serve loop,
        src/app.cpp:455-463, on the ROOT side): the serving loop body
        runs inside a containment boundary, so an engine exception
        escaping a dispatch/consume/transfer can no longer kill the
        daemon batching thread and leave every future unresolved with
        /health still green. Engine-scoped failures are contained
        (`_contain_engine_failure`: abort the pipeline ring, fail the
        affected lanes with finish_reason="error", reset lane state, feed
        the circuit breaker) and the loop KEEPS SERVING — shedding at
        admission while the breaker is open, probing half-open, closing
        on recovery. Request-scoped failures never reach here (their
        sites fail the one request inline). The `finally` runs the
        stop()-style future cleanup even on a truly-fatal path (a raise
        out of containment itself), so no client ever hangs on a dead
        loop."""
        try:
            while True:
                try:
                    self._serve_loop()
                    break  # clean exit: stop() or drain complete
                except Exception as e:  # noqa: BLE001 — containment boundary
                    self._contain_engine_failure(e)
                    if self._stop.is_set():
                        break
        finally:
            self._resolve_exit()

    def _contain_engine_failure(self, e: BaseException) -> None:
        """Engine-scoped containment: log + count the failure, abort the
        pipeline ring WITHOUT consuming (each readback of a poisoned
        in-flight step would re-raise), fail every occupied lane with
        ``finish_reason="error"`` (their KV is garbage now — the
        resident-KV maps are discarded so prefix caching can never reuse
        it), and leave the lanes fresh for the next admission. Never
        raises: containment is the one layer that must not fail."""
        err = f"{type(e).__name__}: {e}"
        self.engine_failures += 1
        state = self.breaker.record_engine_failure(err)
        busy = [
            (i, l.request)
            for i, l in enumerate(self._lanes)
            if l.request is not None
        ]
        self.telemetry.on_engine_failure(
            err, lanes_failed=len(busy), breaker_state=state
        )
        try:
            abort = getattr(self.engine, "pipeline_abort", None)
            if abort is not None:
                abort()
            elif getattr(self.engine, "pipeline_active", False):
                # fallback for engines without the abort primitive; no
                # count= kwarg — proxies (RootControlEngine) don't take it,
                # and an aborted chain SHOULD count as a flush anyway
                self.engine.pipeline_flush()
        except Exception:  # noqa: BLE001 — containment must not throw
            pass
        for i, req in busy:
            try:
                self._fail_request(i, req, err)
            except Exception:  # noqa: BLE001 — containment must not throw
                pass
        # paged: after an engine-scoped failure the device pool contents
        # are not trusted — drop parked sessions and the whole prefix
        # tree too, not just the failed lanes' mappings
        try:
            reset = getattr(self.engine, "paged_reset", None)
            if reset is not None and getattr(self.engine, "kvpool", None) is not None:
                reset()
        except Exception:  # noqa: BLE001 — containment must not throw
            pass

    def _resolve_exit(self) -> None:
        """The stop()/drain() future cleanup, in a ``finally`` so it runs
        even when the supervised loop dies fatally: every in-flight lane
        resolves as cancelled and every queued future resolves (shed on a
        graceful drain, failed otherwise) — no client hangs on a dead
        loop thread."""
        for i, lane in enumerate(self._lanes):
            if lane.request is not None:
                self._finish(i, lane.request, reason="cancelled")
        # pending device ops resolve as failed, not as a timeout wait —
        # an admin thread must never hang on a dead loop
        with self._device_ops_lock:
            pending = list(self._device_ops)
            del self._device_ops[:]
        for _fn, box, done in pending:
            box["error"] = RuntimeError("scheduler stopped")
            done.set()
        draining = self._draining.is_set()
        for req in self.queue.drain():
            if draining:
                # graceful drain: a submit() that passed the pre-push shed
                # check can land its push after this loop's exit snapshot;
                # shed it like submit() would (503 + Retry-After) —
                # "scheduler stopped" would surface as a 500 in the middle
                # of a rolling restart
                self._shed_unadmitted(req)
            else:
                req.state = RequestState.FAILED
                self.telemetry.on_error(req, None, "scheduler stopped")
                if not req.future.done():
                    req.future.set_exception(RuntimeError("scheduler stopped"))

    def _serve_loop(self) -> None:
        n_lanes = self.engine.n_lanes
        cfg = self.engine.config
        while not self._stop.is_set():
            if self._wd_abort.is_set():
                # watchdog tripped but the step eventually returned (slow,
                # not dead): the chain already aborted; clear the flag so
                # serving resumes (the breaker stays open until a probe
                # succeeds)
                self._wd_abort.clear()
            # step boundary: engine.cache is the live chain output here,
            # so posted admin device ops (disagg export/import) run now
            self._drain_device_ops()
            idle = all(l.request is None for l in self._lanes)
            # when every lane is free, park on the queue's condition variable
            # instead of spinning pop(timeout=0)+sleep — an idle server burns
            # no core, and a push wakes the loop immediately
            self._admit(wait_s=0.25 if idle else 0.0)
            now = time.monotonic()
            self._sweep_queue(now)
            if (
                self._draining.is_set()
                and self.queue.empty()
                and all(l.request is None for l in self._lanes)
            ):
                break  # graceful drain: all work done, submit() is shedding
            occupied = [(i, l) for i, l in enumerate(self._lanes) if l.request is not None]
            if not occupied:
                continue  # _admit already waited on the queue

            # drop cancelled / budget-expired requests before spending a
            # step on them (expiry frees the lane for the next admission)
            for i, lane in occupied:
                if lane.request._cancelled.is_set():
                    self._finish(i, lane.request, reason="cancelled")
                elif budget_expired(lane.request, self.deadlines, now):
                    self.budget_timeouts += 1
                    self._finish(i, lane.request, reason="timeout")

            # stall-free admissions: with fused prefill, enter the
            # pipelined path BEFORE the synchronous prefill step — pending
            # prompt chunks and queued admissions ride the chain itself
            # (fused prefill+decode dispatches), so an admission no longer
            # exits steady state
            if self._fused_ok():
                active = [
                    (i, self._lanes[i])
                    for i in range(n_lanes)
                    if self._lanes[i].request is not None
                    and self._lanes[i].request.state
                    == RequestState.GENERATING
                ]
                if (
                    active
                    and self._pipeline_ok(active)
                    and (
                        # drafts ride the chain when the engine verifies
                        # in-chain; only legacy engines flush for them
                        self._spec_pl_ok()
                        or not self._drafts_pending(dict(active))
                    )
                ):
                    self._run_pipelined(active)
                    continue

            # at most ONE prompt bucket per iteration: decoding lanes below
            # stall no longer than one bucket while admissions stream in.
            # Any generating lane held up by this chunk is a real admission
            # stall (with fused prefill this path only runs when the chain
            # declined: drafts pending, a host-exact lane, or pipelining
            # off — the fused chain otherwise hides admission work behind
            # device execution)
            had_generating = any(
                l.request is not None
                and l.request.state == RequestState.GENERATING
                for l in self._lanes
            )
            t_pf = time.perf_counter()
            prefilled = self._prefill_step()
            if prefilled and had_generating:
                with self.engine.stats.lock:
                    self.engine.stats.admission_stall_s += (
                        time.perf_counter() - t_pf
                    )

            active = [
                (i, self._lanes[i])
                for i in range(n_lanes)
                if self._lanes[i].request is not None
                and self._lanes[i].request.state == RequestState.GENERATING
            ]
            if not active:
                # Nothing decodable and no prompt chunk processed. This is
                # only reachable when the cancel/expiry pass above freed
                # every lane after the `occupied` snapshot was taken (an
                # admitting lane implies prefilled; a generating lane
                # implies active) — so loop straight back to the idle
                # check, which parks on the queue's condition variable
                # (QosQueue.pop wait / Queue.get) until the next push or
                # the 0.25s stop-flag recheck, instead of busy-polling
                # `self._stop` at 1ms as earlier revisions did.
                continue

            host_exact_active = any(
                l.host_exact and l.request.temperature > 0 for _, l in active
            )
            tokens = np.zeros(n_lanes, np.int32)
            # EVERY lane gets a KV write from this decode step (one compiled
            # program, all lanes scatter). Idle/finished lanes point at
            # seq_len so the mode="drop" scatter discards the junk write
            # outright — position 0 would clobber slot 0 of a finished
            # lane's cache, which prefix caching may still reuse
            # (round-5 code-review finding). Lanes mid-prefill point at
            # their next unwritten slot, which the next prefill chunk
            # rewrites before any query can read it.
            positions = np.full(n_lanes, cfg.seq_len, np.int32)
            temps = np.zeros(n_lanes, np.float32)
            topps = np.full(n_lanes, DEFAULT_TOPP, np.float32)
            seeds = np.zeros(n_lanes, np.uint32)
            for i, lane in enumerate(self._lanes):
                if lane.request is not None and lane.pending:
                    positions[i] = lane.pos
            for i, lane in active:
                tokens[i] = lane.next_token
                positions[i] = lane.pos
                if not lane.host_exact:
                    temps[i] = lane.request.temperature
                    topps[i] = lane.request.topp
                    seeds[i] = lane.seed

            # speculative step (prompt-lookup drafts, greedy lanes), gated
            # PER LANE: each lane drafts at most the uncommitted cache slots
            # it has left before seq_len (emitting m tokens reads logits at
            # pos..pos+m-1, which need in-bounds KV writes; writes at
            # >= seq_len are dropped by the cache scatter, so a lane at the
            # end of its sequence cannot clobber state or disable drafting
            # on other lanes)
            spec_k = getattr(self.engine, "SPEC_DRAFT", 0)
            draft_len = None
            if self._spec_pl_ok() and self._pipeline_ok(active, prefilled):
                # drafts ride the chain: don't build the sync-path draft
                # arrays just to discard them — the chain's dispatch half
                # probes the SAME indices itself, with the carry-candidate
                # layout the in-chain verify needs
                self._run_pipelined(active)
                continue
            if (
                self.speculative
                and spec_k > 0
                and getattr(self.engine, "supports_speculative", False)
            ):
                drafts = np.zeros((n_lanes, spec_k), np.int32)
                draft_len = np.zeros(n_lanes, np.int32)
                for i, lane in active:
                    d_max = min(spec_k, cfg.seq_len - lane.pos - 1)
                    if lane.request.temperature == 0.0 and d_max > 0:
                        d = lane.drafter.draft(lane.next_token, spec_k)[:d_max]
                        if lane.grammar is not None and d:
                            # host pre-filter: a draft the mask would
                            # reject is simply not proposed (the sync
                            # mirror is exact here), so verification
                            # stays the model's own masked-greedy path
                            d = d[: lane.grammar.filter_prefix(
                                lane.g_state, d
                            )]
                        drafts[i, : len(d)] = d
                        draft_len[i] = len(d)
                if not draft_len.any():
                    draft_len = None  # nothing to verify: plain step

            if draft_len is None and self._pipeline_ok(active, prefilled):
                # steady state with no drafts to verify on a LEGACY engine
                # (the in-chain-verify entry above handles the default):
                # the pipelined path overlaps step k's host consume with
                # step k+1's device execution (device-fed token carry,
                # lagged readback)
                self._run_pipelined(active)
                continue

            chosen = None
            h = 0 if draft_len is not None else self._multi_horizon(
                active, prefilled
            )
            # grammar states for this dispatch (exact host mirror on the
            # sync paths); None -> the engine's all-FREE default
            g_states, g_any = self._g_states_sync(active)
            if g_any:
                self._count_masked_step()
            wd = self.watchdog
            if wd is not None:
                wd.begin_step()
            t_step = time.perf_counter()
            try:
                if draft_len is not None:
                    logits, emitted, n_emit = self.engine.decode_spec(
                        tokens, drafts, draft_len, positions, temps, topps,
                        seeds, g_states=g_states,
                    )
                elif h > 1:
                    logits = None  # host-exact lanes are excluded by the gate
                    chosen = self.engine.decode_multi(
                        tokens, positions, temps, topps, seeds, h,
                        g_states=g_states,
                    )
                else:
                    # logits materialize only when a host-exact lane will
                    # read them: the common all-device-sampling step keeps
                    # no [n_lanes, vocab] buffer alive
                    logits, greedy, sampled = self.engine.decode(
                        tokens, positions, temps, topps, seeds,
                        want_logits=host_exact_active,
                        g_states=g_states,
                    )
                self.telemetry.on_step(
                    "spec" if draft_len is not None
                    else ("multi" if h > 1 else "sync"),
                    t_step, args={"h": h} if h > 1 else None,
                )
                # host-exact lanes (host_sampling=True only — the
                # bit-exact reference-xorshift escape hatch; the device
                # sampler is full-vocab exact, so no request routes here
                # on numerics grounds): one batched [n_lanes, vocab]
                # transfer; pure on-device batches: tokens only
                logits_np = None
                if host_exact_active:
                    # dlint: ok[host-sync] host-exact lanes only: ONE batched [n,vocab] f32 transfer, counted by all_logits
                    logits_np = self.engine.all_logits(logits)
            finally:
                # disarm on success AND on a raise (a raised step is the
                # containment layer's business, not a stall)
                if wd is not None:
                    wd.step_done()
            self.breaker.record_success()

            for i, lane in active:
                req = lane.request
                if draft_len is not None:
                    # feed sequence: next_token + the accepted drafts (they
                    # equal the greedy continuations, so this is exactly the
                    # plain-decode token stream); the model's token after
                    # the accepted prefix becomes the new pending token.
                    # Acceptance counters cover DRAFTED lanes only — sampled
                    # and draft-less lanes ride the same batched verify call
                    # but always emit 1, which would dilute the metric
                    drafted = int(draft_len[i]) > 0
                    cnt = int(n_emit[i])
                    if lane.grammar is not None:
                        # every emitted token is a NEW emission: the last
                        # becomes next_token, the rest are consumed below
                        for t in emitted[i, :cnt]:
                            self._g_adv(lane, int(t))
                    seq = [lane.next_token] + [
                        int(t) for t in emitted[i, : cnt - 1]
                    ]
                    alive = True
                    n_fed = 0
                    for t in seq:
                        n_fed += 1  # consumed (finishing token included)
                        if not self._consume(i, lane, t):
                            alive = False
                            break
                    if drafted:
                        with self.engine.stats.lock:
                            self.engine.stats.spec_lane_steps += 1
                            self.engine.stats.spec_emitted += n_fed
                    if not alive:
                        continue
                    nxt_greedy = int(emitted[i, cnt - 1])
                    nxt_sampled = int(emitted[i, 0])  # n_emit==1 for temp>0
                elif chosen is not None:
                    # multi-step horizon: consume next_token + the first
                    # h-1 chained choices; the last choice becomes the new
                    # pending token. Tokens past a stop are discarded (their
                    # junk KV is rewritten before any query reads it).
                    if lane.grammar is not None:
                        for j in range(h):  # h new emissions this horizon
                            self._g_adv(lane, int(chosen[j, i]))
                    seq = [lane.next_token] + [
                        int(chosen[j, i]) for j in range(h - 1)
                    ]
                    alive = True
                    for t in seq:
                        if not self._consume(i, lane, t):
                            alive = False
                            break
                    if not alive:
                        continue
                    lane.next_token = int(chosen[h - 1, i])
                    continue  # greedy/sampled feed already encoded in chosen
                else:
                    if not self._consume(i, lane, lane.next_token):
                        continue
                    nxt_greedy = int(greedy[i])
                    nxt_sampled = int(sampled[i])
                if req.temperature == 0.0:
                    lane.next_token = nxt_greedy
                elif lane.host_exact:
                    lane.next_token = lane.sampler.sample(logits_np[i])
                else:
                    lane.next_token = nxt_sampled
                if draft_len is None:
                    # plain step: ONE new emission (the spec branch
                    # advanced its whole window above; multi continues
                    # before reaching here)
                    self._g_adv(lane, lane.next_token)
