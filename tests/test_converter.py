"""Converter tests: synthetic HF checkpoint -> .m/.t -> framework loaders."""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

CONVERTER_DIR = os.path.join(os.path.dirname(__file__), "..", "converter")


def _load(name, filename):
    path = os.path.join(CONVERTER_DIR, filename)
    sys.path.insert(0, CONVERTER_DIR)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    """A tiny fake HF Llama checkpoint: config.json + model.safetensors."""
    torch = pytest.importorskip("torch")
    from safetensors.torch import save_file

    d = tmp_path_factory.mktemp("hf")
    dim, hidden, layers, heads, kv = 64, 128, 2, 4, 2
    vocab = 96
    cfg = {
        "model_type": "llama",
        "hidden_act": "silu",
        "hidden_size": dim,
        "intermediate_size": hidden,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv,
        "max_position_embeddings": 64,
        "vocab_size": vocab,
        "rope_theta": 500000.0,
        "rope_scaling": {
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 64,
        },
    }
    (d / "config.json").write_text(json.dumps(cfg))
    g = torch.Generator().manual_seed(0)
    tensors = {"model.embed_tokens.weight": torch.randn(vocab, dim, generator=g) * 0.02}
    kv_dim = dim * kv // heads
    for l in range(layers):
        p = f"model.layers.{l}"
        tensors[f"{p}.self_attn.q_proj.weight"] = torch.randn(dim, dim, generator=g) * 0.02
        tensors[f"{p}.self_attn.k_proj.weight"] = torch.randn(kv_dim, dim, generator=g) * 0.02
        tensors[f"{p}.self_attn.v_proj.weight"] = torch.randn(kv_dim, dim, generator=g) * 0.02
        tensors[f"{p}.self_attn.o_proj.weight"] = torch.randn(dim, dim, generator=g) * 0.02
        tensors[f"{p}.mlp.gate_proj.weight"] = torch.randn(hidden, dim, generator=g) * 0.02
        tensors[f"{p}.mlp.down_proj.weight"] = torch.randn(dim, hidden, generator=g) * 0.02
        tensors[f"{p}.mlp.up_proj.weight"] = torch.randn(hidden, dim, generator=g) * 0.02
        tensors[f"{p}.input_layernorm.weight"] = torch.ones(dim)
        tensors[f"{p}.post_attention_layernorm.weight"] = torch.ones(dim)
    tensors["model.norm.weight"] = torch.ones(dim)
    # no lm_head -> tied-embedding fallback path
    save_file(tensors, str(d / "model.safetensors"))
    return d, cfg, tensors


def test_convert_hf_roundtrip(hf_checkpoint, tmp_path):
    d, cfg, tensors = hf_checkpoint
    mod = _load("convert_hf", "convert-hf.py")
    out = str(tmp_path / "model.m")
    mod.convert(str(d), 2, out)  # q40

    from distributed_llama_multiusers_tpu.formats import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import read_m_tensors
    from distributed_llama_multiusers_tpu.quants.codec import quantize_q40, dequantize_q40

    h = load_model_header(out)
    assert h.dim == cfg["hidden_size"]
    assert h.rope_type == 2  # LLAMA3_1
    assert h.rope_scaling_factor == 8.0
    w = read_m_tensors(out, h)
    # v (unpermuted): matches Q40 QDQ of the HF tensor
    v_hf = tensors["model.layers.0.self_attn.v_proj.weight"].numpy()
    expect = dequantize_q40(quantize_q40(v_hf.reshape(-1))).reshape(v_hf.shape)
    np.testing.assert_allclose(w["wv"][0], expect, rtol=0, atol=0)
    # q is permuted: same values as permuting THEN quantizing
    q_hf = tensors["model.layers.0.self_attn.q_proj.weight"].numpy()
    perm = mod.permute_rotary(q_hf, cfg["num_attention_heads"])
    expect_q = dequantize_q40(quantize_q40(perm.reshape(-1))).reshape(perm.shape)
    np.testing.assert_allclose(w["wq"][0], expect_q, rtol=0, atol=0)
    assert not np.allclose(w["wq"][0], dequantize_q40(quantize_q40(q_hf.reshape(-1))).reshape(q_hf.shape))
    # tied lm_head == embedding (quantized)
    emb = tensors["model.embed_tokens.weight"].numpy()
    np.testing.assert_allclose(
        w["wcls"], dequantize_q40(quantize_q40(emb.reshape(-1))).reshape(emb.shape)
    )
    # and the converted model actually runs
    import jax.numpy as jnp
    from distributed_llama_multiusers_tpu.models import init_kv_cache, llama_forward, load_params_from_m

    config, params = load_params_from_m(out, h, dtype=jnp.float32)
    logits, _ = llama_forward(
        config, params, jnp.array([[1]], jnp.int32), jnp.array([[0]], jnp.int32),
        init_kv_cache(config, 1),
    )
    assert bool(jnp.isfinite(logits).all())


def test_convert_tokenizer_hf(tmp_path):
    """A minimal byte-level-BPE tokenizer.json converts and encodes."""
    mod = _load("convert_tok_hf", "convert-tokenizer-hf.py")
    bd = mod.gpt2_byte_decoder()
    enc = {v: k for k, v in bd.items()}  # byte -> unicode char

    def u(s: bytes) -> str:
        return "".join(enc[b] for b in s)

    vocab = {}
    for i, b in enumerate(range(256)):
        vocab[u(bytes([b]))] = i
    vocab[u(b"he")] = 256
    vocab[u(b"ll")] = 257
    vocab[u(b"hell")] = 258
    vocab[u(b"hello")] = 259
    tok_json = {
        "model": {
            "type": "BPE",
            "vocab": vocab,
            "merges": ["h e", "l l", "he ll", "hell o"],
        },
        "added_tokens": [
            {"id": 260, "content": "<|begin_of_text|>"},
            {"id": 261, "content": "<|eot_id|>"},
        ],
    }
    cfg = {
        "bos_token": "<|begin_of_text|>",
        "eos_token": "<|eot_id|>",
        "chat_template": "x<|start_header_id|>y",
    }
    d = tmp_path / "tok"
    d.mkdir()
    (d / "tokenizer.json").write_text(json.dumps(tok_json))
    (d / "tokenizer_config.json").write_text(json.dumps(cfg))
    out = str(tmp_path / "tok.t")
    mod.convert(str(d), out)

    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

    t = Tokenizer(out)
    assert t.bos_id == 260
    assert t.eos_token_ids == [261]
    ids = t.encode("hello", add_bos=False)
    assert ids == [259]
    assert t.decode_full(t.encode("hello world")) == "hello world"


def test_convert_tokenizer_llama3(tmp_path):
    import base64

    mod = _load("convert_tok_l3", "convert-tokenizer-llama3.py")
    model = tmp_path / "tokenizer.model"
    pieces = [b"a", b"b", b"ab", b"hello"]
    model.write_bytes(b"\n".join(base64.b64encode(p) + b" %d" % i for i, p in enumerate(pieces)))
    out = str(tmp_path / "l3.t")
    mod.convert(str(model), out)

    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

    t = Tokenizer(out)
    assert t.bos_id == len(pieces)
    assert t.vocab[t.bos_id] == b"<|begin_of_text|>"
    assert len(t.eos_token_ids) == 2
    ids = t.encode("ab", add_bos=False)
    assert ids == [2]  # merged via rank-descending scores
