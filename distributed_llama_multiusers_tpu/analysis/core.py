"""dlint core: checker framework, waivers, baseline.

The analyzer is a plain-AST pass over the package (stdlib only — no jax,
no numpy: `make lint` must run anywhere CPython >= 3.10 runs, before any
heavyweight import). Each checker gets two phases:

- ``collect(sf, project)`` — gather cross-file facts (guarded-by
  declarations, declared mesh axes, the lock model) into the shared
  :class:`Project`;
- ``check(sf, project)`` — yield :class:`Finding`s for one file;
- ``finalize(project)`` — yield findings that only exist once every file
  has been seen (lock-order cycles span files, so no single ``check``
  call can report them).

Findings are suppressed by

- **inline waivers** — ``# dlint: ok[check-name] reason`` on the finding's
  line (or on a standalone comment line directly above it). The reason is
  mandatory: a bare waiver is itself a finding (check ``waiver``), so every
  silenced invariant carries its justification in the tree. ``ok[*]``
  waives all checks on that line.
- the **baseline file** — one ``check<TAB>path<TAB>message`` key per line
  for pre-existing findings accepted at adoption time. New findings never
  match old keys, so regressions stay loud while the backlog burns down.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

WAIVER_RE = re.compile(r"#\s*dlint:\s*ok\[([^\]]*)\]\s*(.*?)\s*$")
GUARD_DECL_NAME = "_dlint_guarded_by"


@dataclass(frozen=True)
class Finding:
    """One analyzer hit, anchored to file:line with a line-free message
    (messages are the stable part of the baseline key; line numbers churn
    with every edit, so they are display-only)."""

    check: str
    path: str
    line: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.check}\t{self.path}\t{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


@dataclass(frozen=True)
class Waiver:
    line: int
    checks: tuple[str, ...]  # check names, or ("*",)
    reason: str
    standalone: bool  # comment-only line: also covers the next line

    def covers(self, check: str) -> bool:
        return "*" in self.checks or check in self.checks


@dataclass
class SourceFile:
    path: Path  # absolute
    display: str  # stable-ish path used in findings/baseline keys
    text: str
    tree: ast.Module
    waivers: dict[int, Waiver] = field(default_factory=dict)

    def endswith(self, *suffixes: str) -> bool:
        """Posix-path suffix test, independent of cwd (fixtures live in
        tmp dirs; the real tree under the repo root)."""
        p = self.path.as_posix()
        return any(p.endswith(s) for s in suffixes)


class Project:
    """Cross-file facts collected before checking starts."""

    def __init__(self):
        # attr name -> (frozenset of acceptable lock attr names, decl site)
        self.guarded: dict[str, tuple[frozenset[str], str]] = {}
        # declared mesh axis names (from `AXES = (...)` in parallel/mesh.py)
        self.axes: set[str] = set()
        self.axes_src: str | None = None
        # cross-file lock model (analysis/lockgraph.py), built by the
        # lock-order checker's collect pass and shared by every
        # concurrency check; None until that collect has run
        self.lock_model = None
        # cross-file resource-lifecycle model (analysis/resourcemodel.py),
        # built by the v5 checkers' collect passes; None until one has run
        self.resource_model = None
        # findings raised during collect (malformed declarations)
        self.collect_findings: list[Finding] = []


class Checker:
    """Base class; subclasses set ``name``/``description`` and override
    ``check`` (and ``collect`` when they need cross-file state)."""

    name = "base"
    description = ""

    def collect(self, sf: SourceFile, project: Project) -> None:
        return None

    def check(self, sf: SourceFile, project: Project) -> Iterator[Finding]:
        return iter(())

    def finalize(self, project: Project) -> Iterator[Finding]:
        return iter(())


def walk_with_ancestors(tree: ast.AST) -> Iterator[tuple[ast.AST, tuple[ast.AST, ...]]]:
    """Yield (node, ancestors) over the whole tree, outermost ancestor
    first — checkers need lexical context (enclosing with/while/function)
    that ast.walk throws away."""
    stack: list[ast.AST] = []

    def rec(node: ast.AST) -> Iterator[tuple[ast.AST, tuple[ast.AST, ...]]]:
        yield node, tuple(stack)
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield from rec(child)
        stack.pop()

    yield from rec(tree)


def nearest(ancestors: Iterable[ast.AST], *types) -> ast.AST | None:
    """Innermost ancestor of one of ``types`` (ancestors are outermost
    first, so scan from the end)."""
    for node in reversed(list(ancestors)):
        if isinstance(node, types):
            return node
    return None


def root_name(node: ast.AST) -> str | None:
    """Leftmost Name of an attribute/subscript chain (`a.b[0].c` -> `a`)."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def last_component(func: ast.AST) -> str | None:
    """Final name of a callee (`self.engine.decode` -> `decode`)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def parse_waivers(
    text: str, valid_checks: set[str], display: str
) -> tuple[dict[int, Waiver], list[Finding]]:
    """Extract ``# dlint: ok[...]`` comments with tokenize (comments only —
    a waiver-shaped string literal must not silence anything). Returns the
    per-line waiver map plus syntax findings: empty check list, unknown
    check name, or a missing reason. Waiver-syntax findings are not
    themselves waivable."""
    waivers: dict[int, Waiver] = {}
    findings: list[Finding] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return waivers, findings  # the ast parse reports the real error
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = WAIVER_RE.search(tok.string)
        if m is None:
            if re.search(r"#\s*dlint\s*:", tok.string) and "ok[" not in tok.string:
                findings.append(Finding(
                    "waiver", display, tok.start[0],
                    f"unrecognized dlint comment {tok.string.strip()!r} "
                    "(expected '# dlint: ok[check-name] reason')",
                ))
            continue
        line = tok.start[0]
        checks = tuple(c.strip() for c in m.group(1).split(",") if c.strip())
        reason = m.group(2).strip()
        if not checks:
            findings.append(Finding(
                "waiver", display, line,
                "waiver with an empty check list (use ok[check-name] or ok[*])",
            ))
            continue
        unknown = [c for c in checks if c != "*" and c not in valid_checks]
        if unknown:
            findings.append(Finding(
                "waiver", display, line,
                f"waiver names unknown check(s) {unknown} "
                f"(known: {sorted(valid_checks)})",
            ))
            continue
        if not reason:
            findings.append(Finding(
                "waiver", display, line,
                f"bare waiver ok[{','.join(checks)}] without a reason — every "
                "waiver must say WHY the invariant is intentionally broken",
            ))
            continue
        standalone = text.splitlines()[line - 1][: tok.start[1]].strip() == ""
        waivers[line] = Waiver(line, checks, reason, standalone)
    return waivers, findings


def waived(sf: SourceFile, finding: Finding) -> bool:
    w = sf.waivers.get(finding.line)
    if w is not None and w.covers(finding.check):
        return True
    prev = sf.waivers.get(finding.line - 1)
    return prev is not None and prev.standalone and prev.covers(finding.check)


# -- baseline ----------------------------------------------------------------


def load_baseline(path: Path | str | None) -> set[str]:
    if path is None:
        return set()
    p = Path(path)
    if not p.exists():
        return set()
    out = set()
    for line in p.read_text(encoding="utf-8").splitlines():
        line = line.rstrip("\n")
        if not line.strip() or line.lstrip().startswith("#"):
            continue
        out.add(line)
    return out


def write_baseline(path: Path | str, findings: Iterable[Finding]) -> None:
    keys = sorted({f.key for f in findings})
    header = (
        "# dlint baseline: pre-existing findings accepted at adoption time.\n"
        "# One 'check<TAB>path<TAB>message' key per line; regenerate with\n"
        "# `python -m distributed_llama_multiusers_tpu.analysis --write-baseline`.\n"
        "# Prefer FIXING or waiving (with a reason) over baselining — see\n"
        "# docs/LINT.md for the policy.\n"
    )
    Path(path).write_text(header + "".join(k + "\n" for k in keys), encoding="utf-8")


# -- analyzer ----------------------------------------------------------------


def iter_py_files(paths: Iterable[Path | str]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(q for q in p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    seen: set[Path] = set()
    uniq = []
    for p in out:
        r = p.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(p)
    return uniq


def _display_path(p: Path, root: Path | None) -> str:
    try:
        base = root if root is not None else Path.cwd()
        return p.resolve().relative_to(base.resolve()).as_posix()
    except ValueError:
        return p.as_posix()


class Analyzer:
    def __init__(self, checkers: list[Checker]):
        self.checkers = checkers
        self.valid_checks = {c.name for c in checkers} | {"waiver", "parse"}

    def run(
        self,
        paths: Iterable[Path | str],
        baseline: set[str] | None = None,
        root: Path | None = None,
        check_only: set[Path] | None = None,
    ) -> list[Finding]:
        """``check_only`` (resolved absolute paths — the ``--changed``
        mode): EVERY file still feeds the collect phase, so cross-file
        models (locks, guarded-by decls, the protocol surface) stay
        complete, but per-file ``check`` findings (and waiver hygiene)
        are reported only for the listed files. ``finalize`` findings
        are cross-file by definition and always reported — as are
        ``parse`` findings from ANY file, since an unparseable file is
        a hole in the cross-file model no matter what changed."""
        baseline = baseline or set()
        files: list[SourceFile] = []
        findings: list[Finding] = []

        def _checked(p: Path) -> bool:
            return check_only is None or p.resolve() in check_only

        for p in iter_py_files(paths):
            display = _display_path(p, root)
            try:
                text = p.read_text(encoding="utf-8")
                tree = ast.parse(text, filename=str(p))
            except (OSError, SyntaxError, ValueError) as e:
                # unconditionally, check_only included: an unparseable
                # file is MISSING from the cross-file model (locks,
                # guarded-by decls, the protocol surface), so a
                # --changed run reporting clean against the incomplete
                # model would be a lie — parse findings are unwaivable
                # hygiene and stay loud
                findings.append(Finding(
                    "parse", display, getattr(e, "lineno", 0) or 0,
                    f"cannot analyze: {type(e).__name__}: {e}",
                ))
                continue
            sf = SourceFile(path=p, display=display, text=text, tree=tree)
            sf.waivers, wf = parse_waivers(text, self.valid_checks, display)
            if _checked(p):
                findings.extend(wf)  # waiver-syntax findings: never waivable
            files.append(sf)

        project = Project()
        for checker in self.checkers:
            for sf in files:
                checker.collect(sf, project)
        checked_displays = {sf.display for sf in files if _checked(sf.path)}
        findings.extend(
            f for f in project.collect_findings if f.path in checked_displays
        )

        sf_by_display = {sf.display: sf for sf in files}
        for checker in self.checkers:
            for sf in files:
                if not _checked(sf.path):
                    continue
                for f in checker.check(sf, project):
                    findings.append(f)
        for checker in self.checkers:
            findings.extend(checker.finalize(project))

        out = []
        seen: set[tuple] = set()  # dedup (nested defs are walked twice)
        for f in findings:
            k = (f.check, f.path, f.line, f.message)
            if k in seen:
                continue
            seen.add(k)
            if f.check in ("waiver", "parse"):
                out.append(f)  # hygiene findings are not waivable/baselinable
                continue
            sf = sf_by_display.get(f.path)
            if sf is not None and waived(sf, f):
                continue
            if f.key in baseline:
                continue
            out.append(f)
        out.sort(key=lambda f: (f.path, f.line, f.check, f.message))
        return out
