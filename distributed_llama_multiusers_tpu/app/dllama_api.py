"""`dllama-api` entry point: the multi-user HTTP server
(reference: src/dllama-api.cpp:388-411), backed by the continuous-batching
scheduler instead of the fork's serialized accept loop."""

from __future__ import annotations

import os
import signal
import threading

from ..server import ApiServer
from ..tokenizer import template_type_from_name
from .args import build_parser
from .runtime_setup import honor_cpu_platform_env, load_stack, log, make_scheduler


def main(argv=None) -> None:
    honor_cpu_platform_env()
    args = build_parser("dllama-api", api=True).parse_args(argv)
    config, params, tokenizer, engine = load_stack(args)
    scheduler = make_scheduler(engine, tokenizer, args)
    template_type = template_type_from_name(args.chat_template)
    model_name = os.path.basename(args.model or "dllama")
    server = ApiServer(scheduler, tokenizer, model_name=model_name, template_type=template_type)
    httpd = server.serve(host=args.host, port=args.port)
    log("⭐", f"Server listening on {args.host}:{args.port} ({engine.n_lanes} lanes)")

    def _shutdown(*_):
        log("⭐", "Shutting down")
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _shutdown)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # drain WHILE the server still answers — the accept loop restarts in
        # a helper thread so /health serves 503 and new submissions shed with
        # 503 + Retry-After (load balancers route away) instead of new
        # connections hanging in the accept backlog for the whole window.
        # drain() owns the whole shutdown protocol, including force-stop on
        # timeout — a second stop() here would only re-join a thread drain
        # already dealt with (and re-raise over drain's own failure report
        # when that thread is wedged in a hung device dispatch).
        # dlint: ok[condvar] httpd.shutdown() in the finally ends serve_forever; the helper only spans the drain window
        accept_loop = threading.Thread(target=httpd.serve_forever, daemon=True)
        accept_loop.start()
        try:
            log("⭐", "Draining in-flight requests (30s window)")
            scheduler.drain(timeout=30.0)
        finally:
            httpd.shutdown()
            if args.trace_path:
                # the drained server's span ring as a Perfetto-loadable
                # artifact (same document GET /trace served live)
                try:
                    scheduler.telemetry.dump_trace(args.trace_path)
                    log("⭐", f"Trace written to {args.trace_path}")
                except OSError as e:
                    log("⚠️", f"trace dump failed: {e}")


if __name__ == "__main__":
    main()
