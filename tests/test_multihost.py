"""Multi-host SPMD serving: two real jax.distributed processes on CPU.

The reference's cluster story is a worker binary that receives its program
over TCP (src/app.cpp:405-464); here it is multi-controller SPMD — every
process runs the same engine, the root broadcasts a control packet per call
(parallel/multihost.ControlPlane, the LlmControlPacket analogue), workers
replay it. This test launches an actual 2-process pod (coordinator on
localhost, one virtual CPU device per process, global mesh tp=2), generates
greedily through the RootControlEngine, and asserts the tokens match a
single-process run of the same model.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

POD_PREAMBLE = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    mode, tmp, port = sys.argv[1], sys.argv[2], sys.argv[3]

    from distributed_llama_multiusers_tpu.utils.testing import force_cpu_mesh
    force_cpu_mesh(n_devices=1)  # one local device; the pod supplies 2 globally

    from distributed_llama_multiusers_tpu.parallel.multihost import (
        ControlPlane, RootControlEngine, maybe_initialize_distributed,
        worker_loop, worker_serve,
    )
    os.environ["DLLAMA_COORDINATOR"] = f"127.0.0.1:{{port}}"
    os.environ["DLLAMA_NUM_PROCESSES"] = "2"
    os.environ["DLLAMA_PROCESS_ID"] = "0" if mode == "root" else "1"
    assert maybe_initialize_distributed() == 2

    import jax
    import numpy as np
    import jax.numpy as jnp
    assert jax.process_count() == 2
    assert len(jax.devices()) == 2, jax.devices()

    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

    h = load_model_header(os.path.join(tmp, "m.m"))
    config, params = load_params_from_m(os.path.join(tmp, "m.m"), h, dtype=jnp.float32)
    mesh = make_mesh(MeshPlan(tp=2))
    params = shard_params(params, mesh)
    engine = InferenceEngine(
        config, params, n_lanes=2, mesh=mesh, replicate_outputs=True
    )
    plane = ControlPlane(2, chunk=64)
    """
)

DRIVER = POD_PREAMBLE + textwrap.dedent(
    """
    if mode == "root":
        eng = RootControlEngine(engine, plane)
        t = Tokenizer(os.path.join(tmp, "t.t"))
        ids = t.encode("hello world")
        _, greedy, pos = eng.prefill(0, ids)
        out = [greedy]
        cur = greedy
        toks = np.zeros(2, np.int32); poss = np.zeros(2, np.int32)
        for _ in range(5):
            toks[0] = cur; poss[0] = pos
            _, g, _ = eng.decode(toks, poss)
            pos += 1
            cur = int(g[0])
            out.append(cur)
        eng.stop_workers()
        with open(os.path.join(tmp, "root_tokens.json"), "w") as f:
            json.dump(out, f)
    else:
        worker_loop(engine, plane)
    print(f"{{mode}} done", flush=True)
    """
)

SCHED_DRIVER = POD_PREAMBLE + textwrap.dedent(
    """
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler, Request,
    )

    if mode == "root":
        eng = RootControlEngine(engine, plane)
        t = Tokenizer(os.path.join(tmp, "t.t"))
        out = {{}}
        try:
            # stop_workers must run even when an assert below fails, or the
            # worker blocks in plane.recv() until the harness timeout
            sched = ContinuousBatchingScheduler(eng, t)
            sched.start()
            try:
                req = Request(
                    prompt="hello world", max_tokens=6, temperature=0.7,
                    seed=1234,
                )
                sched.submit(req)
                req.future.result(timeout=300)
                assert req.error is None, req.error
                # sequential greedy requests sharing a long prefix: the
                # second admission prefix-hits (same lane -> the copy
                # no-ops; the accounting still must fire on a pod root)
                shared = "hello world hello world hello world hello wor "
                outs = []
                for tail in ("one", "two"):
                    r = Request(
                        prompt=shared + tail, max_tokens=4, temperature=0.0
                    )
                    sched.submit(r)
                    r.future.result(timeout=300)
                    assert r.error is None, r.error
                    outs.append(r.generated_tokens)
                assert eng.stats.prefix_hits >= 1, "pod prefix cache never hit"
            finally:
                sched.stop()
            # CROSS-LANE prefix copy on the pod: lane 0 -> lane 1 rides an
            # OP_COPY_LANE broadcast (src != dst), workers replay the same
            # cache-copy program, and greedy decode continues on lane 1
            # over the COPIED cache — parity asserted vs the one-process
            # oracle below
            ids = t.encode("hello world hello world")
            _, g, pos = eng.prefill(0, ids)
            eng.copy_lane(0, 1)
            cur = int(g)
            copied = [cur]
            tvec = np.zeros(2, np.int32)
            pvec = np.zeros(2, np.int32)
            for _ in range(4):
                tvec[1] = cur
                pvec[1] = pos
                _, gg, _ = eng.decode(tvec, pvec)
                pos += 1
                cur = int(gg[1])
                copied.append(cur)
            out = {{
                "sampled": req.generated_tokens,
                "prefix": outs,
                "copy": copied,
            }}
        finally:
            eng.stop_workers()
        with open(os.path.join(tmp, "root_sched_tokens.json"), "w") as f:
            json.dump(out, f)
    else:
        worker_serve(engine, plane, max_restarts=0)
    print(f"{{mode}} done", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pod(tmp: str, driver_src: str, timeout: float = 420) -> None:
    """Write the driver, launch root+worker subprocesses against a free
    coordinator port, and assert both exit 0."""
    driver = os.path.join(tmp, "driver.py")
    with open(driver, "w") as f:
        f.write(driver_src.format(repo=REPO))
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # the pod must manage its own platform/devices (the suite's conftest
        # exports an 8-device CPU config)
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, driver, mode, tmp, str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for mode in ("root", "worker")
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"pod process failed:\n{out[-2000:]}"


def test_two_process_pod_matches_single_process(tmp_path):
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        tiny_header,
        write_synthetic_model,
        write_synthetic_tokenizer,
    )

    tmp = str(tmp_path)
    header = tiny_header()
    write_synthetic_model(os.path.join(tmp, "m.m"), header, seed=7)
    write_synthetic_tokenizer(os.path.join(tmp, "t.t"), vocab_size=header.vocab_size)
    _run_pod(tmp, DRIVER)

    with open(os.path.join(tmp, "root_tokens.json")) as f:
        pod_tokens = json.load(f)
    assert len(pod_tokens) == 6

    # single-process reference on the same files (this process, no mesh)
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer
    import numpy as np

    h = load_model_header(os.path.join(tmp, "m.m"))
    config, params = load_params_from_m(os.path.join(tmp, "m.m"), h, dtype=jnp.float32)
    engine = InferenceEngine(config, params, n_lanes=2)
    t = Tokenizer(os.path.join(tmp, "t.t"))
    ids = t.encode("hello world")
    _, greedy, pos = engine.prefill(0, ids)
    want = [greedy]
    cur = greedy
    toks = np.zeros(2, np.int32)
    poss = np.zeros(2, np.int32)
    for _ in range(5):
        toks[0] = cur
        poss[0] = pos
        _, g, _ = engine.decode(toks, poss)
        pos += 1
        cur = int(g[0])
        want.append(cur)

    assert pod_tokens == want


def test_two_process_pod_scheduler_sampled_matches_mesh(tmp_path):
    """The full serving path on a pod: ContinuousBatchingScheduler on the
    root driving a RootControlEngine with a SAMPLED request (temp>0, fixed
    seed), workers replaying PREFILL/DECODE packets that now carry the
    sampling scalars — the round-3 regression (prefill_chunk TypeError +
    divergent replicated sampling operands) stays fixed. Parity oracle: the
    same scheduler over the same tp=2 GSPMD program in ONE process (this
    one, on the suite's virtual CPU devices)."""
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        tiny_header,
        write_synthetic_model,
        write_synthetic_tokenizer,
    )

    tmp = str(tmp_path)
    header = tiny_header()
    write_synthetic_model(os.path.join(tmp, "m.m"), header, seed=7)
    write_synthetic_tokenizer(os.path.join(tmp, "t.t"), vocab_size=header.vocab_size)
    _run_pod(tmp, SCHED_DRIVER)

    with open(os.path.join(tmp, "root_sched_tokens.json")) as f:
        pod = json.load(f)
    assert len(pod["sampled"]) == 6

    # single-process oracle: identical tp=2 mesh + scheduler + request
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

    h = load_model_header(os.path.join(tmp, "m.m"))
    config, params = load_params_from_m(os.path.join(tmp, "m.m"), h, dtype=jnp.float32)
    mesh = make_mesh(MeshPlan(tp=2))
    params = shard_params(params, mesh)
    engine = InferenceEngine(
        config, params, n_lanes=2, mesh=mesh, replicate_outputs=True
    )
    t = Tokenizer(os.path.join(tmp, "t.t"))
    sched = ContinuousBatchingScheduler(engine, t)
    sched.start()
    req = Request(prompt="hello world", max_tokens=6, temperature=0.7, seed=1234)
    sched.submit(req)
    req.future.result(timeout=300)
    assert req.error is None, req.error
    # same sequential shared-prefix pair the pod root served
    shared = "hello world hello world hello world hello wor "
    outs = []
    for tail in ("one", "two"):
        r = Request(prompt=shared + tail, max_tokens=4, temperature=0.0)
        sched.submit(r)
        r.future.result(timeout=300)
        assert r.error is None, r.error
        outs.append(r.generated_tokens)
    sched.stop()

    # same cross-lane copy_lane + decode-on-copied-cache the pod root ran
    import numpy as np

    ids = t.encode("hello world hello world")
    _, g, pos = engine.prefill(0, ids)
    engine.copy_lane(0, 1)
    cur = int(g)
    copied = [cur]
    tvec = np.zeros(2, np.int32)
    pvec = np.zeros(2, np.int32)
    for _ in range(4):
        tvec[1] = cur
        pvec[1] = pos
        _, gg, _ = engine.decode(tvec, pvec)
        pos += 1
        cur = int(gg[1])
        copied.append(cur)

    assert pod["sampled"] == req.generated_tokens
    assert pod["prefix"] == outs
    assert pod["copy"] == copied, "pod cross-lane KV copy diverged"


class _ScriptedPlane:
    """In-process stand-in for ControlPlane: serves a scripted packet list
    (no broadcast, no pod) so worker_serve's restart policy is testable in
    milliseconds. Packets carry the real magic/version header and go
    through the real validation gate."""

    HEADER = 6

    def __init__(self, ops, chunk=8):
        self.chunk = chunk
        self._pkts = [self._pkt(op) for op in ops]

    def _pkt(self, op):
        import numpy as np

        from distributed_llama_multiusers_tpu.parallel.multihost import (
            PACKET_MAGIC, PROTOCOL_VERSION,
        )

        pkt = np.zeros(self.HEADER + 7 * self.chunk, np.int32)
        pkt[0:6] = (PACKET_MAGIC, PROTOCOL_VERSION, op, 0, 2, 0)
        return pkt

    def recv(self):
        from distributed_llama_multiusers_tpu.parallel.multihost import (
            ControlPlane,
        )

        pkt = self._pkts.pop(0)
        ControlPlane.validate(pkt)
        return pkt

    def slot(self, pkt, i, n):
        start = self.HEADER + i * self.chunk
        return pkt[start : start + n]


class _ScriptedEngine:
    """decode() raises on the scripted call indices (1-based)."""

    SPEC_DRAFT = 3

    def __init__(self, fail_on=()):
        self.calls = 0
        self.fail_on = set(fail_on)

    def decode(self, *a, want_logits=True, g_states=None):
        self.calls += 1
        if self.calls in self.fail_on:
            raise RuntimeError(f"transient #{self.calls}")


def test_worker_serve_budget_refreshes_after_healthy_window():
    """Three transient errors spread over a long replay stream survive a
    max_restarts=2 budget because healthy_window replays refresh it — the
    reference worker re-serves indefinitely (src/app.cpp:405-464); the old
    lifetime counter would have died on the third error."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_DECODE, OP_STOP, worker_serve,
    )

    fail_on = {4, 8, 12}  # each preceded by >= 3 healthy replays
    ops = [OP_DECODE] * 13 + [OP_STOP]
    engine = _ScriptedEngine(fail_on)
    worker_serve(
        engine, _ScriptedPlane(ops), max_restarts=2, healthy_window=3,
        log=lambda m: None,
    )
    assert engine.calls == 13  # every packet replayed, worker exited on stop


def test_worker_serve_persistent_error_still_raises():
    """A persistent error (every replay fails — the desync signature)
    exhausts the budget within one window and raises."""
    import pytest

    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_DECODE, worker_serve,
    )

    engine = _ScriptedEngine(fail_on=set(range(1, 100)))
    with pytest.raises(RuntimeError, match="transient"):
        worker_serve(
            engine, _ScriptedPlane([OP_DECODE] * 20), max_restarts=2,
            healthy_window=3, log=lambda m: None,
        )
    assert engine.calls == 3  # restarts 1..3 > max_restarts=2


def test_stats_reset_op_clears_worker_counters():
    """OP_STATS_RESET replays as engine.stats.reset() so pod workers drop
    warmup traffic from their counters (the root restores its own via
    stats.preserved())."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_STATS_RESET, OP_STOP, worker_loop,
    )
    from distributed_llama_multiusers_tpu.runtime.engine import EngineStats

    class _Eng(_ScriptedEngine):
        stats = EngineStats()

    engine = _Eng()
    engine.stats.decode_steps = 7
    engine.stats.spec_steps = 2
    worker_loop(engine, _ScriptedPlane([OP_STATS_RESET, OP_STOP]))
    assert engine.stats.decode_steps == 0
    assert engine.stats.spec_steps == 0
