"""Grammar slab: the fixed-capacity host mirror of the device tables.

One slab per engine. State 0 is the FREE state — mask all-ones, default
target 0 — so unconstrained lanes flow through the identical compiled
math with a literal identity mask and the step families need no grammar
branch at all. Compiled automata install at a base offset (their local
states shift by ``base``); admissions of the same schema share the
installed range by refcount, releases park the range (LRU-evicted under
pressure) so schema churn does not re-upload tables.

Capacities are FIXED at construction: the device arrays the engine
uploads from this mirror keep one shape forever, so a new schema mid-
serving changes array VALUES only — never an XLA recompile. A schema too
big for an empty slab raises :class:`~.automaton.GrammarError` (a 400);
a slab full of OTHER live schemas raises :class:`GrammarSlabFull`
(retryable load, the pool-exhausted shape).

Pure host numpy, shared verbatim by the real engine and the mock engine
(utils/testing.MockAsyncEngine), so scheduler-level tests exercise the
identical allocation/refcount/eviction bookkeeping.
"""

from __future__ import annotations

import numpy as np

from .automaton import GrammarAutomaton, GrammarError

DEFAULT_SLAB_STATES = 1024
DEFAULT_SLAB_EDGES = 49152
_KEY_SENTINEL = np.iinfo(np.int32).max


class GrammarSlabFull(RuntimeError):
    """No contiguous free state range / edge capacity for a new grammar:
    load, not a bad schema — the scheduler sheds it retryably (the
    pool-exhausted 429 shape), never a 500."""


class SlabHandle:
    """One attached grammar: the automaton plus its slab base offset.
    Lane-facing mirror API works in ABSOLUTE slab state ids (what the
    device carry holds)."""

    def __init__(self, slab: "GrammarSlab", automaton: GrammarAutomaton,
                 base: int):
        self.slab = slab
        self.automaton = automaton
        self.base = base

    @property
    def key(self) -> str:
        return self.automaton.key

    @property
    def start_state(self) -> int:
        return self.base  # local start is 0

    def next_state(self, state: int, tok: int) -> int:
        return self.base + self.automaton.next_state(
            state - self.base, int(tok)
        )

    def is_legal(self, state: int, tok: int) -> bool:
        return self.automaton.is_legal(state - self.base, int(tok))

    def filter_prefix(self, state: int, tokens) -> int:
        return self.automaton.filter_prefix(state - self.base, tokens)


class _Entry:
    __slots__ = ("automaton", "base", "refs", "stamp")

    def __init__(self, automaton, base):
        self.automaton = automaton
        self.base = base
        self.refs = 0
        self.stamp = 0  # LRU tick of the last release


class GrammarSlab:
    def __init__(self, vocab_size: int,
                 n_states: int = DEFAULT_SLAB_STATES,
                 n_edges: int = DEFAULT_SLAB_EDGES):
        self.vocab_size = int(vocab_size)
        # device transition keys are int32 (state * vocab + token): shrink
        # the state capacity so the largest key always fits
        max_states = max(2, (2**31 - 1) // max(1, self.vocab_size))
        self.n_states = int(min(n_states, max_states))
        self.n_edges = int(n_edges)
        self.words = (self.vocab_size + 31) // 32
        self.masks = np.zeros((self.n_states, self.words), np.uint32)
        self.masks[0, :] = np.uint32(0xFFFFFFFF)  # FREE: everything legal
        self.default_next = np.zeros(self.n_states, np.int32)
        self.edge_keys = np.full(self.n_edges, _KEY_SENTINEL, np.int32)
        self.edge_next = np.zeros(self.n_edges, np.int32)
        self._entries: dict[str, _Entry] = {}
        self._free_ranges: list[tuple[int, int]] = [(1, self.n_states - 1)]
        self._tick = 0
        # bumped on every array change: the engine re-uploads the device
        # copies when its uploaded version falls behind
        self.version = 0

    # -- allocation ----------------------------------------------------------

    def _alloc(self, n: int) -> int | None:
        for i, (base, size) in enumerate(self._free_ranges):
            if size >= n:
                if size == n:
                    self._free_ranges.pop(i)
                else:
                    self._free_ranges[i] = (base + n, size - n)
                return base
        return None

    def _release_range(self, base: int, n: int) -> None:
        self._free_ranges.append((base, n))
        self._free_ranges.sort()
        merged: list[tuple[int, int]] = []
        for b, s in self._free_ranges:
            if merged and merged[-1][0] + merged[-1][1] == b:
                merged[-1] = (merged[-1][0], merged[-1][1] + s)
            else:
                merged.append((b, s))
        self._free_ranges = merged

    def _edges_used(self) -> int:
        return sum(
            len(e.automaton.edge_keys) for e in self._entries.values()
        )

    def _evict_parked(self, need_states: int, need_edges: int) -> None:
        """Drop refcount-0 entries (oldest release first) until the new
        grammar fits, or nothing parked remains."""
        while True:
            if (
                self._alloc_would_fit(need_states)
                and self._edges_used() + need_edges <= self.n_edges
            ):
                return
            parked = [
                (e.stamp, k) for k, e in self._entries.items() if e.refs == 0
            ]
            if not parked:
                return
            _, key = min(parked)
            self._remove(key)

    def _alloc_would_fit(self, n: int) -> bool:
        return any(size >= n for _, size in self._free_ranges)

    def _remove(self, key: str) -> None:
        e = self._entries.pop(key)
        n = e.automaton.n_states
        self.masks[e.base : e.base + n] = 0
        self.default_next[e.base : e.base + n] = 0
        self._release_range(e.base, n)
        self._rebuild_edges()
        self.version += 1

    def _rebuild_edges(self) -> None:
        keys, nexts = [], []
        for e in self._entries.values():
            a = e.automaton
            keys.append(
                (a.edge_keys + np.int64(e.base) * a.vocab_size).astype(
                    np.int64
                )
            )
            nexts.append(a.edge_next + np.int32(e.base))
        self.edge_keys[:] = _KEY_SENTINEL
        self.edge_next[:] = 0
        if keys:
            k = np.concatenate(keys)
            x = np.concatenate(nexts)
            order = np.argsort(k, kind="stable")
            k, x = k[order], x[order]
            self.edge_keys[: len(k)] = k.astype(np.int32)
            self.edge_next[: len(x)] = x

    # -- public API ----------------------------------------------------------

    def attach(self, automaton: GrammarAutomaton) -> SlabHandle:
        e = self._entries.get(automaton.key)
        if e is not None:
            e.refs += 1
            return SlabHandle(self, e.automaton, e.base)
        n = automaton.n_states
        ne = len(automaton.edge_keys)
        if n > self.n_states - 1 or ne > self.n_edges:
            # would not fit even into an EMPTY slab: a schema problem
            # (400), not load
            raise GrammarError(
                f"grammar needs {n} states / {ne} edges; slab capacity is "
                f"{self.n_states - 1} states / {self.n_edges} edges "
                "(raise --grammar-slab-states or simplify the schema)"
            )
        self._evict_parked(n, ne)
        base = self._alloc(n)
        if base is None or self._edges_used() + ne > self.n_edges:
            if base is not None:
                self._release_range(base, n)
            raise GrammarSlabFull(
                f"grammar slab exhausted by live schemas "
                f"({len(self._entries)} installed)"
            )
        e = _Entry(automaton, base)
        e.refs = 1
        self._entries[automaton.key] = e
        self.masks[base : base + n] = automaton.masks
        self.default_next[base : base + n] = (
            automaton.default_next + np.int32(base)
        )
        self._rebuild_edges()
        self.version += 1
        return SlabHandle(self, automaton, base)

    def detach(self, key: str) -> None:
        """Release one reference; the installed range PARKS at refcount 0
        (tables stay resident for the next same-schema admission) and is
        only evicted under capacity pressure."""
        e = self._entries.get(key)
        if e is None:
            return
        e.refs = max(0, e.refs - 1)
        self._tick += 1
        e.stamp = self._tick

    def resolve(self, state: int):
        """(automaton, base) owning an absolute slab state, or None for
        the FREE state / unmapped ranges — how a state-carrying consumer
        (the mock engine's simulated device) maps a carry back to its
        automaton."""
        for e in self._entries.values():
            n = e.automaton.n_states
            if e.base <= state < e.base + n:
                return e.automaton, e.base
        return None

    def arrays(self):
        """(masks, edge_keys, edge_next, default_next) — the device
        upload source, fixed shapes forever."""
        return (self.masks, self.edge_keys, self.edge_next,
                self.default_next)

    def stats(self) -> dict:
        live = sum(1 for e in self._entries.values() if e.refs > 0)
        return {
            "grammar_schemas_installed": len(self._entries),
            "grammar_schemas_live": live,
            "grammar_slab_states_used": sum(
                e.automaton.n_states for e in self._entries.values()
            ) + 1,
            "grammar_slab_states_total": self.n_states,
        }
