import numpy as np

from distributed_llama_multiusers_tpu.formats import (
    load_model_header,
    load_tokenizer_file,
)
from distributed_llama_multiusers_tpu.formats.model_file import model_tensor_specs, iter_model_tensors


def test_model_header_roundtrip(tiny_model):
    h0 = tiny_model["header"]
    h = load_model_header(tiny_model["model"])
    assert h.dim == h0.dim
    assert h.hidden_dim == h0.hidden_dim
    assert h.n_layers == h0.n_layers
    assert h.n_heads == h0.n_heads
    assert h.n_kv_heads == h0.n_kv_heads
    assert h.vocab_size == h0.vocab_size
    assert h.seq_len == h0.seq_len
    assert h.weight_type == h0.weight_type
    assert h.kv_dim == (h0.dim * h0.n_kv_heads) // h0.n_heads


def test_max_seq_len_clamp(tiny_model):
    # src/llm.cpp:89-91
    h = load_model_header(tiny_model["model"], max_seq_len=16)
    assert h.seq_len == 16
    assert h.orig_seq_len == tiny_model["header"].seq_len


def test_tensor_walk_consumes_whole_file(tiny_model):
    h = load_model_header(tiny_model["model"])
    specs = model_tensor_specs(h)
    assert specs[-1].offset + specs[-1].n_bytes == h.file_size
    names = [s.name for s in specs]
    assert names[0] == "embedding"
    assert names[-1] == "final_matmul_logits"
    count = 0
    for spec, raw in iter_model_tensors(tiny_model["model"], h):
        assert raw.nbytes == spec.n_bytes
        count += 1
    assert count == len(specs)


def test_tokenizer_roundtrip(tiny_model):
    t = load_tokenizer_file(tiny_model["tokenizer"])
    assert t.vocab_size == tiny_model["header"].vocab_size
    assert t.bos_id >= 0
    assert t.vocab[t.bos_id] == b"<|begin_of_text|>"
    assert len(t.eos_token_ids) == 1
    assert t.vocab[t.eos_token_ids[0]] == b"<|eot_id|>"
    assert "<|start_header_id|>" in t.chat_template
    assert t.max_token_length == max(len(v) for v in t.vocab)
