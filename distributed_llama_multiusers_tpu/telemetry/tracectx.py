"""Fleet trace context: one id per request, carried across every hop.

Since PR 12 (fleet router + live migration), PR 16 (disaggregated
prefill) and PR 19 (host-RAM swap tier) a single request routinely
crosses three or four processes — router, prefill-role replica, decode
replica, a migration target — and each process's span ring only knew its
own slice of the story. The ``TraceContext`` here is the thread that
stitches them back together: a 128-bit trace id plus the 64-bit span id
of the hop that forwarded the request, minted by ``dllama-router`` for
fresh traffic or accepted from a client ``X-DLlama-Trace`` header, and
propagated on every hop the fleet already makes (route/retry/failover,
migration ticket inject, disagg prefill→decode hand-off, journal admit
records so a crash-recovered stream rejoins its original trace).

Wire format (the ``X-DLlama-Trace`` header value)::

    <32 lowercase hex chars trace id>-<16 lowercase hex chars span id>

deliberately shaped like W3C traceparent's id fields without the
version/flags framing — two ids, one dash, trivially parseable by any
log pipeline. Invalid headers are *ignored* (a fresh context is minted),
never 400d: tracing must not be able to fail a request.

Pure stdlib like the rest of ``telemetry/`` (registered under dlint's
``host-sync`` scope): ids come from ``os.urandom``, no wall-clock reads
(the ``clock`` check covers this file), and the one stateful class
(``PhaseAccumulator``, the router-side aggregation state behind
``dllama_request_phase_seconds``) declares its lock discipline via
``_dlint_guarded_by`` like every other telemetry lock.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass

from ..lockcheck import make_lock

TRACE_HEADER = "X-DLlama-Trace"

_WIRE_RE = re.compile(r"^([0-9a-f]{32})-([0-9a-f]{16})$")

# an all-zero id is the W3C-traceparent "invalid" convention; refuse it
_ZERO_TRACE = "0" * 32
_ZERO_SPAN = "0" * 16


@dataclass(frozen=True)
class TraceContext:
    """One request's fleet-wide identity: ``trace_id`` names the request
    for its whole life (across migration, hand-off, recovery), ``span_id``
    names the hop that forwarded it (re-minted per hop via ``child()``,
    so a replica can tell a retry from the original attempt)."""

    trace_id: str
    span_id: str

    @staticmethod
    def mint() -> "TraceContext":
        """A fresh context: 128-bit trace id, 64-bit span id, both from
        ``os.urandom`` (no wall clock, no PRNG state to guard)."""
        return TraceContext(
            trace_id=os.urandom(16).hex(), span_id=os.urandom(8).hex()
        )

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — stamp one per forwarding hop
        (route attempt, retry, migration inject, disagg hand-off) so the
        merged timeline attributes each hop distinctly."""
        return TraceContext(trace_id=self.trace_id, span_id=os.urandom(8).hex())

    def to_header(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    @staticmethod
    def parse(value: str | None) -> "TraceContext | None":
        """Parse a wire value; ``None`` on anything malformed (callers
        mint a fresh context instead — tracing never fails a request)."""
        if not value or not isinstance(value, str):
            return None
        m = _WIRE_RE.match(value.strip().lower())
        if m is None:
            return None
        trace_id, span_id = m.group(1), m.group(2)
        if trace_id == _ZERO_TRACE or span_id == _ZERO_SPAN:
            return None
        return TraceContext(trace_id=trace_id, span_id=span_id)

    @staticmethod
    def accept(value: str | None) -> "TraceContext":
        """The router's ingress rule: honour a valid client header
        (clients correlating with their own telemetry), mint otherwise."""
        ctx = TraceContext.parse(value)
        return ctx if ctx is not None else TraceContext.mint()


def trace_id_of(wire: str | None) -> str | None:
    """The trace id of a wire value, or None — the one-liner span
    emitters use to stamp ``trace_id`` args without caring whether the
    request carried a context at all."""
    ctx = TraceContext.parse(wire)
    return None if ctx is None else ctx.trace_id


# phase keys every producer emits, in display order. ``sync_ms`` is only
# non-zero when the mesh reports measured collective time; off-mesh it
# stays 0 rather than absent so consumers need no key probing.
PHASE_KEYS = (
    "queue_wait_ms", "prefill_ms", "decode_ms", "itl_p50_ms", "itl_p99_ms",
    "migration_gap_ms", "swap_in_ms", "sync_ms", "ttft_ms", "total_ms",
)


class PhaseAccumulator:
    """Router-side aggregation of per-request ``phases`` records.

    The router sees every completion's terminal payload (streaming
    terminal chunk or non-streaming body); folding the ``phases`` record
    there gives fleet-wide TTFT/ITL/phase distributions measured at the
    one vantage point that also knows about migrations — the artifact
    ROADMAP item 3(d)'s tail-latency curve reads. Kept deliberately
    small: per-phase count/sum under one short lock; the bucketed
    distribution lives in the caller's ``MetricsRegistry`` histogram
    (``dllama_request_phase_seconds``), fed from the same observe call.
    """

    # dlint guarded-by declaration (analysis/lock_check.py): aggregation
    # state only under `_phase_lock`. Machine-checked by `make lint`.
    _dlint_guarded_by = {
        ("_phase_lock",): ("_phase_counts", "_phase_sums_ms", "_phase_records"),
    }

    def __init__(self):
        # witness-wrappable (DLLAMA_LOCKCHECK=1), named for the
        # class-qualified declaration like every telemetry lock
        self._phase_lock = make_lock("PhaseAccumulator._phase_lock")
        self._phase_counts: dict[str, int] = {}
        self._phase_sums_ms: dict[str, float] = {}
        self._phase_records = 0

    def observe(self, phases: dict | None) -> dict | None:
        """Fold one ``phases`` record; returns the cleaned record (only
        known keys, numeric values) or None if there was nothing usable.
        Callers feed the same cleaned record into their histogram so the
        accumulator and ``/metrics`` cannot drift."""
        if not isinstance(phases, dict):
            return None
        clean = {}
        for key in PHASE_KEYS:
            v = phases.get(key)
            if isinstance(v, (int, float)) and v >= 0:
                clean[key] = float(v)
        if not clean:
            return None
        with self._phase_lock:
            self._phase_records += 1
            for key, v in clean.items():
                self._phase_counts[key] = self._phase_counts.get(key, 0) + 1
                self._phase_sums_ms[key] = (
                    self._phase_sums_ms.get(key, 0.0) + v
                )
        return clean

    def snapshot(self) -> dict:
        """{records, per-phase count + sum_ms} for /stats — dict-valued,
        so the stats bridge republishes it as labelled gauges."""
        with self._phase_lock:
            return {
                "phase_records": self._phase_records,
                "phase_counts": dict(self._phase_counts),
                "phase_sum_ms": {
                    k: round(v, 3) for k, v in self._phase_sums_ms.items()
                },
            }
