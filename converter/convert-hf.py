#!/usr/bin/env python
"""Convert a HuggingFace Llama/Mistral checkpoint folder to the `.m` format.

Usage: python convert-hf.py <sourceFolderPath> <weightsFloatType> <name>

Reimplementation of the reference converter (converter/convert-hf.py):
- tensor order must match the runtime walk (src/llm.cpp:447-483 /
  formats/model_file.py model_tensor_specs)
- Q and K projections are permuted from HF half-rotation layout to the
  interleaved-rotary layout the runtime's RoPE expects
  (reference converter/convert-hf.py:11-14)
- embeddings/norms stay F32; lm_head falls back to the tied embedding
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_llama_multiusers_tpu.formats.model_file import ArchType, HiddenAct, ModelHeader, RopeType
from distributed_llama_multiusers_tpu.quants.codec import FloatType
from writer import parse_float_type, write_header, write_tensor


def permute_rotary(w: "np.ndarray", n_heads: int) -> "np.ndarray":
    """HF half-rotation -> interleaved layout: row blocks [h, 2, d/2] -> [h, d/2, 2]."""
    d_out, d_in = w.shape
    return (
        w.reshape(n_heads, 2, d_out // n_heads // 2, d_in).swapaxes(1, 2).reshape(d_out, d_in)
    )


class SafetensorsIndex:
    """Lazy tensor lookup across sharded safetensors files, loading one file
    at a time (the reference's Processor.__loadModel memory discipline)."""

    def __init__(self, files: list[str]):
        from safetensors import safe_open

        self._open = safe_open
        self._key_to_file: dict[str, str] = {}
        for path in files:
            with safe_open(path, framework="pt", device="cpu") as f:
                for k in f.keys():
                    self._key_to_file[k] = path
        self._current_path: str | None = None
        self._current = None

    def __contains__(self, key: str) -> bool:
        return key in self._key_to_file

    def get(self, key: str) -> np.ndarray:
        import torch

        path = self._key_to_file[key]
        if path != self._current_path:
            self._current = self._open(path, framework="pt", device="cpu").__enter__()
            self._current_path = path
            print(f"💿 reading {os.path.basename(path)}")
        t = self._current.get_tensor(key)
        return t.to(torch.float32).numpy()


def load_config(folder: str, weight_type: int) -> tuple[ModelHeader, dict]:
    with open(os.path.join(folder, "config.json")) as f:
        cfg = json.load(f)
    # qwen2 is the llama graph + q/k/v projection biases (KEY_QKV_BIAS;
    # detected from the checkpoint tensors in convert())
    arch = {
        "llama": ArchType.LLAMA,
        "mistral": ArchType.LLAMA,
        "mixtral": ArchType.LLAMA,
        "qwen2": ArchType.LLAMA,
    }.get(cfg["model_type"])
    if arch is None:
        raise ValueError(f"Unsupported arch type: {cfg['model_type']}")
    act = {"gelu": HiddenAct.GELU, "silu": HiddenAct.SILU}.get(cfg["hidden_act"])
    if act is None:
        raise ValueError(f"Unsupported hidden act: {cfg['hidden_act']}")
    h = ModelHeader(
        version=0,
        arch_type=arch,
        hidden_act=act,
        dim=cfg["hidden_size"],
        hidden_dim=cfg["intermediate_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg["num_key_value_heads"],
        weight_type=weight_type,
        seq_len=cfg["max_position_embeddings"],
        orig_seq_len=cfg["max_position_embeddings"],
        vocab_size=cfg["vocab_size"],
        rope_theta=float(cfg.get("rope_theta", 10000.0)),
    )
    n_experts = cfg.get("num_local_experts")
    if n_experts:
        h.n_experts = int(n_experts)
        h.n_active_experts = int(
            cfg.get("num_active_local_experts") or cfg.get("num_experts_per_tok")
        )
    scaling = cfg.get("rope_scaling")
    if scaling is not None and scaling.get("rope_type") in ("llama3",):
        h.rope_type = RopeType.LLAMA3_1
        h.rope_scaling_factor = float(scaling["factor"])
        h.rope_scaling_low_freq_factor = float(scaling["low_freq_factor"])
        h.rope_scaling_high_freq_factor = float(scaling["high_freq_factor"])
        h.rope_scaling_orig_max_seq_len = int(scaling["original_max_position_embeddings"])
    elif scaling is not None and scaling.get("rope_type") not in (None, "default"):
        raise ValueError(f"Unsupported rope scaling: {scaling}")
    return h, cfg


def convert(folder: str, weight_type: int, out_path: str) -> None:
    header, cfg = load_config(folder, weight_type)
    files = sorted(
        os.path.join(folder, f)
        for f in os.listdir(folder)
        if f.endswith(".safetensors") and not f.startswith(".")
    )
    if not files:
        raise FileNotFoundError("No .safetensors files found")
    index = SafetensorsIndex(files)
    wt = weight_type
    n_heads, n_kv = header.n_heads, header.n_kv_heads
    # Qwen2-family checkpoints (and llama-arch configs with
    # attention_bias=true) carry q/k/v projection biases
    header.qkv_bias = int("model.layers.0.self_attn.q_proj.bias" in index)

    def bias_permuted(key: str, heads: int) -> np.ndarray:
        # same head-dim rotary relayout as the weight, applied to the vector
        return permute_rotary(index.get(key).reshape(-1, 1), heads).reshape(-1)

    with open(out_path, "wb") as out:
        write_header(out, header)
        write_tensor(out, index.get("model.embed_tokens.weight"), FloatType.F32)
        for l in range(header.n_layers):
            pre = f"model.layers.{l}"
            write_tensor(out, permute_rotary(index.get(f"{pre}.self_attn.q_proj.weight"), n_heads), wt)
            if header.qkv_bias:
                write_tensor(out, bias_permuted(f"{pre}.self_attn.q_proj.bias", n_heads), FloatType.F32)
            write_tensor(out, permute_rotary(index.get(f"{pre}.self_attn.k_proj.weight"), n_kv), wt)
            if header.qkv_bias:
                write_tensor(out, bias_permuted(f"{pre}.self_attn.k_proj.bias", n_kv), FloatType.F32)
            write_tensor(out, index.get(f"{pre}.self_attn.v_proj.weight"), wt)
            if header.qkv_bias:
                write_tensor(out, index.get(f"{pre}.self_attn.v_proj.bias"), FloatType.F32)
            write_tensor(out, index.get(f"{pre}.self_attn.o_proj.weight"), wt)
            if header.n_experts > 0:
                # router (framework extension: the reference converter drops
                # the gate, leaving its MoE files unrunnable) + per-expert
                # w3/w1/w2 in the reference's expert order (convert-hf.py:66-73
                # upstream)
                write_tensor(
                    out, index.get(f"{pre}.block_sparse_moe.gate.weight"), FloatType.F32
                )
                for e in range(header.n_experts):
                    epre = f"{pre}.block_sparse_moe.experts.{e}"
                    write_tensor(out, index.get(f"{epre}.w3.weight"), wt)  # up
                    write_tensor(out, index.get(f"{epre}.w1.weight"), wt)  # gate
                    write_tensor(out, index.get(f"{epre}.w2.weight"), wt)  # down
            else:
                write_tensor(out, index.get(f"{pre}.mlp.gate_proj.weight"), wt)  # w1
                write_tensor(out, index.get(f"{pre}.mlp.down_proj.weight"), wt)  # w2
                write_tensor(out, index.get(f"{pre}.mlp.up_proj.weight"), wt)  # w3
            write_tensor(out, index.get(f"{pre}.input_layernorm.weight"), FloatType.F32)
            write_tensor(out, index.get(f"{pre}.post_attention_layernorm.weight"), FloatType.F32)
        write_tensor(out, index.get("model.norm.weight"), FloatType.F32)
        head_key = "lm_head.weight" if "lm_head.weight" in index else "model.embed_tokens.weight"
        write_tensor(out, index.get(head_key), wt)
    print(f"✅ {out_path} created successfully")


def main() -> None:
    if len(sys.argv) < 4:
        print("Usage: python convert-hf.py <sourceFolderPath> <weightsFloatType> <name>")
        raise SystemExit(1)
    folder = sys.argv[1]
    weight_type = parse_float_type(sys.argv[2])
    name = sys.argv[3]
    convert(folder, weight_type, f"dllama_model_{name}_{sys.argv[2]}.m")


if __name__ == "__main__":
    main()
