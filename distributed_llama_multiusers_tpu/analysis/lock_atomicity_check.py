"""lock-atomicity: guarded read-modify-write must not straddle a release.

``_dlint_guarded_by`` (lock_check.py) proves every touch of a guarded
attribute happens under its lock — but lock-per-access is not atomicity.
The classic residual bug is a read-modify-write split across two
critical sections:

    with q._lock:
        depth = q._depth          # read
    ...                            # <- lock released: anyone may write
    with q._lock:
        q._depth = depth - 1      # write of a stale value

Each section is individually locked, so guarded-by is green, yet the
interleaving loses updates (or acts on a stale check — the
check-then-act variant ``if q._depth: ... with q._lock: q._depth -= 1``
under two holds is the same shape). This check flags, within one
function, a **pure read** of a guarded attribute under one hold of its
lock followed by a **write** of the same base+attribute under a later,
distinct hold of the same lock. ``x.attr += 1`` inside ONE section is
fine (the AST spells it as a single Store; the implicit read never
leaves the critical section) — only reads that survive a release count.

Fix by folding the read and the write into one critical section (the
QosQueue/EngineStats code already does: snapshots and bumps are
single-hold by construction); waive (``ok[lock-atomicity] reason``) only
for deliberately optimistic patterns that re-validate after reacquiring.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, Project, SourceFile, nearest, walk_with_ancestors
from .lockgraph import LockModel, module_stem, walk_excluding_nested_defs


class LockAtomicityChecker(Checker):
    name = "lock-atomicity"
    description = (
        "a guarded attribute read under one hold of its lock and written "
        "under a later hold loses updates made between the two sections"
    )

    def check(self, sf: SourceFile, project: Project):
        model: LockModel = project.lock_model
        if not project.guarded or model is None or not model.decls:
            return
        model.ensure_semantics()
        stem = module_stem(sf.path)
        for node, ancestors in walk_with_ancestors(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            cls = nearest(ancestors, ast.ClassDef)
            class_ctx = cls.name if cls is not None else None
            yield from self._check_fn(sf, node, project, model, class_ctx, stem)

    def _check_fn(self, sf: SourceFile, fn, project, model, class_ctx, stem):
        # every with-block in this function body that takes a known lock
        # (nested defs excluded: they run on their own call stacks), in
        # source order; each gets its guarded reads/writes attributed
        blocks: list[dict] = []
        own_nodes = set(map(id, walk_excluding_nested_defs(fn)))
        for w in ast.walk(fn):
            if id(w) not in own_nodes or not isinstance(w, (ast.With, ast.AsyncWith)):
                continue
            quals = set()
            for item in w.items:
                q = model.resolve(item.context_expr, class_ctx, stem)
                if q is not None:
                    quals.add(q)
            if not quals:
                continue
            reads: dict[tuple[str, str], int] = {}
            writes: dict[tuple[str, str], int] = {}
            for inner in ast.walk(w):
                if id(inner) not in own_nodes or not isinstance(inner, ast.Attribute):
                    continue
                if inner.attr not in project.guarded:
                    continue
                key = (ast.unparse(inner.value), inner.attr)
                if isinstance(inner.ctx, ast.Load):
                    reads.setdefault(key, inner.lineno)
                else:  # Store (Assign / AugAssign target) or Del
                    writes.setdefault(key, inner.lineno)
            blocks.append({
                "line": w.lineno, "quals": quals,
                "reads": reads, "writes": writes,
            })
        blocks.sort(key=lambda b: b["line"])
        reported: set[tuple] = set()
        for i, early in enumerate(blocks):
            for late in blocks[i + 1:]:
                shared = early["quals"] & late["quals"]
                if not shared:
                    continue
                for key, w_line in late["writes"].items():
                    r_line = early["reads"].get(key)
                    if r_line is None:
                        continue
                    base, attr = key
                    mark = (base, attr, r_line, w_line)
                    if mark in reported:
                        continue
                    reported.add(mark)
                    lock = sorted(shared)[0]
                    yield Finding(
                        self.name, sf.display, w_line,
                        f"read-modify-write of guarded '{base}.{attr}' "
                        f"straddles a release of '{lock}': read at line "
                        f"{r_line} and write at line {w_line} sit in "
                        "separate critical sections — fold them into one "
                        "hold, or waive an optimistic retry with "
                        "'# dlint: ok[lock-atomicity] reason'",
                    )
