// Exercise the OpenAI-style request shape against dllama-api
// (reference: examples/chat-api-client.js). Node >= 18.
//
//   node examples/chat-api-client.js [http://localhost:9990]

const API = process.argv[2] || "http://localhost:9990";

async function main() {
  const models = await (await fetch(`${API}/v1/models`)).json();
  console.log("models:", models.data.map((m) => m.id).join(", "));

  const body = {
    messages: [
      { role: "system", content: "You are a helpful assistant." },
      { role: "user", content: "Say hello in five words." },
    ],
    max_tokens: 64,
    temperature: 0.7,
    top_p: 0.9,
    stop: ["\n\n"],
  };
  const resp = await (
    await fetch(`${API}/v1/chat/completions`, {
      method: "POST",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify(body),
    })
  ).json();
  console.log("generated_text:", resp.generated_text);
  console.log("finish_reason:", resp.choices[0].finish_reason, "usage:", resp.usage);
}

main().catch((e) => {
  console.error(e);
  process.exit(1);
});
