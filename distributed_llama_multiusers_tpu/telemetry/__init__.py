"""Request-lifecycle tracing + metrics for the pipelined serving path.

The reference engine's entire observability story is per-step-type
``totalTime[]`` sums and socket byte counters (SURVEY.md §5.1,
src/dllama.cpp:54-64); our ``EngineStats``/``/stats`` inherited that
aggregate shape. After the async pipeline (PR 3) and fused admissions
(PR 4) the serving path is exactly the kind of system aggregates lie
about — where a slow request spent its time, whether overlap actually
happened, which lane stalled. This package is the three missing layers:

- **spans.py / trace.py** — per-request lifecycle spans and per-dispatch
  step slices in a bounded host-side ring, exported as Chrome trace-event
  JSON (Perfetto / chrome://tracing loadable): lanes as tracks,
  fused/pipelined steps as slices, admissions/finishes/flushes as
  instants. Zero syncs or locks in the pipelined dispatch half — slices
  are stamped at consume time, one step behind (dlint ``pipeline-sync``
  stays green); monotonic clocks only (``clock`` stays green).
- **metrics.py** — counters/gauges/fixed-bucket log-scale histograms
  (TTFT, inter-token gap, queue wait, step duration) with Prometheus
  text exposition, served at ``GET /metrics`` and bridged from the same
  ``/stats`` snapshot so the two endpoints reconcile.
- **logs.py** — one structured JSON line per request (the summary also
  attached to completion responses) plus startup config lines.

Pure stdlib (no numpy/jax): importable anywhere dlint runs, and
registered under dlint's ``clock``, ``host-sync``, and ``guarded-by``
checks. Entry points: ``Telemetry`` (the hub the scheduler, HTTP server,
and bench share), ``GET /metrics`` / ``GET /trace`` (server/http.py),
``--trace-path`` (dumped on drain). docs/OBSERVABILITY.md is the guide.
"""

from .hub import Telemetry
from .logs import JsonLogger, default_logger, log_event
from .metrics import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    LabelledHistogram,
    MetricsRegistry,
    log_buckets,
)
from .spans import RequestTrace, SpanEvent, SpanTracer
from .trace import (
    chrome_trace,
    dump_chrome_trace,
    merge_chrome_traces,
    tracer_chrome_trace,
)
from .tracectx import (
    PHASE_KEYS,
    TRACE_HEADER,
    PhaseAccumulator,
    TraceContext,
    trace_id_of,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "JsonLogger",
    "LATENCY_BUCKETS_S",
    "LabelledHistogram",
    "MetricsRegistry",
    "PHASE_KEYS",
    "PhaseAccumulator",
    "RequestTrace",
    "SpanEvent",
    "SpanTracer",
    "TRACE_HEADER",
    "Telemetry",
    "TraceContext",
    "chrome_trace",
    "default_logger",
    "dump_chrome_trace",
    "log_buckets",
    "log_event",
    "merge_chrome_traces",
    "trace_id_of",
    "tracer_chrome_trace",
]
