"""Structured one-line JSON logs: startup config and per-request summary.

Deployments grep these, log pipelines parse them, and the ROADMAP-scale
fleet correlates them with traces by ``request_id`` — so every line is a
single JSON object on stderr (never stdout: the CLI prints generated
text there) with a fixed envelope:

    {"event": "...", "ts": <unix seconds>, "mono_s": <monotonic>, ...}

``ts`` is the one sanctioned wall-clock read in the telemetry package —
an absolute timestamp leaving the process, the same category as the API
``created`` fields (waived under dlint's ``clock`` check); everything
that measures a *duration* uses the monotonic fields.
"""

from __future__ import annotations

import json
import sys
import time

from ..lockcheck import make_lock


class JsonLogger:
    """One JSON object per line to ``stream`` (default stderr). A module
    lock serializes lines so concurrent HTTP threads never interleave
    bytes mid-record."""

    def __init__(self, stream=None):
        self.stream = stream
        # witness-wrappable (DLLAMA_LOCKCHECK=1, lockcheck.py)
        self._log_lock = make_lock("JsonLogger._log_lock")

    def emit(self, event: str, **fields) -> None:
        rec = {
            "event": event,
            # dlint: ok[clock] absolute wall timestamp leaving the process in the log line (durations use mono_s)
            "ts": round(time.time(), 3),
            "mono_s": round(time.monotonic(), 6),
        }
        rec.update(fields)
        line = json.dumps(rec, default=str)
        stream = self.stream if self.stream is not None else sys.stderr
        with self._log_lock:
            try:
                # dlint: ok[lock-blocking] serializing whole lines onto the stream is this lock's entire purpose; writers block on each other by design
                print(line, file=stream, flush=True)
            except (ValueError, OSError):
                pass  # closed stream at interpreter teardown: drop the line


_DEFAULT = JsonLogger()


def default_logger() -> JsonLogger:
    return _DEFAULT


def log_event(event: str, **fields) -> None:
    """Emit on the process-default logger (startup lines from code that
    has no Telemetry instance in hand, e.g. ``warmup_engine``)."""
    _DEFAULT.emit(event, **fields)
