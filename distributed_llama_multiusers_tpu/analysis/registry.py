"""Checker registry: the six project-invariant checks, in report order."""

from __future__ import annotations

from .clock_check import ClockChecker
from .condvar_check import CondvarChecker
from .core import Checker
from .host_sync_check import HostSyncChecker
from .lock_check import GuardedByChecker
from .pipeline_check import PipelineSyncChecker
from .sharding_check import ShardingAxisChecker

ALL_CHECKERS = (
    GuardedByChecker,
    HostSyncChecker,
    PipelineSyncChecker,
    ClockChecker,
    CondvarChecker,
    ShardingAxisChecker,
)


def default_checkers() -> list[Checker]:
    """Fresh checker instances (checkers keep no state, but the Project
    they fill does, so every run gets its own set)."""
    return [cls() for cls in ALL_CHECKERS]
