"""Chrome trace-event export: the span ring as a Perfetto-loadable JSON.

Output is the Trace Event Format's JSON-object form
(``{"traceEvents": [...], "displayTimeUnit": "ms"}``) using only the
parts every viewer (chrome://tracing, ui.perfetto.dev) honours:

- one process (pid 1, named for the model/server),
- one *thread* per logical track — ``lane0..laneN`` (requests pinned to
  their KV lane), ``pipeline`` (per-dispatch step slices), ``queue``
  (submit→admit waits) — named via ``M``/``thread_name`` metadata and
  ordered via ``thread_sort_index``,
- ``X`` complete events (``ts``+``dur`` in µs) for spans,
- ``i`` thread-scoped instants for admissions, finishes, flushes.

Fused prefill+decode dispatches render as ``step.fused`` slices on the
``pipeline`` track (plus a ``prefill.fused`` slice on the admitting
lane's track), so "did the admission actually ride the chain" is a thing
you *see*, not infer from counters.
"""

from __future__ import annotations

import json
from typing import Iterable

from .spans import SpanEvent, SpanTracer

PROCESS_NAME = "dllama-serving"


def _track_order(track: str) -> tuple:
    """Stable display order: lanes first (numeric), then pipeline, queue,
    then anything else alphabetically."""
    if track.startswith("lane"):
        suffix = track[4:]
        if suffix.isdigit():
            return (0, int(suffix), track)
    return ({"pipeline": 1, "queue": 2}.get(track, 3), 0, track)


def chrome_trace(events: Iterable[SpanEvent], origin: float = 0.0) -> dict:
    """Render span events into a Chrome trace-event JSON object.

    ``origin`` (the tracer's perf_counter epoch) rebases timestamps so
    the trace starts near t=0; event ``ts``/``dur`` come out in µs as the
    format requires."""
    events = list(events)
    tracks = sorted({e.track for e in events}, key=_track_order)
    tids = {t: i + 1 for i, t in enumerate(tracks)}
    # metadata events carry ts 0: the format ignores it, and a uniform
    # required-field set (name/ph/pid/tid/ts) keeps consumers simple
    out: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": 1, "tid": 0, "ts": 0,
        "args": {"name": PROCESS_NAME},
    }]
    for track, tid in tids.items():
        out.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid, "ts": 0,
            "args": {"name": track},
        })
        out.append({
            "name": "thread_sort_index", "ph": "M", "pid": 1, "tid": tid,
            "ts": 0, "args": {"sort_index": tid},
        })
    for e in events:
        args = dict(e.args) if e.args else {}
        if e.req_id is not None:
            args.setdefault("request_id", e.req_id)
        rec = {
            "name": e.name,
            "ph": e.ph,
            "pid": 1,
            "tid": tids[e.track],
            "ts": round((e.ts - origin) * 1e6, 3),
            "args": args,
        }
        if e.ph == "X":
            rec["dur"] = round(e.dur * 1e6, 3)
        elif e.ph == "i":
            rec["s"] = "t"  # thread-scoped instant
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def tracer_chrome_trace(tracer: SpanTracer) -> dict:
    return chrome_trace(tracer.snapshot(), origin=tracer.origin)


def dump_chrome_trace(tracer: SpanTracer, path: str) -> dict:
    """Write the tracer's current window to ``path`` and return the
    rendered document (the bench reports slice counts from it)."""
    doc = tracer_chrome_trace(tracer)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return doc
