#!/bin/bash
# Round-5 second-window supervisor: when the TPU tunnel answers, spend the
# window in strict value order for the kernel investigation:
#   1. kernel_lab3  — the cheaper-dequant variant A/B (decides the rework)
#   2. stage_probe  — micro-stage cost breakdown (dma/unpack/convert/scale)
#   3. missing bench phases (ablations, longctx) as standalone children
# Each step has its own timeout; steps run even if earlier ones fail. Logs
# under scripts/hw_window_<ts>/. Never touches git.
set -u
DIR="$(cd "$(dirname "$0")" && pwd)"
REPO="$(dirname "$DIR")"
cd "$REPO"
DEADLINE=$(( $(date +%s) + ${WINDOW_MAX_S:-36000} ))

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  TPU_PROBE_TIMEOUT_S=120 TPU_PROBE_INTERVAL_S=180 bash scripts/tpu_watch.sh || exit 1
  TS=$(date +%Y%m%d_%H%M%S)
  OUT="$DIR/hw_window_$TS"
  mkdir -p "$OUT"
  echo "tunnel alive at $(date -u)" > "$OUT/status"

  timeout 600 python scripts/kernel_lab3.py 4096 14336 8 8 \
    > "$OUT/kernel_lab3.log" 2>&1
  echo "kernel_lab3 rc=$?" >> "$OUT/status"

  timeout 480 python scripts/stage_probe.py 4096 14336 8 8 \
    > "$OUT/stage_probe.log" 2>&1
  echo "stage_probe rc=$?" >> "$OUT/status"

  BENCH_CHILD=1 BENCH_PHASE=ablations timeout 480 python bench.py \
    > "$OUT/ablations.json" 2> "$OUT/ablations.err"
  echo "ablations rc=$?" >> "$OUT/status"

  BENCH_CHILD=1 BENCH_PHASE=longctx timeout 360 python bench.py \
    > "$OUT/longctx.json" 2> "$OUT/longctx.err"
  echo "longctx rc=$?" >> "$OUT/status"

  # full bench last: banks the headline + serving (multi-step) + 8B +
  # the dequant-mode/DMA sweep + parity into BENCH_LIVE.json unattended
  BENCH_DEADLINE=2400 timeout 2600 python bench.py \
    > "$OUT/bench.out" 2> "$OUT/bench.err"
  echo "bench rc=$?" >> "$OUT/status"
  if python - "$OUT/bench.out" <<'EOF'
import json, sys
plat = None
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            plat = json.loads(line).get("platform")
except Exception:
    pass
sys.exit(0 if plat == "tpu" else 1)
EOF
  then
    tail -1 "$OUT/bench.out" > "$REPO/BENCH_LIVE.json"
    echo "TPU bench artifact banked" >> "$OUT/status"
  fi

  echo DONE >> "$OUT/status"
  # got a full window's evidence: stop so the foreground session decides
  # what the NEXT window should run (kernel rework A/B, full re-bench)
  exit 0
done
echo "next_window: deadline passed"
