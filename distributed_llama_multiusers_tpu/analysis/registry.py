"""Checker registry: the eighteen project-invariant checks, in report order.

Order matters for collection: the lock-order checker's collect pass
builds the shared cross-file lock model (``project.lock_model``) that
the other concurrency checks read. The analyzer runs every checker's
collect over every file before any check runs, so the model is complete
regardless of this ordering — but keeping the graph builder first keeps
the dependency legible.
"""

from __future__ import annotations

from .broadcast_check import PodBroadcastChecker
from .clock_check import ClockChecker
from .condvar_check import CondvarChecker
from .core import Checker
from .determinism_check import ReplayDeterminismChecker
from .host_sync_check import HostSyncChecker
from .jit_surface_check import (
    DonationDisciplineChecker,
    JitStabilityChecker,
    WarmupCoverageChecker,
)
from .lock_atomicity_check import LockAtomicityChecker
from .lock_blocking_check import LockBlockingChecker
from .lock_check import GuardedByChecker
from .lock_order_check import LockOrderChecker
from .pipeline_check import PipelineSyncChecker
from .protocol_check import ProtocolChecker, ProtocolManifestChecker
from .resource_check import DeviceAffinityChecker, ResourceBalanceChecker
from .sharding_check import ShardingAxisChecker

ALL_CHECKERS = (
    LockOrderChecker,
    GuardedByChecker,
    LockBlockingChecker,
    LockAtomicityChecker,
    PodBroadcastChecker,
    ProtocolChecker,
    ProtocolManifestChecker,
    ReplayDeterminismChecker,
    JitStabilityChecker,
    DonationDisciplineChecker,
    WarmupCoverageChecker,
    ResourceBalanceChecker,
    DeviceAffinityChecker,
    HostSyncChecker,
    PipelineSyncChecker,
    ClockChecker,
    CondvarChecker,
    ShardingAxisChecker,
)


def default_checkers() -> list[Checker]:
    """Fresh checker instances (checkers keep no state, but the Project
    they fill does, so every run gets its own set)."""
    return [cls() for cls in ALL_CHECKERS]
