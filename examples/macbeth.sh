#!/bin/sh
# Long-generation KV-cache-filling regression check (reference: examples/macbeth.sh):
# a long prompt + long generation exercises the full context window.
# Usage: ./examples/macbeth.sh <model.m> <tokenizer.t>
MODEL="${1:?model path}"
TOK="${2:?tokenizer path}"
PROMPT="Tomorrow, and tomorrow, and tomorrow, creeps in this petty pace from day to day, \
to the last syllable of recorded time. And all our yesterdays have lighted fools the way \
to dusty death. Out, out, brief candle. Life is but a walking shadow, a poor player that \
struts and frets his hour upon the stage,"
exec python -m distributed_llama_multiusers_tpu.app.dllama inference \
  --model "$MODEL" --tokenizer "$TOK" \
  --prompt "$PROMPT" --steps 256 --temperature 0 --max-seq-len 4096
