"""Test/dev-environment helpers.

Multi-chip behavior is validated on a virtual CPU device mesh, the TPU
analogue of the reference's fake-synchronizer + local-process-cluster test
strategy (src/nn/nn-executor.cpp:6-8, examples/n-workers.sh): the same GSPMD
partitioner and collectives run, just over host devices.
"""

from __future__ import annotations

import os
import sys
import time


def force_cpu_mesh(n_devices: int = 8) -> None:
    """Force JAX onto `n_devices` virtual CPU devices. Call BEFORE any jax
    backend is initialized.

    Two things are needed in this environment:
    1. xla_force_host_platform_device_count so one host looks like a mesh.
    2. Dropping any pre-registered TPU PJRT plugin (this box's sitecustomize
       registers one at interpreter start whose init dials a network tunnel —
       even under JAX_PLATFORMS=cpu, backend discovery would block on it).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    try:
        import jax
        from jax._src import xla_bridge

        if xla_bridge._default_backend is not None:  # pragma: no cover
            raise RuntimeError("force_cpu_mesh() must run before JAX backends initialize")
        # jax may have been imported (and read JAX_PLATFORMS) before us
        jax.config.update("jax_platforms", "cpu")
        for name in list(xla_bridge._backend_factories):
            if name != "cpu":
                del xla_bridge._backend_factories[name]
                # keep the NAME known: modules imported later (e.g. pallas ->
                # checkify) register platform-specific lowerings and assert
                # is_known_platform; only the factory must go, not the name
                plugins = getattr(xla_bridge, "_nonexperimental_plugins", None)
                if plugins is not None:
                    plugins.add(name)
        plugins = getattr(xla_bridge, "_nonexperimental_plugins", None)
        if plugins is not None:
            plugins.add("tpu")
    except ImportError:
        pass


class StubStreamTokenizer:
    """Minimal stream-decoder tokenizer for scheduler-only harnesses (the
    measurement/assertion is the scheduler loop, not BPE). EOS id =
    vocab_size, never produced, so requests run to max_tokens."""

    class _Vocab:  # TokenizerChatStops renders eos pieces from .vocab
        def __getitem__(self, i) -> bytes:
            return b"</s>"

    def __init__(self, vocab_size: int = 64, prompt_tokens: int = 8):
        self.vocab_size = vocab_size
        self.prompt_tokens = prompt_tokens
        self.eos_token_ids = [vocab_size]
        self.chat_template = None
        self.bos_id = 1
        self.vocab = self._Vocab()

    def encode(self, text, add_bos=True, add_special_tokens=True):
        n = max(1, min(len(text), self.prompt_tokens))
        return [(7 + i) % self.vocab_size for i in range(n)]

    def make_stream_decoder(self):
        return self

    def decode(self, token):  # stream-decoder protocol
        return "x"


class ByteJsonTokenizer(StubStreamTokenizer):
    """Byte-level tokenizer for grammar-constrained harnesses: id 0 =
    BOS (special), ids 1..256 = the raw bytes 0..255, id 257 = EOS —
    every byte is a token, so the grammar automaton's token closure is
    the character machine itself and constrained mock streams decode to
    REAL text the tests can ``json.loads``. ``token_table()`` feeds
    ``engine.grammar_init`` (None for the specials, bytes elsewhere)."""

    def __init__(self):
        super().__init__(vocab_size=258)
        self.eos_token_ids = [257]
        self.bos_id = 0
        # a recognizable template marker so ApiServer's chat route works
        # against this tokenizer (rendered text is plain bytes anyway)
        self.chat_template = "[INST]"

    def token_table(self):
        return [None] + [bytes([i]) for i in range(256)] + [None]

    def encode(self, text, add_bos=True, add_special_tokens=True):
        data = text.encode("utf-8", errors="replace") or b"?"
        out = [0] if add_bos else []
        return out + [1 + b for b in data]

    def decode(self, token):  # stream-decoder protocol
        # BOS/EOS yield nothing; ids past the byte range (model vocab
        # padding an UNCONSTRAINED lane can sample) render as nothing
        # too — only grammar-masked lanes are guaranteed in-range
        if not 1 <= int(token) <= 256:
            return None
        # latin-1 keeps the byte value verbatim, so the concatenated
        # stream text reconstructs the constrained byte stream exactly
        return bytes([int(token) - 1]).decode("latin-1")


class CharStreamTokenizer(StubStreamTokenizer):
    """Char-level, prompt-DEPENDENT encoding for prefix-sharing
    harnesses: shared text prefixes become shared token prefixes exactly
    as long as they are (the base stub maps every prompt to the same
    tokens, which would make any prefix probe a trivial full-prompt
    hit). One home shared by tests/test_prefix_cache.py and bench.py's
    serving_prefix phase, so the encoding the byte-identity tests pin
    and the encoding the bench measures cannot drift. ``max_chars``
    caps the prompt length in tokens (None = unbounded)."""

    def __init__(self, vocab_size: int = 64, max_chars: int | None = None):
        super().__init__(vocab_size)
        self.max_chars = max_chars

    def encode(self, text, add_bos=True, add_special_tokens=True):
        if self.max_chars is not None:
            text = text[: self.max_chars]
        return [2 + ord(c) % (self.vocab_size - 2) for c in text]


class MockAsyncEngine:
    """Engine stub modelling an ASYNC device for scheduler pipeline tests
    and the bench microbench: dispatch is free and advances a simulated
    device busy-until timeline, consume blocks until the simulated step
    completes. The scheduler's pipelined loop runs against it unmodified,
    so the ``events`` log proves the lag structure (consume of step k runs
    while step k+1 is already dispatched) without accelerator timing noise.
    One implementation, imported by both tests/test_pipelined_decode.py and
    bench.py, so the pinned test and the bench evidence cannot drift.

    Tokens are a pure function of (lane, position) — NOT of global step
    order — so the synchronous scheduler and the pipelined/fused one emit
    byte-identical streams for the same requests regardless of how
    admissions interleave: the property the fused-prefill churn tests pin.
    Supports the fused prefill+decode dispatch (``decode_prefill_fused``)
    with the real engine's packed-readback contract (an extra boundary
    column on fused steps).

    Carries the real engine's fault-injection hooks (utils/faults.py:
    ``engine.dispatch`` / ``engine.consume``) and its ``pipeline_abort``
    containment primitive, so the chaos suite (tests/test_failures.py)
    drives the supervised scheduler loop through deterministic failures
    without accelerator timing noise."""

    supports_multi_step = False
    supports_speculative = False
    supports_pipelined = True
    supports_fused_prefill = True
    supports_spec_pipelined = False
    SPEC_DRAFT = 3

    def __init__(self, n_lanes=4, vocab=64, seq_len=4096, step_s=0.002,
                 pipeline_depth=2, max_chunk=16, speculative=False,
                 content_keyed=False, paged=False, kv_page_size=16,
                 kv_pool_pages=None, kv_max_parked=8, kv_host_bytes=0):
        """``speculative=True`` opts this instance into the speculative
        families (``decode_spec`` + the in-chain
        ``decode_spec_pipelined`` / ``decode_spec_prefill_fused``),
        mirroring the real engine's verify semantics over the
        deterministic f(lane, pos) token function — drafts genuinely
        accept whenever the scheduler's n-gram index predicts the
        stream's own periodicity, so zero-flush speculation is testable
        without accelerator noise. Off by default: pre-existing mock
        tests pin non-speculative behavior.

        ``content_keyed=True`` makes tokens a pure function of
        (PROMPT CONTENT, position) instead of (lane, position): each
        prefill folds its chunk into a per-lane stream key, so the same
        request produces the same stream regardless of which lane it
        lands on. That is the real engine's replay-determinism class
        (sampling is per (seed, pos), greedy is per (model, prompt) —
        never per lane), which the crash-recovery chaos tests pin: a
        recovered request re-admitted onto a DIFFERENT lane must still
        regenerate byte-identically.

        ``paged=True`` mirrors the real engine's paged-KV contract
        (runtime/kvpool.py — a pure-host module, so no jax is needed):
        ``kvpool`` + ``paged_admit``/``paged_commit``/``paged_finish``/
        ``paged_reset``/``pool_stats`` drive the REAL pool bookkeeping
        (free list, refcounts, prefix tree, parking, exhaustion sheds)
        and maintain the host page-table mirror; the only thing mocked
        is the device half (table writes land in a numpy array, COW
        copies just count). Combined with ``content_keyed``, a shared
        prefix served by refcount reproduces the stream prefilling it
        would have produced: ``paged_admit`` folds the SKIPPED prefix's
        content into the lane stream key, so scheduler-level
        oversubscription tests assert byte-identity without a backend."""
        import numpy as np
        import types

        from ..runtime.engine import EngineStats

        self.n_lanes = n_lanes
        self.config = types.SimpleNamespace(seq_len=seq_len, vocab_size=vocab)
        self.stats = EngineStats()
        self.pipeline_depth = pipeline_depth
        self.step_s = step_s
        self._max_chunk = max_chunk
        self.supports_speculative = speculative
        self.supports_spec_pipelined = speculative
        self._content_keyed = content_keyed
        self._lane_key = np.zeros(n_lanes, np.int64)
        self._free_at = 0.0  # simulated device busy-until timestamp
        # (ready_at, dispatched_at, step_idx, kind, payload): payload is
        # (toks, boundary|None) for "tok" steps, (emitted, n_emit) for
        # "spec" steps — computed AT DISPATCH (the sim is deterministic),
        # returned at consume like the real engine's lagged readback
        self._ring = []
        self._carry_live = False
        # simulated device carry: each lane's next feed token + write
        # position (the real engine's _pl_carry/_pl_carry_pos); a host
        # position >= 0 overrides, -1 reads the carry — same contract.
        # _sim_g is the grammar-state carry (absolute slab id, 0 = FREE)
        self._sim_tok = np.zeros(n_lanes, np.int64)
        self._sim_pos = np.zeros(n_lanes, np.int64)
        self._sim_g = np.zeros(n_lanes, np.int64)
        # grammar-constrained decoding: the REAL slab + compiler (pure
        # numpy — no jax needed); the mocked device half is the masked
        # token choice in _tok_g
        self.grammar_slab = None
        self._g_vocab = None
        self._g_eos = ()
        self._steps = 0
        self.events = []  # ("dispatch"|"consume", step_idx)
        # paged KV mirror (the real engine's host half, device half mocked)
        self.kvpool = None
        if paged:
            from ..runtime.kvpool import KVPagePool

            # the REAL engine's construction recipe (validation, shrink,
            # footprint default) — shared classmethod, so the mock's
            # pool geometry provably cannot drift from the engine's
            self.kvpool = KVPagePool.for_seq_len(
                seq_len, n_lanes, page_size=kv_page_size,
                pool_pages=kv_pool_pages, max_parked=kv_max_parked,
                host_bytes=kv_host_bytes,
            )
            self._host_tables = np.asarray(
                [self.kvpool.table_row([])] * n_lanes, np.int32
            )
            self.page_copies_applied = 0  # the mocked device COW half
            # tiered residency (host swap tier): the engine's traffic
            # counters, fed by the mocked device halves below
            self.swap_ins = 0
            self.swap_outs = 0
            self.swap_in_bytes = 0
            self.swap_out_bytes = 0
            self.swap_in_ms = 0.0
            # disagg transfer mock: imported payloads keyed by page, each
            # pinned to the tree node it was imported FOR (a reused page
            # re-registered with different content falls back to the
            # canonical derivation instead of replaying stale bytes)
            self._page_payloads = {}
            self.pages_imported = 0

    def max_chunk(self):
        return self._max_chunk

    # -- grammar-constrained decoding (grammar/; REAL slab + compiler) -----

    @property
    def supports_grammar(self):
        return self._g_vocab is not None

    def grammar_init(self, token_table, eos_ids):
        from ..grammar.slab import GrammarSlab

        table = list(token_table)[: self.config.vocab_size]
        table += [None] * (self.config.vocab_size - len(table))
        self._g_vocab = table
        self._g_eos = tuple(int(e) for e in eos_ids)
        self.grammar_slab = GrammarSlab(self.config.vocab_size)

    def grammar_attach(self, rf):
        if self._g_vocab is None:
            raise ValueError(
                "structured output is disabled on this engine "
                "(--grammar off, or no tokenizer vocab registered)"
            )
        from ..grammar.automaton import compile_automaton

        auto = compile_automaton(rf, self._g_vocab, self._g_eos)
        handle = self.grammar_slab.attach(auto)
        with self.stats.lock:
            self.stats.grammar_lanes += 1
        return handle

    def grammar_detach(self, key):
        self.grammar_slab.detach(key)

    def grammar_stats(self):
        return (
            self.grammar_slab.stats() if self.grammar_slab is not None
            else {}
        )

    def _g_next_abs(self, g, tok):
        """Absolute-state transition (the device rule's mock twin)."""
        if g <= 0 or self.grammar_slab is None:
            return 0
        got = self.grammar_slab.resolve(int(g))
        if got is None:
            return 0
        auto, base = got
        return base + auto.next_state(int(g) - base, int(tok))

    def _tok_g(self, lane, pos, g):
        """The masked token choice: the deterministic base token function
        picks WHICH legal token (mod the legal count), so constrained
        streams stay pure functions of (content key, position) — the
        replay-determinism class — while always being grammar-legal
        (the real engine's masked-argmax analogue)."""
        t = self._tok(lane, pos)
        if g is None or g <= 0 or self.grammar_slab is None:
            return t
        got = self.grammar_slab.resolve(int(g))
        if got is None:
            return t
        auto, base = got
        legal = auto.legal_ids(int(g) - base)
        # choose among the LOWEST legal ids (structural bytes sort low):
        # a real model's masked argmax terminates values promptly; an
        # unbiased pick over ~250 legal string bytes would close a quote
        # once per ~250 tokens and every mock stream would hit max_tokens.
        # The index mixes (t, pos) NON-linearly: the raw token function is
        # linear in pos mod 256, and a linear pick resonates with
        # multi-token loop bodies (an array that never draws ']' runs to
        # max_tokens deterministically).
        cap = min(len(legal), 12)
        h = (t * 2654435761 + int(pos) * 0x9E3779B1) & 0xFFFFFFFF
        h ^= h >> 13
        return int(legal[h % cap])

    def _eff_g(self, g_states, reseed=False):
        """The grammar-state select: None defaults like the real engine
        (FREE on reseed, carry otherwise); -1 reads the simulated carry,
        >= 0 overrides."""
        n = self.n_lanes
        if g_states is None:
            if reseed:
                return [0] * n
            return [int(x) for x in self._sim_g]
        return [
            int(self._sim_g[i]) if int(g) < 0 else int(g)
            for i, g in enumerate(g_states)
        ]

    # -- paged KV (runtime/kvpool.py contract; device half mocked) ---------

    def paged_admit(self, lane, tokens, reserve_tokens,
                    min_share_tokens=1):
        """The real engine's paged admission over the REAL pool
        bookkeeping; raises the real :class:`~..runtime.kvpool.PoolExhausted`.
        The device half is a numpy table write + a COW counter bump; the
        tiered-residency ordering matches the engine's (drain staged
        swap-outs, apply host-tier swap-ins, then the table write)."""
        start, blocks, copies, swapins = self.kvpool.admit(
            lane, list(tokens), reserve_tokens, min_share_tokens
        )
        self.drain_kv_swapouts()
        if swapins:
            self.swap_in_pages([p for p, _ in swapins],
                               [b for _, b in swapins])
        self._host_tables[int(lane)] = self.kvpool.table_row(blocks)
        self.page_copies_applied += len(copies)
        if self._content_keyed and start > 0:
            # the shared prefix's KV is resident: fold its CONTENT into
            # the lane stream key exactly as prefilling it would have, so
            # a refcount-served prefix and a prefilled one are stream-
            # indistinguishable (the byte-identity property under test)
            self._lane_key[int(lane)] = 0
            self._feed_key(lane, list(tokens[:start]), 0)
        return start

    def _paged_table_row(self, blocks):
        """The pod control plane's table-row hook (mirrors the real
        engine): the pool's shared encoding as the int32 wire dtype."""
        import numpy as np
        return np.asarray(self.kvpool.table_row(list(blocks)), np.int32)

    def apply_paged_admit(self, lane, row, copies):
        """Device half of a pod admission replay on the mock: land the
        table row and apply COW copies to the payload shadow."""
        for src, dst in copies:
            got = self._page_payloads.get(int(src))
            if got is not None:
                self._page_payloads[int(dst)] = got
        self._host_tables[int(lane)] = row
        self.page_copies_applied += len(copies)

    def paged_commit(self, lane, tokens):
        self.kvpool.commit(lane, list(tokens))

    def paged_finish(self, lane, park=True):
        held = self.kvpool.finish(lane, park=park)
        self.drain_kv_swapouts()
        if held:
            self._host_tables[int(lane)] = self.kvpool.table_row([])

    def paged_reset(self):
        self.kvpool.reset()
        self._host_tables[:] = self.kvpool.table_row([])

    def pool_stats(self):
        if self.kvpool is None:
            return {}
        stats = self.kvpool.stats()
        stats["swap_ins"] = int(self.swap_ins)
        stats["swap_outs"] = int(self.swap_outs)
        stats["swap_in_bytes"] = int(self.swap_in_bytes)
        stats["swap_out_bytes"] = int(self.swap_out_bytes)
        stats["swap_in_ms"] = round(float(self.swap_in_ms), 3)
        return stats

    # -- host swap tier (runtime/engine.py contract; device half mocked) ---

    def drain_kv_swapouts(self):
        """Mocked device half of a swap-out drain: the 'device read' is
        the content-canonical payload rule shared with export_kv_page —
        imported bytes replay if the page still backs the staged node,
        otherwise the payload is the pure function of the node key. Same
        pool-side bookkeeping (take_pending_swapouts -> tier.put) as the
        real engine, so leak witnesses and tier stats are exercised."""
        import hashlib

        if self.kvpool is None or not self.kvpool.host_tier.enabled:
            return 0
        pending = self.kvpool.take_pending_swapouts()
        stored = 0
        for node_key, blk_tokens, page in pending:
            got = self._page_payloads.get(int(page))
            if got is not None and got[0] == node_key:
                payload = got[1]
            else:
                payload = hashlib.sha256(
                    repr(node_key).encode("utf-8")
                ).digest() * 2
            if self.kvpool.host_tier.put(node_key, blk_tokens, payload):
                stored += 1
            self.swap_outs += 1
            self.swap_out_bytes += len(payload)
        return stored

    def swap_in_pages(self, pages, payloads):
        """Mocked device half of a batched host->device swap-in: record
        each payload against the node its page now backs (admit()
        registered the chain just before this call), so a later export
        or re-swap-out round-trips the exact bytes."""
        if self.kvpool is None:
            raise RuntimeError("swap_in_pages needs a paged engine")
        if len(pages) != len(payloads):
            raise ValueError(
                f"swap_in_pages: {len(pages)} pages vs "
                f"{len(payloads)} payloads"
            )
        for page, payload in zip(pages, payloads):
            self._page_payloads[int(page)] = (
                self.kvpool.page_key(int(page)), bytes(payload)
            )
            self.swap_ins += 1
            self.swap_in_bytes += len(payload)

    def swap_out_parked(self):
        """Evict every parked chain into the host tier (bench lever)."""
        if self.kvpool is None:
            return 0
        n = self.kvpool.swap_out_parked()
        self.drain_kv_swapouts()
        return n

    def reset_swap_stats(self):
        self.swap_ins = 0
        self.swap_outs = 0
        self.swap_in_bytes = 0
        self.swap_out_bytes = 0
        self.swap_in_ms = 0.0

    def _page_leaf_geometry(self):
        """One page's K (or V) leaf geometry under the mock's content-
        canonical payload convention: each half is the 32-byte sha256
        digest, so every canonical payload is exactly 2 * half — the
        same contract RootControlEngine's pre-broadcast validation
        checks on the real engine."""
        import numpy as np

        return (8,), np.dtype(np.float32)

    def export_kv_page(self, page):
        """The real engine's disagg export, mocked content-canonically:
        a committed page's payload is a pure function of its block-
        content chain (sha256 of the tree node key), so two replicas
        that committed the same prefix export IDENTICAL bytes and the
        kvtransfer integrity hashes are genuinely exercised end to end.
        Imported pages replay the imported bytes (round-trip fidelity),
        as long as the page still backs the node it was imported for."""
        import hashlib

        if self.kvpool is None:
            raise RuntimeError("export_kv_page needs a paged engine")
        key = self.kvpool.page_key(int(page))
        if key is None:
            raise ValueError(
                f"page {int(page)} backs no committed block — only "
                "immutable full blocks cross replicas"
            )
        got = self._page_payloads.get(int(page))
        if got is not None and got[0] == key:
            return got[1]
        return hashlib.sha256(repr(key).encode("utf-8")).digest() * 2

    def import_kv_page(self, page, payload):
        """Mocked device half of a page import: record the bytes against
        the node the page currently backs (adopt() registered it just
        before this call — the same ordering the real engine gets from
        the donated cache pytree)."""
        if self.kvpool is None:
            raise RuntimeError("import_kv_page needs a paged engine")
        self._page_payloads[int(page)] = (
            self.kvpool.page_key(int(page)), bytes(payload)
        )
        self.pages_imported += 1

    def reset_lane(self, lane):
        pass

    def _tok(self, lane, pos):
        # deterministic per (lane, position) — or per (prompt-content
        # key, position) in content_keyed mode: stream identity across
        # scheduler paths / lane placements is checkable by equality.
        # The keyed multiplier is 13, coprime to every small even
        # vocab-2 modulus (31 shares a factor with the default 62 and
        # would collapse the key to its parity).
        if self._content_keyed:
            key = int(self._lane_key[int(lane)])
            return 2 + (key * 13 + int(pos) * 7) % (self.config.vocab_size - 2)
        return 2 + (int(lane) * 31 + int(pos) * 7) % (self.config.vocab_size - 2)

    def _feed_key(self, lane, chunk, start_pos):
        """content_keyed mode: fold a prefill chunk into the lane's
        stream key (reset at a fresh prompt's first chunk), so the token
        function depends on WHAT was prefilled, not WHERE."""
        if not self._content_keyed:
            return
        k = 0 if start_pos == 0 else int(self._lane_key[int(lane)])
        for t in chunk:
            k = (k * 1000003 + int(t) + 1) & 0xFFFFFFFF
        self._lane_key[int(lane)] = k

    def prefill_chunk(self, lane, chunk, start_pos, temp=0.0, topp=0.9,
                      seed=0, g_state=0):
        from . import faults

        faults.fire("engine.dispatch")
        self._feed_key(lane, chunk, start_pos)
        # boundary token under the automaton's start-state mask (the
        # real engine's _prefill_half rule; g_state 0 = identity)
        t = self._tok_g(lane, start_pos + len(chunk) - 1, g_state)
        with self.stats.lock:
            self.stats.prefill_tokens += len(chunk)
        return None, t, t

    def _toks_at(self, positions, g_states=None):
        import numpy as np

        return np.asarray(
            [
                self._tok_g(
                    i, positions[i],
                    0 if g_states is None else int(g_states[i]),
                )
                for i in range(self.n_lanes)
            ],
            np.int32,
        )

    def decode(self, tokens, positions, temps=None, topps=None, seeds=None,
               want_logits=True, g_states=None):
        from . import faults

        faults.fire("engine.dispatch")
        # synchronous fallback (admission iterations): dispatch + block
        now = time.monotonic()
        self._free_at = max(now, self._free_at) + self.step_s
        time.sleep(max(0.0, self._free_at - now))
        self._steps += 1
        with self.stats.lock:
            self.stats.decode_steps += 1
        t = self._toks_at(positions, g_states)
        return None, t, t

    def decode_spec(self, tokens, drafts, draft_len, positions, temps=None,
                    topps=None, seeds=None, g_states=None):
        """Synchronous speculative verify over the deterministic token
        function: the real engine's acceptance rule (longest draft prefix
        matching the model's own continuation) with greedy_j =
        f(lane, pos + j) — masked per position for constrained lanes."""
        import numpy as np

        from . import faults

        faults.fire("engine.dispatch")
        now = time.monotonic()
        self._free_at = max(now, self._free_at) + self.step_s
        time.sleep(max(0.0, self._free_at - now))
        self._steps += 1
        emitted, n_emit, _ = self._verify(
            np.asarray(tokens), np.asarray(drafts), np.asarray(draft_len),
            np.asarray(positions),
            None if g_states is None else [int(g) for g in g_states],
        )
        with self.stats.lock:
            self.stats.decode_steps += 1
            self.stats.spec_steps += 1
        return None, emitted, n_emit

    def _verify(self, tokens, drafts, draft_len, positions, g0=None):
        """The acceptance math shared by the sync and in-chain verify
        mocks. drafts here are the K continuation candidates (the real
        ``decode_spec`` layout). The grammar state walks the window
        exactly like the real verify core: each position's greedy is the
        MASKED choice under the state reached by the accepted prefix.
        Returns (emitted, n_emit, g_final) with g_final the per-lane
        state after the last emitted token."""
        import numpy as np

        n = self.n_lanes
        k = drafts.shape[1]
        emitted = np.zeros((n, k + 1), np.int64)
        n_emit = np.ones(n, np.int64)
        g_final = np.zeros(n, np.int64)
        seq_len = self.config.seq_len
        for i in range(n):
            pos = int(positions[i])
            g = 0 if g0 is None else int(g0[i])
            dlen = min(int(draft_len[i]), max(0, seq_len - pos - 1), k)
            j = 0
            while True:
                t = self._tok_g(i, pos + j, g)
                emitted[i, j] = t
                g = self._g_next_abs(g, t)
                if j < dlen and int(drafts[i, j]) == t:
                    j += 1
                    continue
                break
            n_emit[i] = j + 1
            g_final[i] = g
        return emitted, n_emit, g_final

    def pipeline_inflight(self):
        return len(self._ring)

    @property
    def pipeline_active(self):
        return bool(self._ring) or self._carry_live

    def _eff_positions(self, positions):
        """The carried-position select: -1 reads the simulated device
        carry, >= 0 overrides from host metadata."""
        return [
            int(self._sim_pos[i]) if int(p) < 0 else int(p)
            for i, p in enumerate(positions)
        ]

    def _push(self, kind, payload):
        now = time.monotonic()
        self._free_at = max(now, self._free_at) + self.step_s
        s = self._steps
        self._steps += 1
        self._ring.append((self._free_at, now, s, kind, payload))
        self._carry_live = True
        self.events.append(("dispatch", s))
        with self.stats.lock:
            self.stats.pipeline_dispatches += 1
            d = len(self._ring)
            self.stats.pipeline_depth_hist[d] = (
                self.stats.pipeline_depth_hist.get(d, 0) + 1
            )

    def decode_pipelined(self, positions, temps=None, topps=None, seeds=None,
                         tokens=None, g_states=None):
        from . import faults

        faults.fire("engine.dispatch")
        eff = self._eff_positions(positions)
        effg = self._eff_g(g_states, reseed=tokens is not None)
        toks = [
            self._tok_g(i, eff[i], effg[i]) for i in range(self.n_lanes)
        ]
        for i in range(self.n_lanes):
            self._sim_tok[i] = toks[i]
            self._sim_pos[i] = min(eff[i] + 1, self.config.seq_len)
            self._sim_g[i] = self._g_next_abs(effg[i], toks[i])
        self._push("tok", (toks, None))

    def decode_prefill_fused(self, positions, temps=None, topps=None,
                             seeds=None, p_lane=0, chunk=None, p_start=0,
                             p_temp=0.0, p_topp=0.9, p_seed=0, tokens=None,
                             g_states=None, p_g=0):
        """Fused prefill+decode dispatch: one simulated device step that
        both advances the decode lanes and consumes one prompt chunk; the
        packed readback carries the chunk's boundary token in an extra
        column, like the real engine's [2, n+1] pack."""
        from . import faults

        if not chunk:
            raise ValueError("fused prefill needs a non-empty prompt chunk")
        if len(chunk) > self._max_chunk:
            raise ValueError(
                f"chunk of {len(chunk)} exceeds bucket {self._max_chunk}"
            )
        faults.fire("engine.dispatch")
        eff = self._eff_positions(positions)
        effg = self._eff_g(g_states, reseed=tokens is not None)
        toks = [
            self._tok_g(i, eff[i], effg[i]) for i in range(self.n_lanes)
        ]
        self._feed_key(p_lane, chunk, p_start)
        boundary = self._tok_g(p_lane, p_start + len(chunk) - 1, p_g)
        for i in range(self.n_lanes):
            self._sim_tok[i] = toks[i]
            self._sim_pos[i] = min(eff[i] + 1, self.config.seq_len)
            self._sim_g[i] = self._g_next_abs(effg[i], toks[i])
        # the joined lane's carry = the boundary pair (real-engine rule)
        self._sim_tok[p_lane] = boundary
        self._sim_pos[p_lane] = p_start + len(chunk)
        self._sim_g[p_lane] = self._g_next_abs(p_g, boundary)
        self._push("tok", (toks, boundary))
        with self.stats.lock:
            self.stats.fused_steps += 1
            self.stats.prefill_tokens += len(chunk)
            self.stats.fused_bucket_hist[self._max_chunk] = (
                self.stats.fused_bucket_hist.get(self._max_chunk, 0) + 1
            )

    def _spec_payload(self, positions, drafts, draft_len, tokens,
                      g_states=None):
        """The in-chain verify sim: resolve carry tok/pos/grammar-state,
        apply the candidate-0 alignment gate, run the acceptance math,
        and advance the simulated carries by the per-lane emit counts."""
        import numpy as np

        n = self.n_lanes
        eff = self._eff_positions(positions)
        effg = self._eff_g(g_states, reseed=tokens is not None)
        carry = (
            [int(t) for t in tokens] if tokens is not None
            else [int(t) for t in self._sim_tok]
        )
        k1 = np.asarray(drafts).shape[1]  # SPEC_DRAFT + 1
        if k1 != self.SPEC_DRAFT + 1:
            raise ValueError(
                f"spec drafts shape {np.asarray(drafts).shape} != "
                f"{(n, self.SPEC_DRAFT + 1)}"
            )
        eff_drafts = np.asarray(drafts)[:, 1:]
        eff_len = np.zeros(n, np.int64)
        for i in range(n):
            if int(draft_len[i]) > 0 and int(drafts[i][0]) == carry[i]:
                eff_len[i] = int(draft_len[i]) - 1
        emitted, n_emit, g_final = self._verify(
            np.asarray(carry), eff_drafts, eff_len, np.asarray(eff), effg,
        )
        for i in range(n):
            cnt = int(n_emit[i])
            self._sim_tok[i] = int(emitted[i, cnt - 1])
            self._sim_pos[i] = min(eff[i] + cnt, self.config.seq_len)
            self._sim_g[i] = int(g_final[i])
        return emitted, n_emit

    def decode_spec_pipelined(self, positions, drafts, draft_len,
                              temps=None, topps=None, seeds=None,
                              tokens=None, g_states=None):
        from . import faults

        faults.fire("engine.dispatch")
        emitted, n_emit = self._spec_payload(
            positions, drafts, draft_len, tokens, g_states
        )
        self._push("spec", (emitted, n_emit))
        with self.stats.lock:
            self.stats.spec_steps += 1
            self.stats.spec_pipelined_steps += 1

    def decode_spec_prefill_fused(self, positions, drafts, draft_len,
                                  temps=None, topps=None, seeds=None,
                                  p_lane=0, chunk=None, p_start=0,
                                  p_temp=0.0, p_topp=0.9, p_seed=0,
                                  tokens=None, g_states=None, p_g=0):
        """An admitting chunk and a spec verify sharing one dispatch —
        the readback appends the boundary pair as an extra ROW
        (emitted[-1, :2]), the real engine's spec-pack layout."""
        import numpy as np

        from . import faults

        if not chunk:
            raise ValueError("fused prefill needs a non-empty prompt chunk")
        if len(chunk) > self._max_chunk:
            raise ValueError(
                f"chunk of {len(chunk)} exceeds bucket {self._max_chunk}"
            )
        faults.fire("engine.dispatch")
        emitted, n_emit = self._spec_payload(
            positions, drafts, draft_len, tokens, g_states
        )
        self._feed_key(p_lane, chunk, p_start)
        boundary = self._tok_g(p_lane, p_start + len(chunk) - 1, p_g)
        self._sim_tok[p_lane] = boundary
        self._sim_pos[p_lane] = p_start + len(chunk)
        self._sim_g[p_lane] = self._g_next_abs(p_g, boundary)
        brow = np.zeros((1, emitted.shape[1]), np.int64)
        brow[0, 0] = brow[0, 1] = boundary
        emitted = np.concatenate([emitted, brow])
        n_emit = np.concatenate([n_emit, np.ones(1, np.int64)])
        self._push("spec", (emitted, n_emit))
        with self.stats.lock:
            self.stats.spec_steps += 1
            self.stats.spec_pipelined_steps += 1
            self.stats.fused_steps += 1
            self.stats.prefill_tokens += len(chunk)
            self.stats.fused_bucket_hist[self._max_chunk] = (
                self.stats.fused_bucket_hist.get(self._max_chunk, 0) + 1
            )

    def pipeline_consume(self):
        import numpy as np

        from . import faults

        faults.fire("engine.consume")
        ready_at, dispatched_at, s, kind, payload = self._ring.pop(0)
        t0 = time.monotonic()
        time.sleep(max(0.0, ready_at - t0))
        self.events.append(("consume", s))
        with self.stats.lock:
            self.stats.decode_steps += 1
            self.stats.decode_s += max(0.0, ready_at - t0)
            self.stats.overlap_s += max(0.0, t0 - dispatched_at)
        if kind == "spec":
            emitted, n_emit = payload
            return emitted, n_emit
        toks, boundary = payload
        t = np.asarray(toks, np.int32)
        if boundary is not None:
            t = np.concatenate([t, np.asarray([boundary], np.int32)])
        return t, t

    def pipeline_flush(self, count=True):
        n = len(self._ring)
        while self._ring:
            self.pipeline_consume()
        self._carry_live = False
        if n and count:
            with self.stats.lock:
                self.stats.pipeline_flushes += 1
        return n

    def pipeline_abort(self):
        """The real engine's containment primitive: drop the ring without
        consuming (a poisoned step's readback would re-raise)."""
        n = len(self._ring)
        self._ring.clear()
        self._carry_live = False
        if n:
            with self.stats.lock:
                self.stats.pipeline_flushes += 1
        return n

    def count_overlapped_consumes(self):
        """(consumed steps, consumes of step k that happened after step k+1
        was already dispatched) — the one-step-lag evidence."""
        seen = set()
        consumed = overlapped = 0
        for kind, s in self.events:
            if kind == "dispatch":
                seen.add(s)
            else:
                consumed += 1
                if s + 1 in seen:
                    overlapped += 1
        return consumed, overlapped


def greedy_rollout(engine, prompt, n):
    """Plain greedy decode of n tokens on lane 0 (other lanes idle);
    returns (produced tokens, final position). Shared by the speculative-
    decoding tests and the multichip dryrun's on-mesh acceptance check."""
    import numpy as np

    _, g, pos = engine.prefill(0, prompt)
    toks = [int(g)]
    tokens = np.zeros(engine.n_lanes, np.int32)
    positions = np.zeros(engine.n_lanes, np.int32)
    for _ in range(n - 1):
        tokens[0], positions[0] = toks[-1], pos
        _, greedy, _ = engine.decode(tokens, positions)
        toks.append(int(greedy[0]))
        pos += 1
    return toks, pos
