"""Sequence-parallel attention vs the dense reference math (8-dev CPU mesh).

The reference has no sequence parallelism (SURVEY.md §5.7); the spec here is
self-consistency: sharded attention must reproduce the single-device dense
softmax result, including fully-masked sequence shards (short positions) and
the full-model forward must be logit-identical with and without sp.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
from distributed_llama_multiusers_tpu.parallel.ring_attention import (
    ring_attention,
    sp_attention,
)


def _dense_reference(q, k, v, mask, scale):
    scores = jnp.einsum("btkgh,bskh->btkgs", q * scale, k)
    scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("btkgs,bskh->btkgh", probs, v)


@pytest.fixture(scope="module")
def mesh222():
    return make_mesh(MeshPlan(dp=2, tp=2, sp=2))


@pytest.mark.parametrize("pos", [0, 3, 15, 31])
def test_sp_attention_matches_dense(mesh222, pos):
    """Decode-style: T=1 queries at various positions, incl. positions that
    leave whole sp shards fully masked (pos < S/sp)."""
    rng = np.random.default_rng(pos)
    b, t, s, n_kv, g, hd = 4, 1, 32, 2, 2, 8
    q = jnp.asarray(rng.standard_normal((b, t, n_kv, g, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, n_kv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, n_kv, hd)), jnp.float32)
    positions = jnp.full((b, t), pos, jnp.int32)
    scale = 1.0 / hd**0.5

    mask = jnp.arange(s)[None, None, :] <= positions[:, :, None]
    ref = _dense_reference(q, k, v, mask, scale)
    got = sp_attention(q, k, v, positions, mesh222, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_sp_attention_per_lane_positions(mesh222):
    """Every lane at a different position (continuous batching)."""
    rng = np.random.default_rng(7)
    b, s, n_kv, g, hd = 4, 64, 2, 2, 8
    q = jnp.asarray(rng.standard_normal((b, 1, n_kv, g, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, n_kv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, n_kv, hd)), jnp.float32)
    positions = jnp.asarray([[0], [13], [31], [63]], jnp.int32)
    scale = 1.0 / hd**0.5

    mask = jnp.arange(s)[None, None, :] <= positions[:, :, None]
    ref = _dense_reference(q, k, v, mask, scale)
    got = sp_attention(q, k, v, positions, mesh222, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_attention_matches_causal_dense(mesh222):
    rng = np.random.default_rng(3)
    b, t, n_kv, g, hd = 4, 32, 2, 2, 8
    q = jnp.asarray(rng.standard_normal((b, t, n_kv, g, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, n_kv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, n_kv, hd)), jnp.float32)
    scale = 1.0 / hd**0.5

    causal = jnp.tril(jnp.ones((t, t), bool))[None]
    ref = _dense_reference(q, k, v, jnp.broadcast_to(causal, (b, t, t)), scale)
    got = ring_attention(q, k, v, mesh222, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_full_model_sp_logit_parity(mesh222):
    """llama_forward with sp-parallel attention == mesh-free forward."""
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
    )
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.parallel.sharding import (
        cache_shardings,
        shard_params,
    )

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=96, seq_len=32,
    )
    params = params_from_random(config, seed=11, dtype=jnp.float32)
    tokens = jnp.asarray([[5, 9, 21], [3, 1, 2], [7, 7, 7], [90, 2, 40]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2]] * 4, jnp.int32)

    cache = init_kv_cache(config, 4)
    ref_logits, _ = llama_forward(config, params, tokens, positions, cache)

    sp_params = shard_params(params, mesh222)
    cache = jax.device_put(init_kv_cache(config, 4), cache_shardings(mesh222))
    got_logits, _ = llama_forward(
        config, sp_params, tokens, positions, cache, mesh=mesh222
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), atol=2e-4, rtol=2e-4
    )


def test_engine_with_sp_mesh_matches_meshfree(mesh222):
    """InferenceEngine(mesh=...) — sp attention composed with cache donation,
    per-lane dynamic-slice prefill, and bucketing — must match the mesh-free
    engine token-for-token."""
    from distributed_llama_multiusers_tpu.models import params_from_random
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=96, seq_len=32,
    )
    params = params_from_random(config, seed=17, dtype=jnp.float32)
    prompt = [5, 9, 21, 3, 1]

    def run(engine):
        toks = []
        _, greedy, pos = engine.prefill(lane=1, tokens=prompt)
        toks.append(greedy)
        import numpy as np_

        tokens = np_.zeros(4, np_.int32)
        positions = np_.zeros(4, np_.int32)
        for _ in range(4):
            tokens[1], positions[1] = toks[-1], pos
            _, greedy, _ = engine.decode(tokens, positions)
            toks.append(int(greedy[1]))
            pos += 1
        return toks

    ref = run(InferenceEngine(config, params, n_lanes=4, prefill_buckets=(4, 8)))
    got = run(
        InferenceEngine(
            config,
            shard_params(params, mesh222),
            n_lanes=4,
            prefill_buckets=(4, 8),
            mesh=mesh222,
        )
    )
    assert ref == got, (ref, got)


def test_ring_attention_train_forward(mesh222):
    """llama_forward_train with ring attention == dense causal forward."""
    from distributed_llama_multiusers_tpu.models import (
        llama_forward_train,
        params_from_random,
    )
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=96, seq_len=32,
    )
    params = params_from_random(config, seed=13, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 96, (4, 16)), jnp.int32)

    ref = llama_forward_train(config, params, tokens)
    got = llama_forward_train(config, shard_params(params, mesh222), tokens, mesh=mesh222)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)


@pytest.mark.slow  # tier-2: heavy (xplane profiler capture); mesh decode parity stays tier-1 via test_engine_with_sp_mesh_matches_meshfree (see pyproject markers)
def test_measured_sync_stats_on_mesh(mesh222):
    """engine.measured_sync_stats profiles real decode steps and splits out
    collective time — the measured analogue of the reference's per-token
    Sync ms (src/dllama.cpp:54-64). On the virtual CPU mesh the XLA:CPU
    thunks emit op-name TraceMes, so all-reduce/all-gather time is real
    measured time, not the static HLO byte estimate."""
    from distributed_llama_multiusers_tpu.models import params_from_random
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=96, seq_len=32,
    )
    params = params_from_random(config, seed=3, dtype=jnp.float32)
    engine = InferenceEngine(
        config, shard_params(params, mesh222), n_lanes=4,
        prefill_buckets=(4,), mesh=mesh222,
    )
    m = engine.measured_sync_stats(steps=2)
    assert m["step_ms"] > 0
    if m["source"] == "wall-only":  # xplane proto unavailable on this box
        return
    assert m["device_busy_ms"] > 0
    assert m["sync_ms"] > 0, m  # tp=2 forward must psum/all-gather
    assert 0 < m["sync_frac"] <= 1, m
    assert m["sync_ms_by_kind"], m
