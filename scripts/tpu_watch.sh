#!/bin/bash
# Probe the axon TPU tunnel until it answers, then exit 0.
# Each probe is a short-lived child with a hard timeout so a hung backend
# init can't wedge the watcher. Status appended to scripts/tpu_watch.log.
LOG="$(cd "$(dirname "$0")" && pwd)/tpu_watch.log"
DEADLINE=$(( $(date +%s) + ${TPU_WATCH_MAX_S:-39600} ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout "${TPU_PROBE_TIMEOUT_S:-90}" python - <<'EOF' >>"$LOG" 2>&1
import jax, time
t0 = time.time()
d = jax.devices()
print(f"ALIVE {time.strftime('%F %T')} init={time.time()-t0:.1f}s devices={d}")
import jax.numpy as jnp
x = jnp.ones((128, 128))
(x @ x).block_until_ready()
print(f"MATMUL-OK {time.time()-t0:.1f}s")
EOF
  then
    echo "TPU ALIVE at $(date)" >>"$LOG"
    exit 0
  fi
  echo "probe dead at $(date)" >>"$LOG"
  sleep "${TPU_PROBE_INTERVAL_S:-240}"
done
echo "watcher gave up at $(date)" >>"$LOG"
exit 1
