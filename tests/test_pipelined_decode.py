"""Async decode pipeline (``engine.decode_pipelined`` + the scheduler's
dispatch/consume halves).

The serving invariant under test is STREAM IDENTITY: the pipelined path —
step k+1 dispatched from the on-device token carry while step k's host
readback runs one step behind — must emit byte-identical token streams to
the synchronous path, for greedy AND device-sampled lanes, including a
stop string that lands while steps are in flight (the junk-KV discard
rule) and a mid-stream cancel. Plus the overlap mechanics themselves,
pinned deterministically against a mocked async engine (real-engine CPU
timings are too noisy to prove a lag structure).
"""

import threading
import time

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.runtime.engine import (
    DEFAULT_PIPELINE_DEPTH,
    DEFAULT_TOPP,
    EngineStats,
    warmup_engine,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer
from distributed_llama_multiusers_tpu.utils.testing import (
    MockAsyncEngine,
    StubStreamTokenizer,
)


@pytest.fixture(scope="module")
def loaded(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    return config, params, tok


def _fresh_engine(config, params, n_lanes=2, **kw):
    return InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(4,), **kw
    )


# ---------------------------------------------------------------------------
# engine level: the device-fed chain equals single stepping
# ---------------------------------------------------------------------------


def test_engine_pipelined_matches_single_steps(loaded):
    """A pipelined chain (depth-2 ring, device token carry) emits exactly
    the tokens the synchronous decode loop would, for a greedy lane and a
    seeded device-sampled lane together — same fold_in(seed, pos) draws."""
    config, params, _ = loaded
    prompt = [5, 9, 3]
    temps = np.asarray([0.0, 0.8], np.float32)
    topps = np.full(2, DEFAULT_TOPP, np.float32)
    seeds = np.asarray([0, 123], np.uint32)

    def sync_chain(engine, n_steps):
        _, g0, pos = engine.prefill(0, prompt)
        _, g1, _ = engine.prefill(1, prompt)
        toks = np.asarray([g0, g1], np.int32)
        positions = np.asarray([pos, pos], np.int32)
        out = []
        for _ in range(n_steps):
            _, greedy, sampled = engine.decode(toks, positions, temps, topps, seeds)
            toks = np.where(temps == 0.0, greedy, sampled).astype(np.int32)
            out.append(toks.copy())
            positions = positions + 1
        return np.stack(out)

    def pipelined_chain(engine, n_steps):
        _, g0, pos = engine.prefill(0, prompt)
        _, g1, _ = engine.prefill(1, prompt)
        toks = np.asarray([g0, g1], np.int32)
        positions = np.asarray([pos, pos], np.int32)
        out = []
        first = True
        dispatched = 0
        while len(out) < n_steps:
            while dispatched - len(out) < engine.pipeline_depth and dispatched < n_steps:
                engine.decode_pipelined(
                    positions, temps, topps, seeds,
                    tokens=toks if first else None,
                )
                first = False
                dispatched += 1
                positions = positions + 1
            greedy, sampled = engine.pipeline_consume()
            out.append(np.where(temps == 0.0, greedy, sampled).astype(np.int32))
        engine.pipeline_flush()
        return np.stack(out)

    single = sync_chain(_fresh_engine(config, params), 8)
    multi = pipelined_chain(_fresh_engine(config, params), 8)
    np.testing.assert_array_equal(single, multi)


def test_engine_pipeline_ring_discipline(loaded):
    """The in-flight ring is bounded at pipeline_depth; consume without a
    dispatch and a carry-less device-fed dispatch are caller bugs; flush
    counts only when it actually discards in-flight steps."""
    config, params, _ = loaded
    engine = _fresh_engine(config, params, pipeline_depth=2)
    z = np.zeros(2, np.int32)
    with pytest.raises(RuntimeError, match="carry"):
        engine.decode_pipelined(z)  # no chain seeded yet
    with pytest.raises(RuntimeError, match="empty"):
        engine.pipeline_consume()
    engine.decode_pipelined(z, tokens=z)
    engine.decode_pipelined(z)
    assert engine.pipeline_inflight() == 2
    with pytest.raises(RuntimeError, match="ring full"):
        engine.decode_pipelined(z)
    assert engine.pipeline_active
    assert engine.pipeline_flush() == 2  # discarded two in-flight steps
    assert not engine.pipeline_active
    snap = engine.stats.snapshot()
    assert snap["pipeline_dispatches"] == 2
    assert snap["pipeline_flushes"] == 1  # the discarding flush counted
    assert snap["pipeline_depth_hist"] == {1: 1, 2: 1}
    assert engine.pipeline_flush() == 0  # nothing in flight: not a flush
    assert engine.stats.snapshot()["pipeline_flushes"] == 1


def test_decode_want_logits_gate(loaded):
    """want_logits=False (the common all-device-sampling step) returns the
    same tokens without materializing the [n, vocab] logits output."""
    config, params, _ = loaded
    e1 = _fresh_engine(config, params)
    e2 = _fresh_engine(config, params)
    z = np.zeros(2, np.int32)
    logits, g1, s1 = e1.decode(z, z)
    none, g2, s2 = e2.decode(z, z, want_logits=False)
    assert logits is not None and none is None
    np.testing.assert_array_equal(g1, g2)
    np.testing.assert_array_equal(s1, s2)


def test_default_topp_single_source(loaded):
    """Satellite: the top-p default is one constant — Request and the
    engine wrappers cannot desync."""
    assert Request(prompt="x").topp == DEFAULT_TOPP
    config, params, _ = loaded
    engine = _fresh_engine(config, params)
    # defaulted topps must equal explicitly passing DEFAULT_TOPP
    z = np.zeros(2, np.int32)
    t = np.asarray([0.0, 0.9], np.float32)
    seeds = np.asarray([1, 2], np.uint32)
    _, _, s_default = engine.decode(z, z, t, None, seeds)
    _, _, s_explicit = _fresh_engine(config, params).decode(
        z, z, t, np.full(2, DEFAULT_TOPP, np.float32), seeds
    )
    np.testing.assert_array_equal(s_default, s_explicit)


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_warmup_covers_horizon_set_and_pipeline(loaded):
    """Satellite: warmup compiles every multi-step horizon bucket the
    scheduler can pick (not just the top one) and the pipelined step, so
    none of them charges first-request latency mid-service."""
    config, params, _ = loaded
    engine = _fresh_engine(config, params)
    warmup_engine(engine, spec=True, multi_step=8)
    assert sorted(engine._decode_multi_fns) == [2, 4, 8]
    # the pipelined chain ran and was flushed back to idle, and warmup
    # left no trace in the serving counters
    assert not engine.pipeline_active
    snap = engine.stats.snapshot()
    assert snap["pipeline_dispatches"] == 0 and snap["decode_steps"] == 0


# ---------------------------------------------------------------------------
# scheduler level: stream identity pipelined vs synchronous
# ---------------------------------------------------------------------------


def _run_requests(config, params, tok, reqs, pipelined, n_lanes=2, **kw):
    engine = _fresh_engine(config, params, n_lanes=n_lanes)
    kw.setdefault("speculative", False)
    sched = ContinuousBatchingScheduler(
        engine, tok, prefix_min_tokens=0, multi_step=0,
        pipelined=pipelined, **kw,
    )
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs], engine.stats.snapshot()


def test_scheduler_pipelined_stream_identity(loaded):
    """The serving loop with pipelining produces EXACTLY the synchronous
    token streams — greedy and seeded device-sampled lanes, different
    max_tokens so one lane finishes while steps for it are still in
    flight (its junk columns are discarded)."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="hello world", max_tokens=13, temperature=0.0),
            Request(prompt="other prompt", max_tokens=24, temperature=0.8,
                    seed=42),
        ]

    base, base_stats = _run_requests(config, params, tok, reqs(), pipelined=False)
    pl, stats = _run_requests(config, params, tok, reqs(), pipelined=True)
    assert pl == base
    assert stats["pipeline_dispatches"] > 0  # the pipeline actually engaged
    assert stats["overlap_s"] > 0  # consume ran behind a live dispatch
    assert base_stats["pipeline_dispatches"] == 0
    # steady-state decode: chains ended by lane completion, never aborted
    assert stats["pipeline_flushes"] == 0


def test_scheduler_pipelined_stop_string_mid_flight(loaded):
    """An EOS (stop string) that lands while a step is in flight: the
    consume half discovers it one step late, the in-flight junk for that
    lane is discarded, and the emitted stream is byte-identical to the
    synchronous path's."""
    config, params, tok = loaded
    # probe run: derive a stop string from a MID-STREAM token piece of the
    # text an unconstrained greedy run actually produces (the detector's
    # window anchors near piece boundaries, so the stop must align to one)
    probe = Request(prompt="hello world", max_tokens=24, temperature=0.0)
    _run_requests(config, params, tok, [probe], pipelined=False)
    dec = tok.make_stream_decoder()
    pieces = [dec.decode(t) for t in probe.generated_tokens]
    stop = next(
        (p for i, p in enumerate(pieces)
         if 3 <= i <= len(pieces) - 6 and p and p.strip()),
        None,
    )
    assert stop is not None, f"no usable mid-stream piece in {pieces!r}"

    def stopped():
        return [Request(prompt="hello world", max_tokens=24, temperature=0.0,
                        stop=[stop])]

    base, base_stats = _run_requests(config, params, tok, stopped(), pipelined=False)
    pl_reqs = stopped()
    pl, stats = _run_requests(config, params, tok, pl_reqs, pipelined=True)
    assert pl == base
    assert pl_reqs[0].finish_reason == "stop"
    assert len(pl[0]) < 24  # the stop really fired
    assert stats["pipeline_dispatches"] > 0
    # the junk-KV discard path ran: the pipelined run executed MORE decode
    # steps than it emitted tokens (the in-flight step past the stop ran
    # with a junk feed and was discarded), while the sync run stepped
    # exactly once per token
    assert base_stats["decode_steps"] == len(base[0])
    assert stats["decode_steps"] > len(pl[0])


def test_scheduler_pipelined_cancel_mid_stream(loaded):
    """A cancel() while steps are in flight: the lane resolves as
    cancelled with a PREFIX of the synchronous stream, and the other lane's
    stream is untouched."""
    config, params, tok = loaded
    base, _ = _run_requests(
        config, params, tok,
        [Request(prompt="hello world", max_tokens=40, temperature=0.0),
         Request(prompt="other prompt", max_tokens=16, temperature=0.8,
                 seed=7)],
        pipelined=False,
    )

    deltas = []
    victim = Request(prompt="hello world", max_tokens=40, temperature=0.0)

    def on_delta(piece):
        deltas.append(piece)
        if len(deltas) == 3:
            victim.cancel()

    victim.on_delta = on_delta
    other = Request(prompt="other prompt", max_tokens=16, temperature=0.8,
                    seed=7)
    pl, _ = _run_requests(config, params, tok, [victim, other], pipelined=True)
    assert victim.finish_reason == "cancelled"
    assert len(pl[0]) < 40  # actually cut short
    assert pl[0] == base[0][: len(pl[0])]  # prefix of the sync stream
    assert pl[1] == base[1]  # the surviving lane is byte-identical


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_scheduler_pipelined_with_speculation(loaded):
    """speculative=True: drafts force a pipeline flush and the spec path
    runs (it wins steady-state greedy); streams still match the
    non-pipelined scheduler exactly."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="aa bb aa bb aa", max_tokens=12, temperature=0.0),
            Request(prompt="sampled one", max_tokens=8, temperature=0.8,
                    seed=123),
        ]

    base, _ = _run_requests(
        config, params, tok, reqs(), pipelined=False, speculative=True
    )
    pl, stats = _run_requests(
        config, params, tok, reqs(), pipelined=True, speculative=True
    )
    assert pl == base
    assert stats["spec_steps"] > 0  # speculation still engaged


def test_wide_nucleus_lane_rides_pipeline(loaded):
    """A wide-nucleus lane (top_p = 1.0 — the old host-exact fallback
    class) samples on device with the EXACT full-vocab sampler now, so it
    rides the pipelined chain instead of disabling it: streams identical
    to the synchronous path (same fold_in(seed, pos) draws), pipeline
    engaged, zero host_exact lanes."""
    config, params, tok = loaded

    def reqs():
        return [Request(prompt="hello", max_tokens=6, temperature=0.8,
                        topp=1.0, seed=3)]

    base, _ = _run_requests(config, params, tok, reqs(), pipelined=False)
    out, stats = _run_requests(config, params, tok, reqs(), pipelined=True)
    assert out == base  # on-device exact sampler stream either way
    assert len(out[0]) >= 1
    assert stats["pipeline_dispatches"] > 0  # the chain served it
    assert stats["pipeline_flushes"] == 0
    assert stats["host_exact_lanes"] == 0


def test_host_sampling_mode_disables_pipeline(loaded):
    """host_sampling=True (the bit-exact reference-xorshift escape hatch)
    is the ONE remaining host-exact path: it reads full logits every step,
    so the gate must keep the whole batch on the synchronous path."""
    config, params, tok = loaded

    def reqs():
        return [Request(prompt="hello", max_tokens=6, temperature=0.8,
                        topp=0.9, seed=3)]

    base, _ = _run_requests(config, params, tok, reqs(), pipelined=False,
                            host_sampling=True)
    out, stats = _run_requests(config, params, tok, reqs(), pipelined=True,
                               host_sampling=True)
    assert out == base  # bit-exact host sampler stream either way
    assert len(out[0]) >= 1
    assert stats["pipeline_dispatches"] == 0  # gate kept the sync path
    assert stats["host_exact_lanes"] == 1


def test_pipelined_overshoot_does_not_corrupt_prefix_reuse(loaded):
    """Junk-KV invariant: a lane that finished while pipelined steps were
    in flight holds junk KV past its consumed tokens; a later request
    prefix-reusing that lane must still decode the cold-prefill stream."""
    config, params, tok = loaded
    prompt = "shared prefix for reuse "

    def run(prefix_min, pipelined):
        engine = _fresh_engine(config, params, n_lanes=2)
        sched = ContinuousBatchingScheduler(
            engine, tok, speculative=False, prefix_min_tokens=prefix_min,
            multi_step=0, pipelined=pipelined,
        )
        sched.start()
        try:
            a = sched.submit(Request(prompt=prompt, max_tokens=9))
            a.future.result(timeout=300)
            b = sched.submit(Request(prompt=prompt, max_tokens=16))
            b.future.result(timeout=300)
        finally:
            sched.stop()
        assert a.error is None and b.error is None
        snap = engine.stats.snapshot()
        return list(b.generated_tokens), snap["prefix_hits"]

    cold, _ = run(prefix_min=0, pipelined=True)
    warm, hits = run(prefix_min=4, pipelined=True)
    assert hits >= 1  # the second request actually reused lane KV
    assert warm == cold


# ---------------------------------------------------------------------------
# EngineStats hygiene for the new counters
# ---------------------------------------------------------------------------


def test_stats_depth_hist_snapshot_isolation():
    s = EngineStats()
    with s.lock:
        s.pipeline_depth_hist[2] = 5
    snap = s.snapshot()
    with s.lock:
        s.pipeline_depth_hist[2] = 99
    assert snap["pipeline_depth_hist"] == {2: 5}  # copy, not alias
    reset_snap = s.reset()
    assert reset_snap.pipeline_depth_hist == {2: 99}
    with s.lock:
        assert s.pipeline_depth_hist == {}


# ---------------------------------------------------------------------------
# mocked async engine: the overlap structure itself, deterministically
# ---------------------------------------------------------------------------


def _drive(engine, reqs, **kw):
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        speculative=False, prefix_min_tokens=0, multi_step=0, **kw,
    )
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=60)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]


def test_mocked_scheduler_overlaps_consume_with_dispatch():
    """Acceptance microbench: in steady-state decode the consume of step k
    happens after step k+1 was dispatched (one-step lag) and no chain is
    ever aborted (pipeline_flushes == 0)."""
    engine = MockAsyncEngine(n_lanes=2)
    _drive(engine, [
        Request(prompt="a", max_tokens=32, temperature=0.0),
        Request(prompt="b", max_tokens=32, temperature=0.0),
    ])
    consumed, overlapped = engine.count_overlapped_consumes()
    assert consumed >= 30
    # all but the chain-final consumes ran behind a younger dispatch
    assert overlapped >= consumed - 2, engine.events
    snap = engine.stats.snapshot()
    assert snap["pipeline_flushes"] == 0
    assert snap["overlap_s"] > 0


def test_mocked_scheduler_admission_forces_flush():
    """With fused prefill OFF (the escape hatch), a queued admission
    mid-chain exits the pipelined mode (counted as a flush) and the sync
    loop admits; the chain then re-forms. The fused default keeps the
    chain intact instead — pinned in tests/test_fused_prefill.py."""
    engine = MockAsyncEngine(n_lanes=2, step_s=0.005)
    first = Request(prompt="a", max_tokens=200, temperature=0.0)
    second = Request(prompt="b", max_tokens=8, temperature=0.0)
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        speculative=False, prefix_min_tokens=0, multi_step=0,
        fused_prefill=False,
    )
    sched.start()
    try:
        sched.submit(first)
        # wait until the pipelined chain is demonstrably running
        deadline = time.monotonic() + 30
        while engine.stats.snapshot()["pipeline_dispatches"] < 4:
            assert time.monotonic() < deadline, "pipeline never engaged"
            time.sleep(0.005)
        sched.submit(second)
        second.future.result(timeout=60)
        first.future.result(timeout=60)
    finally:
        sched.stop()
    assert first.error is None and second.error is None
    assert len(second.generated_tokens) == 8
    snap = engine.stats.snapshot()
    assert snap["pipeline_flushes"] >= 1  # the admission cut a chain short


# ---------------------------------------------------------------------------
# SpecStream flush hook
# ---------------------------------------------------------------------------


def test_specstream_flushes_live_pipeline(loaded):
    """SpecStream.advance must flush a live device-fed chain before its own
    direct engine dispatch (they thread the same cache)."""
    from distributed_llama_multiusers_tpu.runtime.spec import SpecStream

    config, params, _ = loaded
    engine = _fresh_engine(config, params)
    _, g0, pos = engine.prefill(0, [5, 9, 3])
    # leave a chain active, as a buggy caller might
    z = np.zeros(2, np.int32)
    engine.decode_pipelined(z, tokens=z)
    assert engine.pipeline_active
    spec = SpecStream(engine, config, enabled=False)
    nxt, used = spec.advance(int(g0), pos)
    assert used and isinstance(nxt, int)
    assert not engine.pipeline_active  # flushed before the direct decode


# ---------------------------------------------------------------------------
# pod control plane: OP_DECODE_PIPELINED replay
# ---------------------------------------------------------------------------


def test_pod_packet_replays_decode_pipelined():
    """OP_DECODE_PIPELINED round-trips the feed flag, ring depth, and all
    operand arrays through the control-plane packet into the worker's
    pipelined engine calls — including the flush-then-reseed on a host-fed
    packet."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    calls = []

    class _Eng:
        n_lanes = 2
        SPEC_DRAFT = 3
        pipeline_depth = 2

        def __init__(self):
            self._ring = 0

        def pipeline_inflight(self):
            return self._ring

        def pipeline_consume(self):
            calls.append(("consume",))
            self._ring -= 1

        def pipeline_flush(self, count=True):
            # worker-side flushes must never count as aborts (count=False)
            assert count is False
            calls.append(("flush", self._ring))
            self._ring = 0

        def decode_pipelined(self, positions, temps=None, topps=None,
                             seeds=None, tokens=None, g_states=None):
            self._ring += 1
            calls.append((
                "dispatch",
                None if tokens is None else np.asarray(tokens).tolist(),
                np.asarray(positions).tolist(),
                np.asarray(temps).tolist(),
                np.asarray(seeds).tolist(),
            ))

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    plane = _Plane()
    temps = np.asarray([0.0, 0.8], np.float32)
    topps = np.full(2, 0.9, np.float32)
    seeds = np.asarray([1, 2], np.uint32)
    # reseed (host-fed), then two device-fed continuations, then reseed
    plane.send_decode_pipelined(
        np.asarray([7, 9], np.int32), np.asarray([3, 4], np.int32),
        temps, topps, seeds, depth=2,
    )
    for pos in ((4, 5), (5, 6)):
        plane.send_decode_pipelined(
            None, np.asarray(pos, np.int32), temps, topps, seeds, depth=2,
        )
    plane.send_decode_pipelined(
        np.asarray([1, 2], np.int32), np.asarray([0, 0], np.int32),
        temps, topps, seeds, depth=2,
    )
    # root ends the chain: workers must drain their own rings too
    plane.send_pipeline_flush()
    plane.send_stop()

    replay = iter(sent)

    class _ReplayPlane:
        def recv(self):
            return next(replay)

        def slot(self, pkt, i, n):
            return plane.slot(pkt, i, n)

    mh.worker_loop(_Eng(), _ReplayPlane())
    kinds = [c[0] for c in calls]
    # host-fed -> flush+dispatch; device-fed -> dispatch; ring at depth 2
    # before the third dispatch -> consume first; reseed -> flush again;
    # the root's chain-end flush broadcast drains the worker ring last
    assert kinds == ["flush", "dispatch", "dispatch", "consume", "dispatch",
                     "flush", "dispatch", "flush"], calls
    assert calls[-1] == ("flush", 1)  # the final dispatch was still ringed
    first = calls[1]
    assert first[1] == [7, 9] and first[2] == [3, 4] and first[4] == [1, 2]
    assert calls[2][1] is None and calls[2][2] == [4, 5]
    assert calls[-2][1] == [1, 2]  # the reseed dispatch carried host tokens


def test_pod_packet_decode_want_logits_flag():
    """The decode packet carries want_logits so every process dispatches
    the same compiled program (logits vs no-logits are different HLO)."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    seen = []

    class _Eng:
        n_lanes = 2

        def decode(self, tokens, positions, temps=None, topps=None,
                   seeds=None, want_logits=True, g_states=None):
            seen.append(want_logits)

    plane = _Plane()
    z = np.zeros(2, np.int32)
    zf = np.zeros(2, np.float32)
    plane.send_decode(z, z, zf, zf, z.view(np.uint32), want_logits=False)
    plane.send_decode(z, z, zf, zf, z.view(np.uint32), want_logits=True)
    plane.send_stop()

    replay = iter(sent)

    class _ReplayPlane:
        def recv(self):
            return next(replay)

        def slot(self, pkt, i, n):
            return plane.slot(pkt, i, n)

    mh.worker_loop(_Eng(), _ReplayPlane())
    assert seen == [False, True]
