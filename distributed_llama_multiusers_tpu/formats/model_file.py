"""The `.m` model file format — header + raw tensors in fixed order.

Format (reference src/llm.cpp:26-98, converter/writer.py:109-145):

    int32 magic = 0xA00ABCD
    int32 headerSize            # bytes of (magic, headerSize, kv...) == 8 + 8*nKv
    (int32 key, int32 value) * nKv
    raw tensor bytes...

Tensor order (src/llm.cpp:447-483):
    embedding (F32, [vocab, dim])
    per layer: q k v wo w1 w2 w3 (weightType), rms_att rms_ffn (F32, [dim])
    final: rms_final (F32, [dim]), wcls (weightType, [vocab, dim])

Matmul weights are stored row-major [d_out, d_in] (d_in contiguous), i.e. a
tensor that maps x[d_in] -> y[d_out] via y = W @ x. Q/K weights are stored
pre-permuted to the interleaved-rotary layout (converter/convert-hf.py:11-14).
"""

from __future__ import annotations

import mmap
import os
import struct
from dataclasses import dataclass, field
from typing import BinaryIO, Iterator

import numpy as np

from ..quants.codec import FloatType, tensor_bytes

MODEL_MAGIC = 0xA00ABCD

# Header keys (src/llm.hpp:8-28)
KEY_VERSION = 0
KEY_ARCH_TYPE = 1
KEY_DIM = 2
KEY_HIDDEN_DIM = 3
KEY_N_LAYERS = 4
KEY_N_HEADS = 5
KEY_N_KV_HEADS = 6
KEY_N_EXPERTS = 7
KEY_N_ACTIVE_EXPERTS = 8
KEY_VOCAB_SIZE = 9
KEY_SEQ_LEN = 10
KEY_HIDDEN_ACT = 11
KEY_ROPE_THETA = 12
KEY_WEIGHT_FLOAT_TYPE = 13
KEY_ROPE_SCALING_FACTOR = 14
KEY_ROPE_SCALING_LOW_FREQ_FACTOR = 15
KEY_ROPE_SCALING_HIGH_FREQ_FACTORY = 16
KEY_ROPE_SCALING_ORIG_MAX_SEQ_LEN = 17
KEY_ROPE_TYPE = 18
# framework extension (reference enum src/llm.hpp:8-28 stops at 18):
# nonzero = per-layer q/k/v bias vectors follow each q/k/v matmul tensor
# (Qwen2-family checkpoints). Readers of bias-free files never see the key,
# so every pre-extension .m stays byte-identical.
KEY_QKV_BIAS = 19


class ArchType:
    LLAMA = 0xABCD00


class HiddenAct:
    GELU = 0
    SILU = 1


class RopeType:
    LLAMA = 0
    FALCON = 1  # reserved in reference enum; unused
    LLAMA3_1 = 2


@dataclass
class ModelHeader:
    """Parsed .m header (mirror of LlmHeader, src/llm.hpp:39-67)."""

    version: int = 0
    arch_type: int = ArchType.LLAMA
    dim: int = 0
    hidden_dim: int = 0
    n_layers: int = 0
    n_heads: int = 0
    n_kv_heads: int = 0
    n_experts: int = 0
    n_active_experts: int = 0
    vocab_size: int = 0
    seq_len: int = 0
    orig_seq_len: int = 0
    hidden_act: int = HiddenAct.SILU
    rope_theta: float = 10000.0
    weight_type: int = -1
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    rope_type: int = RopeType.LLAMA
    qkv_bias: int = 0  # Qwen2-family q/k/v bias vectors (KEY_QKV_BIAS)
    norm_epsilon: float = 1e-5
    header_size: int = 0
    file_size: int = 0

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        return (self.dim * self.n_kv_heads) // self.n_heads

    def to_kv_pairs(self) -> list[tuple[int, int]]:
        """Serializable (key, int-value) pairs, converter order (writer.py:109-130)."""
        return [
            (KEY_VERSION, self.version),
            (KEY_ARCH_TYPE, self.arch_type),
            (KEY_HIDDEN_ACT, self.hidden_act),
            (KEY_DIM, self.dim),
            (KEY_HIDDEN_DIM, self.hidden_dim),
            (KEY_N_LAYERS, self.n_layers),
            (KEY_N_HEADS, self.n_heads),
            (KEY_N_KV_HEADS, self.n_kv_heads),
            (KEY_WEIGHT_FLOAT_TYPE, self.weight_type),
            (KEY_SEQ_LEN, self.orig_seq_len or self.seq_len),
            (KEY_VOCAB_SIZE, self.vocab_size),
            (KEY_N_EXPERTS, self.n_experts),
            (KEY_N_ACTIVE_EXPERTS, self.n_active_experts),
            (KEY_ROPE_THETA, int(self.rope_theta)),
            (KEY_ROPE_SCALING_FACTOR, int(self.rope_scaling_factor)),
            (KEY_ROPE_SCALING_LOW_FREQ_FACTOR, int(self.rope_scaling_low_freq_factor)),
            (KEY_ROPE_SCALING_HIGH_FREQ_FACTORY, int(self.rope_scaling_high_freq_factor)),
            (KEY_ROPE_SCALING_ORIG_MAX_SEQ_LEN, self.rope_scaling_orig_max_seq_len),
            (KEY_ROPE_TYPE, self.rope_type),
        ] + ([(KEY_QKV_BIAS, self.qkv_bias)] if self.qkv_bias else [])


def write_model_header(f: BinaryIO, header: ModelHeader) -> int:
    """Write magic + headerSize + KV pairs; returns bytes written."""
    data = b"".join(struct.pack("<ii", k, v) for k, v in header.to_kv_pairs())
    head = struct.pack("<i", MODEL_MAGIC)
    head += struct.pack("<i", 8 + len(data))
    f.write(head)
    f.write(data)
    return len(head) + len(data)


def load_model_header(path: str, max_seq_len: int = 0) -> ModelHeader:
    """Parse the .m KV header (src/llm.cpp:26-98). ``max_seq_len`` > 0 clamps
    seq_len the way --max-seq-len does (src/llm.cpp:89-91)."""
    h = ModelHeader()
    with open(path, "rb") as f:
        magic = struct.unpack("<i", f.read(4))[0]
        if magic in (0xABCD00, 0xABCD01):
            raise ValueError("Old model format is not supported")
        if magic != MODEL_MAGIC:
            raise ValueError(f"Unsupported magic number 0x{magic & 0xFFFFFFFF:X}")
        header_size = struct.unpack("<i", f.read(4))[0]
        n_kv = (header_size - 8) // 8
        buf = f.read(n_kv * 8)
        for i in range(n_kv):
            key, value = struct.unpack_from("<ii", buf, i * 8)
            if key == KEY_VERSION:
                h.version = value
            elif key == KEY_ARCH_TYPE:
                h.arch_type = value
            elif key == KEY_DIM:
                h.dim = value
            elif key == KEY_HIDDEN_DIM:
                h.hidden_dim = value
            elif key == KEY_N_LAYERS:
                h.n_layers = value
            elif key == KEY_N_HEADS:
                h.n_heads = value
            elif key == KEY_N_KV_HEADS:
                h.n_kv_heads = value
            elif key == KEY_N_EXPERTS:
                h.n_experts = value
            elif key == KEY_N_ACTIVE_EXPERTS:
                h.n_active_experts = value
            elif key == KEY_VOCAB_SIZE:
                h.vocab_size = value
            elif key == KEY_SEQ_LEN:
                h.seq_len = value
            elif key == KEY_HIDDEN_ACT:
                h.hidden_act = value
            elif key == KEY_ROPE_THETA:
                h.rope_theta = float(value)
            elif key == KEY_WEIGHT_FLOAT_TYPE:
                h.weight_type = value
            elif key == KEY_ROPE_SCALING_FACTOR:
                h.rope_scaling_factor = float(value)
            elif key == KEY_ROPE_SCALING_LOW_FREQ_FACTOR:
                h.rope_scaling_low_freq_factor = float(value)
            elif key == KEY_ROPE_SCALING_HIGH_FREQ_FACTORY:
                h.rope_scaling_high_freq_factor = float(value)
            elif key == KEY_ROPE_SCALING_ORIG_MAX_SEQ_LEN:
                h.rope_scaling_orig_max_seq_len = value
            elif key == KEY_ROPE_TYPE:
                h.rope_type = value
            elif key == KEY_QKV_BIAS:
                h.qkv_bias = value
            else:
                raise ValueError(f"Unsupported header key {key}")
        if h.weight_type == -1:
            raise ValueError("Model does not specify weight type")
        h.header_size = header_size
        h.orig_seq_len = h.seq_len
        if max_seq_len > 0 and h.seq_len > max_seq_len:
            h.seq_len = max_seq_len
        h.file_size = os.path.getsize(path)
    return h


@dataclass
class TensorSpec:
    """One tensor in the fixed .m walk order."""

    name: str  # reference op-name it feeds, e.g. "block_matmul_q"
    layer: int
    float_type: int
    shape: tuple[int, int]  # (d_out, d_in) for matmuls; (1, n) for vectors
    offset: int  # byte offset in file
    n_bytes: int
    expert: int = -1  # expert index for MoE tensors, -1 for dense


def model_tensor_specs(h: ModelHeader) -> list[TensorSpec]:
    """The full tensor walk of a .m file (src/llm.cpp:447-483).

    MoE models (n_experts > 0): the FFN block per layer becomes a router
    tensor `block_moe_gate` (F32, [n_experts, dim]) followed by per-expert
    w3, w1, w2 in the reference converter's expert order
    (convert-hf.py:66-73 upstream). The router tensor is a FRAMEWORK
    EXTENSION: the reference converter emits expert weights but no gate, and
    its runtime only executes dense Llama (src/llm.cpp:21-24), so no
    reference-produced MoE file was ever runnable."""
    specs: list[TensorSpec] = []
    offset = h.header_size

    def add(name: str, layer: int, ftype: int, shape: tuple[int, int], expert: int = -1):
        nonlocal offset
        nb = tensor_bytes(ftype, shape[0] * shape[1])
        specs.append(TensorSpec(name, layer, ftype, shape, offset, nb, expert))
        offset += nb

    wt = h.weight_type
    dim, hidden, kv_dim, vocab = h.dim, h.hidden_dim, h.kv_dim, h.vocab_size
    add("embedding", 0, FloatType.F32, (vocab, dim))
    for l in range(h.n_layers):
        add("block_matmul_q", l, wt, (dim, dim))
        if h.qkv_bias:
            add("block_bias_q", l, FloatType.F32, (1, dim))
        add("block_matmul_k", l, wt, (kv_dim, dim))
        if h.qkv_bias:
            add("block_bias_k", l, FloatType.F32, (1, kv_dim))
        add("block_matmul_v", l, wt, (kv_dim, dim))
        if h.qkv_bias:
            add("block_bias_v", l, FloatType.F32, (1, kv_dim))
        add("block_matmul_wo", l, wt, (dim, dim))
        if h.n_experts > 0:
            add("block_moe_gate", l, FloatType.F32, (h.n_experts, dim))
            for e in range(h.n_experts):
                add("block_matmul_w3", l, wt, (hidden, dim), e)
                add("block_matmul_w1", l, wt, (hidden, dim), e)
                add("block_matmul_w2", l, wt, (dim, hidden), e)
        else:
            add("block_matmul_w1", l, wt, (hidden, dim))
            add("block_matmul_w2", l, wt, (dim, hidden))
            add("block_matmul_w3", l, wt, (hidden, dim))
        add("block_rms_norm_0", l, FloatType.F32, (1, dim))
        add("block_rms_norm_1", l, FloatType.F32, (1, dim))
    add("final_rms_norm", 0, FloatType.F32, (1, dim))
    add("final_matmul_logits", 0, wt, (vocab, dim))
    return specs


def iter_model_tensors(path: str, header: ModelHeader) -> Iterator[tuple[TensorSpec, np.ndarray]]:
    """Yield (spec, raw bytes as uint8 array) for every tensor, via mmap.

    Verifies byte-exact file consumption like src/llm.cpp:477-479.
    """
    specs = model_tensor_specs(header)
    with open(path, "rb") as f:
        # The mmap is left to the GC: yielded arrays are zero-copy views into
        # it, so an explicit close() would invalidate buffers still in use.
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        end = specs[-1].offset + specs[-1].n_bytes
        if end != header.file_size:
            raise ValueError(
                f"Missing bytes in weight file: expected {end}, file has {header.file_size}"
            )
        for spec in specs:
            raw = np.frombuffer(mm, dtype=np.uint8, count=spec.n_bytes, offset=spec.offset)
            yield spec, raw
