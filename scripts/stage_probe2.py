"""Slab-layout DMA experiments for the Q40 kernel.

stage_probe.py showed the current kernel is DMA-bound: blocks of
[chunk/2, 512] u8 over a [half, d_out] plane fetch 512-BYTE strided rows
and per-grid-step overhead dominates (~10 us/step). This probe measures
pure-DMA and full-matmul throughput when the packed plane is PRE-TILED to
[J, half, T] (one output tile = one contiguous slab) across slab sizes,
plus full-width blocks, to find the layout that saturates HBM.

Run: python scripts/stage_probe2.py [d_in] [d_out] [L]
"""

from __future__ import annotations

import sys
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from distributed_llama_multiusers_tpu.quants.packed import (  # noqa: E402
    pack_q40_host,
)
from distributed_llama_multiusers_tpu.ops.pallas_q40 import (  # noqa: E402
    _f16_bits_to_f32,
)

HBM_GB_S = 819.0
M = 8


def timeit(name, build_call, bytes_per_pass, reps=8):
    @jax.jit
    def loop(seed):
        def body(_, acc):
            t = jnp.full((1, 128), acc, jnp.float32)
            out = build_call(t)
            return out.reshape(-1)[0].astype(jnp.float32) * 1e-30

        return jax.lax.fori_loop(0, reps, body, seed)

    try:
        np.asarray(loop(jnp.float32(0)))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(loop(jnp.float32(0)))
            best = min(best, time.perf_counter() - t0)
        sec = best / reps
        gbs = bytes_per_pass / sec / 1e9
        print(f"{name:28s} {sec * 1e3:8.3f} ms  {gbs:7.1f} GB/s "
              f"({gbs / HBM_GB_S * 100:5.1f}% HBM)", flush=True)
    except Exception as e:
        print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:140]}",
              flush=True)


def main():
    d_in = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    d_out = int(sys.argv[2]) if len(sys.argv) > 2 else 14336
    L = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    half = d_in // 2
    n_blk = d_in // 32

    rng = np.random.default_rng(0)
    w = (rng.standard_normal((d_out, d_in), dtype=np.float32) * 0.05)
    p, s = pack_q40_host(w)  # [half, d_out], [n_blk, d_out] f16
    pbytes = L * p.size
    print(f"d_in={d_in} d_out={d_out} L={L} packed={pbytes / 1e6:.1f} MB "
          f"device={jax.devices()[0].device_kind}", flush=True)

    t_spec = pl.BlockSpec((1, 128), lambda *_: (0, 0))

    # ---- slab layouts: [L, J, half, T] --------------------------------------
    for T in (512, 1024, 2048):
        J = d_out // T
        pt = np.moveaxis(p.reshape(half, J, T), 1, 0)  # [J, half, T]
        slab = jnp.asarray(np.broadcast_to(pt, (L, J, half, T)))
        st = np.moveaxis(s.reshape(n_blk, J, T), 1, 0)
        slab_s = jax.lax.bitcast_convert_type(
            jnp.asarray(np.broadcast_to(st.astype(np.float16), (L, J, n_blk, T))),
            jnp.int16,
        )
        grid = (L, J)
        p_spec = pl.BlockSpec((1, 1, half, T), lambda l, j: (l, j, 0, 0))
        s_spec = pl.BlockSpec((1, 1, n_blk, T), lambda l, j: (l, j, 0, 0))
        o_spec = pl.BlockSpec((1, T), lambda l, j: (0, j))
        o_shape = jax.ShapeDtypeStruct((1, d_out), jnp.float32)
        params = pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "parallel"),
        )

        def dma_call(t, slab=slab, grid=grid, p_spec=p_spec, o_spec=o_spec,
                     o_shape=o_shape, params=params):
            def kern(t_ref, p_ref, o_ref):
                o_ref[...] = (
                    p_ref[0, 0, 0:1, :].astype(jnp.int32).astype(jnp.float32)
                    + t_ref[0, 0]
                )

            return pl.pallas_call(
                kern, grid=grid, in_specs=[t_spec, p_spec],
                out_specs=o_spec, out_shape=o_shape, compiler_params=params,
            )(t, slab)

        timeit(f"slab T={T} dma", dma_call, pbytes)

        def scale_call(t, slab=slab, slab_s=slab_s, grid=grid, p_spec=p_spec,
                       s_spec=s_spec, o_spec=o_spec, o_shape=o_shape,
                       params=params, T=T):
            def kern(t_ref, p_ref, s_ref, o_ref):
                pb = p_ref[0, 0].astype(jnp.int32)
                sb = _f16_bits_to_f32(s_ref[0, 0])[:, None, :]
                nb = pb.shape[0] // 16
                lo = (pb & 0x0F).astype(jnp.float32).reshape(nb, 16, T) * sb
                hi = (pb >> 4).astype(jnp.float32).reshape(nb, 16, T) * sb
                o_ref[...] = (
                    jnp.sum((lo + hi).reshape(nb * 16, T), axis=0,
                            keepdims=True)
                    + t_ref[0, 0]
                )

            return pl.pallas_call(
                kern, grid=grid, in_specs=[t_spec, p_spec, s_spec],
                out_specs=o_spec, out_shape=o_shape, compiler_params=params,
            )(t, slab, slab_s)

        timeit(f"slab T={T} dequant+scale", scale_call, pbytes)

        # full matmul on slab layout: two-dot, f32 planes
        xf = jnp.asarray(rng.standard_normal((M, d_in), dtype=np.float32))
        xb = xf.reshape(M, n_blk, 2, 16)
        x_lo = xb[:, :, 0, :].reshape(M, half)
        x_hi = xb[:, :, 1, :].reshape(M, half)
        x_spec = pl.BlockSpec((M, half), lambda l, j: (0, 0))
        om_spec = pl.BlockSpec((M, T), lambda l, j: (0, j))
        om_shape = jax.ShapeDtypeStruct((M, d_out), jnp.float32)

        def full_call(t, slab=slab, slab_s=slab_s, x_lo=x_lo, x_hi=x_hi,
                      grid=grid, p_spec=p_spec, s_spec=s_spec,
                      x_spec=x_spec, om_spec=om_spec, om_shape=om_shape,
                      params=params, T=T, w_dt=jnp.float32):
            def kern(t_ref, xl_ref, xh_ref, p_ref, s_ref, o_ref):
                pb = p_ref[0, 0].astype(jnp.int32)
                sb = _f16_bits_to_f32(s_ref[0, 0])[:, None, :]
                nb = pb.shape[0] // 16
                w_lo = ((pb & 0x0F).astype(jnp.float32).reshape(nb, 16, T)
                        * sb).reshape(nb * 16, T).astype(w_dt)
                w_hi = ((pb >> 4).astype(jnp.float32).reshape(nb, 16, T)
                        * sb).reshape(nb * 16, T).astype(w_dt)
                o_ref[...] = (
                    jnp.dot(xl_ref[...].astype(w_dt), w_lo,
                            preferred_element_type=jnp.float32)
                    + jnp.dot(xh_ref[...].astype(w_dt), w_hi,
                              preferred_element_type=jnp.float32)
                    + t_ref[0, 0]
                )

            return pl.pallas_call(
                kern, grid=grid,
                in_specs=[t_spec, x_spec, x_spec, p_spec, s_spec],
                out_specs=om_spec, out_shape=om_shape,
                compiler_params=params,
            )(t, x_lo, x_hi, slab, slab_s)

        timeit(f"slab T={T} full f32", full_call, pbytes)
        timeit(f"slab T={T} full bf16",
               partial(full_call, w_dt=jnp.bfloat16), pbytes)
        del slab, slab_s

    # ---- full-width blocks: [L, half, d_out], block rows x full width ------
    stacked = jnp.asarray(np.broadcast_to(p, (L, half, d_out)))
    for rows in (256, 512, 1024):
        grid = (L, half // rows)
        p_spec = pl.BlockSpec((1, rows, d_out), lambda l, k: (l, k, 0))
        o_spec = pl.BlockSpec((1, d_out), lambda l, k: (0, 0))
        o_shape = jax.ShapeDtypeStruct((1, d_out), jnp.float32)
        params = pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        )

        def dma_wide(t, stacked=stacked, grid=grid, p_spec=p_spec,
                     o_spec=o_spec, o_shape=o_shape, params=params):
            def kern(t_ref, p_ref, o_ref):
                o_ref[...] = (
                    p_ref[0, 0:1, :].astype(jnp.int32).astype(jnp.float32)
                    + t_ref[0, 0]
                )

            return pl.pallas_call(
                kern, grid=grid, in_specs=[t_spec, p_spec],
                out_specs=o_spec, out_shape=o_shape, compiler_params=params,
            )(t, stacked)

        timeit(f"wide rows={rows} dma", dma_wide, pbytes)


if __name__ == "__main__":
    main()
