"""dlint v3 (protocol / protocol-manifest / replay-determinism +
``--changed``): the wire-protocol surface model, the pinned layout
manifest, and the replay-determinism scope.

Same two-layer contract as tests/test_dlint.py: known-bad/known-good
fixture snippets regression-test each checker as a program, and
rot-guards against the REAL modules prove the checks still see the
sites they were built for (op count >= 14, send_* encoders >= 14, the
shipped manifest byte-current). Pure-stdlib imports: no jax.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import textwrap
from pathlib import Path

import pytest

from distributed_llama_multiusers_tpu.analysis import (
    PACKAGE_ROOT,
    Analyzer,
    analyze_paths,
    default_checkers,
)
from distributed_llama_multiusers_tpu.analysis.cli import (
    git_changed_files,
    main as dlint_main,
)
from distributed_llama_multiusers_tpu.analysis.determinism_check import (
    SCOPE as DET_SCOPE,
)
from distributed_llama_multiusers_tpu.analysis.protocol_check import (
    extract_protocol,
    manifest_from_model,
    render_manifest,
    write_protocol_manifest,
)

MULTIHOST = PACKAGE_ROOT / "parallel" / "multihost.py"
SHIPPED_LOCK = PACKAGE_ROOT / "analysis" / "protocol.lock"


def run_on(tmp_path: Path, files: dict[str, str], baseline: set | None = None):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    analyzer = Analyzer(default_checkers())
    return analyzer.run([tmp_path], baseline=baseline or set(), root=tmp_path)


def checks_of(findings):
    return sorted(f.check for f in findings)


def real_model():
    import ast

    return extract_protocol(ast.parse(MULTIHOST.read_text()), str(MULTIHOST))


# -- protocol: fixtures ------------------------------------------------------

# a minimal well-formed protocol file: 2 ops, each with an encoder and a
# replay arm, a validated proxy broadcast, consistent header literals
MINI_OK = """
    import numpy as np

    PROTOCOL_VERSION = 1

    OP_STOP = 0
    OP_DECODE = 1

    class ControlPlane:
        HEADER = 6
        SLOTS = 4

        def _send(self, op, lane, n, start_pos, *payloads):
            pkt = np.zeros(self._size, np.int32)
            pkt[0:6] = (MAGIC, PROTOCOL_VERSION, op, lane, n, start_pos)
            self._bcast(pkt)

        def send_stop(self):
            self._send(OP_STOP, 0, 0, 0)

        def send_decode(self, tokens, positions):
            self._send(OP_DECODE, 0, len(tokens), 0, tokens, positions)

    class RootControlEngine:
        def decode(self, tokens, positions):
            if len(tokens) != len(positions):
                raise ValueError("ragged")
            self._plane.send_decode(tokens, positions)
            return self._engine.decode(tokens, positions)

        def stop_workers(self):
            self._plane.send_stop()

    def worker_loop(engine, plane):
        while True:
            pkt = plane.recv()
            op, lane, n, start_pos = (int(x) for x in pkt[2:6])
            if op == OP_STOP:
                return
            elif op == OP_DECODE:
                engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 1, n))
"""


def write_fixture_lock(tmp_path: Path) -> Path:
    """Pin the fixture's CURRENT layout so protocol-manifest stays quiet
    in tests that target the `protocol` check."""
    return write_protocol_manifest(tmp_path / "parallel" / "multihost.py")


def test_protocol_well_formed_fixture_is_clean(tmp_path):
    findings = run_on(tmp_path, {"parallel/multihost.py": MINI_OK})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert findings == [], [f.render() for f in findings]


def test_protocol_silent_without_protocol_version(tmp_path):
    """The scope gate: protocol-shaped fixtures for OTHER checks (no
    PROTOCOL_VERSION declared) are not this check's business."""
    findings = run_on(tmp_path, {"parallel/multihost.py": """
        OP_ORPHAN = 9

        class RootControlEngine:
            def poke(self, x):
                self._plane.send_poke(x)
                return self._engine.poke(x)
    """})
    assert "protocol" not in checks_of(findings)
    assert "protocol-manifest" not in checks_of(findings)


def test_protocol_op_without_replay_arm(tmp_path):
    src = MINI_OK.replace(
        "OP_DECODE = 1",
        "OP_DECODE = 1\n\n    OP_ORPHAN = 2",
    ).replace(
        "def send_decode(self, tokens, positions):",
        "def send_orphan(self):\n"
        "            self._send(OP_ORPHAN, 0, 0, 0)\n\n"
        "        def send_decode(self, tokens, positions):",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert checks_of(findings) == ["protocol"]
    assert "no replay arm" in findings[0].message
    assert "OP_ORPHAN" in findings[0].message


def test_protocol_op_without_encoder(tmp_path):
    src = MINI_OK.replace("OP_DECODE = 1", "OP_DECODE = 1\n\n    OP_MUTE = 2") \
                 .replace(
        "            elif op == OP_DECODE:",
        "            elif op == OP_MUTE:\n"
        "                engine.mute()\n"
        "            elif op == OP_DECODE:",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert checks_of(findings) == ["protocol"]
    assert "no send_* encoder" in findings[0].message


def test_protocol_encoder_slot_overflow(tmp_path):
    """SLOTS = 4 but the encoder writes five payload slots — the packet
    is sized for SLOTS; slot 4 lands out of bounds."""
    src = MINI_OK.replace(
        "self._send(OP_DECODE, 0, len(tokens), 0, tokens, positions)",
        "self._send(OP_DECODE, 0, len(tokens), 0, tokens, positions, "
        "tokens, positions, tokens)",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert checks_of(findings) == ["protocol"]
    assert "SLOTS is 4" in findings[0].message and "slot 4" in findings[0].message


def test_protocol_arm_slot_read_overflow(tmp_path):
    src = MINI_OK.replace(
        "engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 1, n))",
        "engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 9, n))",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert checks_of(findings) == ["protocol"]
    assert "reads packet slot 9" in findings[0].message


def test_protocol_unvalidated_broadcast(tmp_path):
    """Generalizes pod-broadcast beyond raise placement: an
    operand-carrying broadcast with NO validation before it (and a
    non-self-validating encoder) flags even though nothing raises
    between send and pair."""
    src = MINI_OK.replace(
        "            if len(tokens) != len(positions):\n"
        "                raise ValueError(\"ragged\")\n"
        "            self._plane.send_decode(tokens, positions)",
        "            self._plane.send_decode(tokens, positions)",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert checks_of(findings) == ["protocol"]
    assert "no pre-broadcast validation" in findings[0].message
    assert "RootControlEngine.decode" in findings[0].message


def test_protocol_self_validating_encoder_needs_no_caller_check(tmp_path):
    """send_kv_table-style encoders raise before their own _send; the
    proxy method does not need a second validation."""
    src = MINI_OK.replace(
        "        def send_decode(self, tokens, positions):\n"
        "            self._send(OP_DECODE, 0, len(tokens), 0, tokens, positions)",
        "        def send_decode(self, tokens, positions):\n"
        "            if len(tokens) > self.chunk:\n"
        "                raise ValueError(\"payload exceeds packet slot\")\n"
        "            self._send(OP_DECODE, 0, len(tokens), 0, tokens, positions)",
    ).replace(
        "            if len(tokens) != len(positions):\n"
        "                raise ValueError(\"ragged\")\n"
        "            self._plane.send_decode(tokens, positions)",
        "            self._plane.send_decode(tokens, positions)",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert findings == [], [f.render() for f in findings]


def test_protocol_header_width_disagreement(tmp_path):
    """An np.zeros(<literal>) header builder writes 5 words; the replay
    arm re-slices 4 — the worker decodes a shifted header."""
    src = MINI_OK.replace(
        "        def send_decode(self, tokens, positions):\n"
        "            self._send(OP_DECODE, 0, len(tokens), 0, tokens, positions)",
        "        @staticmethod\n"
        "        def _hdr(a, b):\n"
        "            phdr = np.zeros(5, np.int32)\n"
        "            phdr[0] = a\n"
        "            phdr[1] = b\n"
        "            return phdr\n\n"
        "        def send_decode(self, tokens, a, b):\n"
        "            phdr = self._hdr(a, b)\n"
        "            self._send(OP_DECODE, 0, len(tokens), 0, tokens, phdr)",
    ).replace(
        "engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 1, n))",
        "engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 1, 4))",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert checks_of(findings) == ["protocol"]
    assert "header width disagreement" in findings[0].message
    assert "writes 5" in findings[0].message and "reads 4" in findings[0].message


def test_protocol_duplicate_op_value(tmp_path):
    src = MINI_OK.replace("OP_DECODE = 1", "OP_DECODE = 1\n\n    OP_CLASH = 1")
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert "protocol" in checks_of(findings)
    assert any("op value collision" in f.message for f in findings)


def test_protocol_duplicate_encoder_and_shadowed_arm(tmp_path):
    """'Exactly one' cuts both ways: a second encoder for an op and a
    second (unreachable) replay arm are both findings."""
    src = MINI_OK.replace(
        "def send_decode(self, tokens, positions):",
        "def send_decode2(self, tokens):\n"
        "            self._send(OP_DECODE, 0, len(tokens), 0, tokens)\n\n"
        "        def send_decode(self, tokens, positions):",
    ).replace(
        "            elif op == OP_DECODE:\n"
        "                engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 1, n))",
        "            elif op == OP_DECODE:\n"
        "                engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 1, n))\n"
        "            elif op == OP_DECODE:\n"
        "                engine.decode(plane.slot(pkt, 0, n), plane.slot(pkt, 1, n))",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    msgs = [f.message for f in findings]
    assert any("more than one encoder" in m for m in msgs), msgs
    assert any("duplicate replay arm" in m for m in msgs), msgs
    assert checks_of(findings) == ["protocol", "protocol"]


def test_protocol_waiver_suppresses(tmp_path):
    src = MINI_OK.replace(
        "OP_DECODE = 1",
        "OP_DECODE = 1\n\n    "
        "# dlint: ok[protocol] deliberately encoder-less fixture op\n    "
        "OP_ORPHAN = 2",
    )
    run_on(tmp_path, {"parallel/multihost.py": src})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {})
    assert findings == [], [f.render() for f in findings]


# -- protocol-manifest: the acceptance fixture -------------------------------


def test_manifest_missing_is_a_finding(tmp_path):
    findings = run_on(tmp_path, {"parallel/multihost.py": MINI_OK})
    assert checks_of(findings) == ["protocol-manifest"]
    assert "--update-protocol-manifest" in findings[0].message


def test_manifest_unreadable_is_a_finding(tmp_path):
    run_on(tmp_path, {"parallel/multihost.py": MINI_OK})
    lock = tmp_path / "analysis" / "protocol.lock"
    lock.parent.mkdir(parents=True, exist_ok=True)
    lock.write_text("{not json", encoding="utf-8")
    findings = run_on(tmp_path, {})
    assert checks_of(findings) == ["protocol-manifest"]
    assert "unreadable" in findings[0].message


def test_manifest_layout_change_without_bump_fails_with_bump_passes(tmp_path):
    """THE acceptance pin: simulate a packet-layout change (a new op +
    encoder + arm). Against the pinned manifest it FAILS without a
    PROTOCOL_VERSION bump and passes with one."""
    findings = run_on(tmp_path, {"parallel/multihost.py": MINI_OK})
    write_fixture_lock(tmp_path)
    assert run_on(tmp_path, {}) == []  # pinned layout: clean

    grown = MINI_OK.replace(
        "OP_DECODE = 1", "OP_DECODE = 1\n\n    OP_NEW = 2"
    ).replace(
        "def send_decode(self, tokens, positions):",
        "def send_new(self, xs):\n"
        "            if len(xs) > self.chunk:\n"
        "                raise ValueError(\"too big\")\n"
        "            self._send(OP_NEW, 0, len(xs), 0, xs)\n\n"
        "        def send_decode(self, tokens, positions):",
    ).replace(
        "            elif op == OP_DECODE:",
        "            elif op == OP_NEW:\n"
        "                engine.new(plane.slot(pkt, 0, n))\n"
        "            elif op == OP_DECODE:",
    )
    findings = run_on(tmp_path, {"parallel/multihost.py": grown})
    assert checks_of(findings) == ["protocol-manifest"]
    assert "without a PROTOCOL_VERSION bump" in findings[0].message
    assert "OP_NEW" in findings[0].message

    bumped = grown.replace("PROTOCOL_VERSION = 1", "PROTOCOL_VERSION = 2")
    findings = run_on(tmp_path, {"parallel/multihost.py": bumped})
    assert findings == [], [f.render() for f in findings]


def test_manifest_slots_change_without_bump_fails(tmp_path):
    run_on(tmp_path, {"parallel/multihost.py": MINI_OK})
    write_fixture_lock(tmp_path)
    findings = run_on(tmp_path, {
        "parallel/multihost.py": MINI_OK.replace("SLOTS = 4", "SLOTS = 6"),
    })
    assert checks_of(findings) == ["protocol-manifest"]
    assert "slots: 4 -> 6" in findings[0].message


# -- rot-guards against the real modules -------------------------------------


def test_real_protocol_surface_extracts_fully():
    """The real multihost.py still has the anatomy the model keys on: if
    this shrinks, the checks went blind, not green."""
    model = real_model()
    assert model is not None
    assert len(model.ops) >= 14, sorted(model.ops)
    assert len(model.encoders) >= 14, sorted(model.encoders)
    assert len(model.arms) >= 14, sorted(model.arms)
    assert model.header == 6 and model.slots is not None
    # every op encoded and replayed (the package-wide gate re-proves this
    # through the checker; here we pin the extraction itself)
    encoded = {e.op for e in model.encoders.values()}
    assert set(model.ops) <= encoded
    assert set(model.ops) <= set(model.arms)
    # the fused-prefill header width is modelled on both fused ops
    widths = manifest_from_model(model)["header_widths"]
    assert "OP_DECODE_PREFILL_FUSED" in widths
    assert "OP_DECODE_SPEC_PREFILL_FUSED" in widths


def test_real_root_sends_are_all_validated():
    """Every operand-carrying RootControlEngine broadcast has a
    pre-broadcast validation event (the four findings this PR fixed stay
    fixed)."""
    model = real_model()
    unvalidated = [
        s for s in model.root_sends if s.n_args > 0 and not s.validated
        and not model.encoders.get(s.send_name,
                                   type("E", (), {"self_validating": False})
                                   ).self_validating
    ]
    assert unvalidated == [], [(s.method, s.send_name) for s in unvalidated]


def test_shipped_manifest_is_current_and_stable(tmp_path):
    """Round-trip: regenerating the manifest from the real multihost.py
    is byte-identical to the shipped analysis/protocol.lock (a version
    bump therefore CANNOT merge without the regenerated pin), and the
    generator is deterministic."""
    assert SHIPPED_LOCK.exists()
    model = real_model()
    rendered = render_manifest(manifest_from_model(model))
    assert rendered == SHIPPED_LOCK.read_text(encoding="utf-8")
    out1 = write_protocol_manifest(MULTIHOST, tmp_path / "a.lock")
    out2 = write_protocol_manifest(MULTIHOST, tmp_path / "b.lock")
    assert out1.read_text() == out2.read_text() == rendered
    pinned = json.loads(rendered)
    assert pinned["protocol_version"] == model.version
    assert pinned["ops"]["OP_GRAMMAR"] == 13


def test_kv_pages_op_cannot_land_without_version_bump(tmp_path):
    """ISSUE 16's wire satellite: replaying the introduction of
    OP_KV_PAGES against a lock that still pins the pre-disagg layout at
    the SAME version is a finding naming the op — the new wire op
    cannot land silently. With the pinned version differing (the v4->v5
    bump that actually shipped with it), the manifest checker stands
    down: the bump IS the landing permit."""
    dst = tmp_path / "parallel" / "multihost.py"
    dst.parent.mkdir(parents=True)
    shutil.copy(MULTIHOST, dst)
    manifest = manifest_from_model(real_model())
    # rot-guard: the disagg op is part of the pinned surface
    assert manifest["ops"]["OP_KV_PAGES"] == 14
    assert manifest["encoders"]["send_kv_pages"] == "OP_KV_PAGES"

    stale = json.loads(render_manifest(manifest))
    del stale["ops"]["OP_KV_PAGES"]
    del stale["encoders"]["send_kv_pages"]
    del stale["payload_slots"]["send_kv_pages"]
    lock = tmp_path / "analysis" / "protocol.lock"
    lock.parent.mkdir(parents=True, exist_ok=True)
    lock.write_text(render_manifest(stale), encoding="utf-8")
    findings = [f for f in run_on(tmp_path, {})
                if f.check == "protocol-manifest"]
    assert len(findings) == 1, [f.render() for f in findings]
    assert "without a PROTOCOL_VERSION bump" in findings[0].message
    assert "OP_KV_PAGES" in findings[0].message

    # the sanctioned path: the pre-disagg lock pinned v4, the op landed
    # with the bump to v5 + a regenerated lock in the same diff
    stale["protocol_version"] = manifest["protocol_version"] - 1
    lock.write_text(render_manifest(stale), encoding="utf-8")
    findings = run_on(tmp_path, {})
    assert [f for f in findings if f.check == "protocol-manifest"] == []


def test_cli_update_manifest_roundtrip_relints_clean(tmp_path, capsys):
    """`dlint --update-protocol-manifest` over a copied tree reproduces
    the shipped lock, and the copied protocol file re-lints clean
    against it."""
    dst = tmp_path / "parallel" / "multihost.py"
    dst.parent.mkdir(parents=True)
    shutil.copy(MULTIHOST, dst)
    assert dlint_main(["--update-protocol-manifest", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "wrote protocol manifest" in out
    lock = tmp_path / "analysis" / "protocol.lock"
    assert lock.read_text() == SHIPPED_LOCK.read_text()
    analyzer = Analyzer(default_checkers())
    findings = analyzer.run([tmp_path], baseline=set(), root=tmp_path)
    assert findings == [], [f.render() for f in findings]


def test_cli_protocol_table(capsys):
    assert dlint_main(["--protocol-table"]) == 0
    out = capsys.readouterr().out
    assert "OP_GRAMMAR" in out and "send_grammar" in out
    assert "manifest: in sync" in out


# -- replay-determinism ------------------------------------------------------


def test_determinism_flags_entropy_in_scope(tmp_path):
    findings = run_on(tmp_path, {"serving/journal.py": """
        import random

        def fresh_ticket():
            return random.random()
    """})
    assert checks_of(findings) == ["replay-determinism"]
    assert "entropy" in findings[0].message
    assert "fresh_seed" in findings[0].message


def test_determinism_flags_unseeded_rng_seeded_is_fine(tmp_path):
    findings = run_on(tmp_path, {"fleet/migrate.py": """
        import numpy as np

        def draw(seed):
            good = np.random.default_rng(seed)
            bad = np.random.default_rng()
            return good, bad
    """})
    assert checks_of(findings) == ["replay-determinism"]
    assert "np.random.default_rng" in findings[0].message
    assert findings[0].line == 6


def test_determinism_flags_from_import_and_uuid(tmp_path):
    findings = run_on(tmp_path, {"serving/recovery.py": """
        from random import randint
        import uuid

        def ticket_id():
            return uuid.uuid4().hex
    """})
    assert checks_of(findings) == ["replay-determinism", "replay-determinism"]
    msgs = " ".join(f.message for f in findings)
    assert "from random import randint" in msgs
    assert "uuid.uuid4" in msgs


def test_determinism_dotted_import_still_resolves_entropy(tmp_path):
    """`import os.path` binds the root name `os` — os.urandom through it
    must still flag (the root->dotted alias mis-map let it escape)."""
    findings = run_on(tmp_path, {"serving/journal.py": """
        import os.path

        def salt():
            return os.urandom(4)
    """})
    assert checks_of(findings) == ["replay-determinism"]
    assert "os.urandom" in findings[0].message


def test_determinism_from_numpy_random_import_is_banned(tmp_path):
    """`from numpy.random import randint` binds a bare Name the
    attribute resolver can never see — the import line is the finding
    (seeded constructors stay importable)."""
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        from numpy.random import default_rng, randint
    """})
    assert checks_of(findings) == ["replay-determinism"]
    assert "randint" in findings[0].message
    assert "default_rng" not in findings[0].message


def test_determinism_fresh_seed_is_the_sanctioned_source(tmp_path):
    """The one sanctioned draw: fresh_seed() resolved at admission and
    journaled — no waiver needed at the call site."""
    findings = run_on(tmp_path, {"serving/journal.py": """
        from ..utils.seeds import fresh_seed

        def resolve_seed(requested):
            return requested if requested is not None else fresh_seed()
    """})
    assert findings == [], [f.render() for f in findings]


def test_determinism_flags_builtin_hash(tmp_path):
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        def bucket_of(user):
            return hash(user) % 64
    """})
    assert checks_of(findings) == ["replay-determinism"]
    assert "PYTHONHASHSEED" in findings[0].message
    assert "stable_hash" in findings[0].message


def test_determinism_flags_set_iteration_sorted_is_fine(tmp_path):
    findings = run_on(tmp_path, {"grammar/automaton.py": """
        KEYS = frozenset(("b", "a"))

        def canon_bad():
            return [k for k in KEYS]

        def canon_good():
            return [k for k in sorted(KEYS)]

        def canon_literal_bad(xs):
            out = []
            for x in set(xs):
                out.append(x)
            return out
    """})
    assert checks_of(findings) == ["replay-determinism", "replay-determinism"]
    assert all("iteration order" in f.message for f in findings)
    assert sorted(f.line for f in findings) == [5, 12]


def test_determinism_waiver_names_the_journaled_draw(tmp_path):
    findings = run_on(tmp_path, {"serving/journal.py": """
        import os

        def salt():
            # dlint: ok[replay-determinism] journaled in the admit record's salt field
            return os.urandom(4)
    """})
    assert findings == [], [f.render() for f in findings]


def test_determinism_out_of_scope_file_is_clean(tmp_path):
    findings = run_on(tmp_path, {"serving/qos.py": """
        import random

        def jitter():
            return random.random()
    """})
    assert "replay-determinism" not in checks_of(findings)


def test_determinism_membership_test_is_not_iteration(tmp_path):
    """`c in _WS` (the automaton's frozenset membership tests) is not an
    ordering hazard."""
    findings = run_on(tmp_path, {"grammar/automaton.py": """
        _WS = frozenset((9, 10, 13, 32))

        def is_ws(c):
            return c in _WS
    """})
    assert findings == [], [f.render() for f in findings]


def test_determinism_scope_files_exist_and_pin_the_satellite_fix():
    """Rot-guard: every declared scope file exists (a rename would
    silently blind the check), the scheduler's admit build still draws
    through fresh_seed, and app/dllama.py's no-seed cases route through
    it (the `args.seed or 0` / `or fresh_seed()` collapse this PR
    fixed)."""
    for rel in DET_SCOPE:
        assert (PACKAGE_ROOT / rel).exists(), rel
    sched = (PACKAGE_ROOT / "runtime" / "scheduler.py").read_text()
    assert "fresh_seed()" in sched
    cli = (PACKAGE_ROOT / "app" / "dllama.py").read_text()
    assert "args.seed or" not in cli, (
        "`args.seed or ...` collapses an explicit --seed 0 into the "
        "no-seed path"
    )
    # chat draws fresh entropy; train JOURNALS its draw in the ckpt dir
    # (durable resume, not a log-and-hope hint)
    assert "args.seed if args.seed is not None else fresh_seed()" in cli
    assert 'seed_file.write_text(f"{batch_seed}\\n")' in cli


# -- --changed mode ----------------------------------------------------------


def _git(cwd: Path, *args: str) -> None:
    subprocess.run(
        ["git", "-C", str(cwd), *args], check=True, capture_output=True,
        env={"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
             "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
             "HOME": str(cwd), "PATH": "/usr/bin:/bin:/usr/local/bin"},
    )


def _git_ok() -> bool:
    try:
        subprocess.run(["git", "--version"], capture_output=True, timeout=10)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


CLOCKY = """
    import time

    def stamp():
        return time.time()
"""


@pytest.mark.skipif(not _git_ok(), reason="git unavailable")
def test_changed_mode_lints_only_changed_files(tmp_path, capsys):
    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    (repo / "pkg" / "a.py").write_text(textwrap.dedent(CLOCKY))
    (repo / "pkg" / "b.py").write_text(textwrap.dedent(CLOCKY))
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    # modify b only; add an untracked c
    (repo / "pkg" / "b.py").write_text(
        textwrap.dedent(CLOCKY).replace("stamp", "stamp2")
    )
    (repo / "pkg" / "c.py").write_text(textwrap.dedent(CLOCKY))

    repo_root, changed = git_changed_files("HEAD", repo / "pkg")
    assert repo_root == repo.resolve()
    assert changed == {(repo / "pkg" / "b.py").resolve(),
                       (repo / "pkg" / "c.py").resolve()}

    rc = dlint_main(["--changed", "HEAD", str(repo / "pkg")])
    out = capsys.readouterr().out
    assert rc == 1  # clock findings in the changed files
    assert "b.py" in out and "c.py" in out
    assert "a.py" not in out  # unchanged: not re-linted
    assert "2 changed of 3 file(s)" in out

    # the full run still sees all three
    rc = dlint_main([str(repo / "pkg")])
    out = capsys.readouterr().out
    assert rc == 1 and "a.py" in out


@pytest.mark.skipif(not _git_ok(), reason="git unavailable")
def test_changed_mode_loads_the_whole_model(tmp_path, capsys):
    """Cross-file facts come from UNCHANGED files: a guarded-by
    declaration in committed a.py still convicts the fresh violation in
    changed b.py."""
    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    (repo / "pkg" / "a.py").write_text(textwrap.dedent("""
        import threading

        class Stats:
            _dlint_guarded_by = {("lock",): ("hits",)}

            def __init__(self):
                self.lock = threading.Lock()
                self.hits = 0
    """))
    (repo / "pkg" / "b.py").write_text("x = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    (repo / "pkg" / "b.py").write_text(textwrap.dedent("""
        def bump(s):
            s.hits += 1
    """))
    rc = dlint_main(["--changed", "HEAD", str(repo / "pkg")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "[guarded-by]" in out and "b.py:3" in out
    assert not any(  # a.py itself was not re-linted (the finding's
        # message may still NAME it as the decl site)
        line.split(":")[0].endswith("a.py")
        for line in out.splitlines() if "[" in line
    )


@pytest.mark.skipif(not _git_ok(), reason="git unavailable")
def test_changed_mode_bad_ref_is_a_usage_error(tmp_path, capsys):
    """A typo'd ref must error loudly (exit 2, git's own message), not
    silently degrade into a full run labelled 'git unavailable'."""
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "m.py").write_text(textwrap.dedent(CLOCKY))
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    rc = dlint_main(["--changed", "no-such-ref", str(repo)])
    err = capsys.readouterr().err
    assert rc == 2
    assert "no-such-ref" in err
    assert "falling back" not in err


def test_changed_mode_rejects_write_baseline(tmp_path, capsys):
    """--changed restricts findings to the diff; writing the baseline
    from that subset would silently un-baseline every other file."""
    (tmp_path / "m.py").write_text("x = 1\n")
    rc = dlint_main(["--changed", "HEAD", "--write-baseline", str(tmp_path)])
    assert rc == 2
    assert "cannot be combined" in capsys.readouterr().err


def test_determinism_set_names_resolve_per_scope(tmp_path):
    """A set-bound name in one function must not convict a same-named
    list iterated in another; module-level set bindings stay visible
    everywhere."""
    findings = run_on(tmp_path, {"fleet/migrate.py": """
        GLOBAL_KEYS = frozenset(("a", "b"))

        def f():
            pending = {1, 2}
            return max(pending)

        def g(items):
            pending = sorted(items)
            out = []
            for x in pending:
                out.append(x)
            for k in GLOBAL_KEYS:
                out.append(k)
            return out
    """})
    assert checks_of(findings) == ["replay-determinism"]
    assert "GLOBAL_KEYS" in findings[0].message  # g's list loop is clean


def test_check_only_still_reports_foreign_parse_failures(tmp_path):
    """A file outside check_only that fails to parse is a HOLE in the
    cross-file model — the parse finding must stay loud, or a --changed
    run reports clean against an incomplete lock/protocol model."""
    (tmp_path / "ok.py").write_text("x = 1\n")
    (tmp_path / "broken.py").write_text("def f(:\n")
    analyzer = Analyzer(default_checkers())
    findings = analyzer.run([tmp_path], baseline=set(), root=tmp_path,
                            check_only={(tmp_path / "ok.py").resolve()})
    assert [f.check for f in findings] == ["parse"]
    assert findings[0].path == "broken.py"


def test_changed_mode_falls_back_without_git(tmp_path, capsys):
    (tmp_path / "m.py").write_text(textwrap.dedent(CLOCKY))
    assert git_changed_files("HEAD", tmp_path) is None
    rc = dlint_main(["--changed", "HEAD", str(tmp_path)])
    err = capsys.readouterr()
    assert "falling back to a full run" in err.err
    assert rc == 1 and "m.py" in err.out  # full lint still ran


@pytest.mark.skipif(not _git_ok(), reason="git unavailable")
def test_changed_mode_paths_outside_the_repo_stay_checked(tmp_path, capsys):
    """A second analyzed path outside the anchored repo has no diff to
    consult — it must be linted in full, not silently skipped."""
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "a.py").write_text("x = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    outside = tmp_path / "outside"
    outside.mkdir()
    (outside / "m.py").write_text(textwrap.dedent(CLOCKY))
    rc = dlint_main(["--changed", "HEAD", str(repo), str(outside)])
    out = capsys.readouterr().out
    assert rc == 1 and "m.py" in out and "[clock]" in out


# -- the package-wide gate ----------------------------------------------------


def test_package_runs_all_three_new_checks_clean():
    """Acceptance: the three new checks run package-wide with zero
    findings and the baseline still empty (the shared gate in
    tests/test_dlint.py re-proves this for every check; here we pin that
    the new checkers are actually REGISTERED — a de-registration would
    keep that gate green)."""
    names = {c.name for c in default_checkers()}
    assert {"protocol", "protocol-manifest", "replay-determinism"} <= names
    assert analyze_paths() == []
