"""Fleet balancing: consistent-hash prefix affinity + load-based picks.

The routing brain of the ``dllama-router`` front-end (fleet/router.py).
Two selection modes over one replica table:

- **prefix affinity** — requests whose prompts share leading content
  blocks hash to the same replica, so same-system-prompt sessions land
  where the paged KV pool (runtime/kvpool.py) already holds the warm
  prefix pages and the prefix tree serves them by refcount bump instead
  of a fresh prefill. The key is a content-hash CHAIN over the prompt's
  leading fixed-size blocks — the router twin of kvpool's
  ``(parent_key, block_tokens)`` node-key chain, computed over request
  text instead of token ids (the router has no tokenizer; BPE is
  prefix-preserving over a shared leading system prompt, so equal text
  blocks imply equal token blocks). Placement uses a classic
  consistent-hash ring (virtual nodes per replica): when a replica
  leaves, only the keys it owned move (~1/N), so the fleet's warm-KV map
  survives membership churn instead of reshuffling wholesale.
- **least-loaded** — requests with no usable prefix (short prompts)
  go to the eligible replica with the smallest queue depth (free lanes
  break ties), from the queue_depth/lanes_free fields each replica's
  ``GET /load`` surface serves.

Eligibility folds in every per-replica signal the serving stack already
emits: a replica is skipped while it is **dead** (connect failures /
failed scrapes), **backing off** (a typed 429/503 shed's Retry-After is
honored — the router never hammers a replica that just said "not now"),
**draining** (SIGTERM flipped /health), or **breaker-open** (repeated
engine failures). The affinity ring simply walks past ineligible
replicas, which IS the consistent-hash failover: the key's placement
comes back the moment the replica does.

Pure stdlib, no jax/numpy — registered under dlint's host-sync scope and
the lock discipline (``_dlint_guarded_by``) like the rest of serving/.
"""

from __future__ import annotations

import bisect
import threading
import time
import zlib

from ..lockcheck import make_lock
from ..utils.faults import _mix64

# affinity block geometry: ~4 chars/token (the page_cost estimate's BPE
# density) x the pool's 64-token default page -> 256 chars per block; the
# chain covers at most DEFAULT_AFFINITY_BLOCKS leading blocks so a long
# shared system prompt maps to ONE key regardless of what follows it
DEFAULT_BLOCK_CHARS = 256
DEFAULT_AFFINITY_BLOCKS = 4
# virtual ring points per replica: enough that ownership splits within a
# few percent of 1/N without making ring rebuilds noticeable
DEFAULT_VNODES = 64
# a replica that refused a TCP connect (or failed a scrape) sits out at
# least this long before the router re-probes it inline
DEFAULT_DEAD_BACKOFF_S = 2.0


def stable_hash(data: bytes, seed: int = 0) -> int:
    """Deterministic 64-bit hash of ``data`` (crc32 folded through the
    splitmix64 finalizer — the same ``_mix64`` the fault plan and the
    Retry-After jitter use). Python's builtin ``hash`` is salted per
    process, which would reshuffle the whole ring on every router
    restart; this one is stable across processes and restarts, so a
    restarted router routes the same prefixes to the same replicas."""
    return _mix64(zlib.crc32(data, seed & 0xFFFFFFFF) + (seed << 32))


def prefix_key(text: str, block_chars: int = DEFAULT_BLOCK_CHARS,
               max_blocks: int = DEFAULT_AFFINITY_BLOCKS) -> int | None:
    """Content-hash chain over the prompt's leading full blocks — the
    affinity key. ``None`` when the prompt has no full block (nothing
    sharable enough to steer by; the caller balances by load instead).
    Chained like kvpool's tree keys: block b's hash folds the hash of
    blocks [0, b), so two prompts get the same key iff their leading
    ``min(full_blocks, max_blocks)`` blocks are identical."""
    data = text.encode("utf-8", "replace")
    n = min(len(data) // block_chars, max_blocks)
    if n <= 0:
        return None
    key = 0
    for b in range(n):
        key = zlib.crc32(data[b * block_chars:(b + 1) * block_chars], key)
    return _mix64(key + (n << 32))


class ReplicaState:
    """One replica's routing view: static identity plus the last-scraped
    load fields and the router's own failure bookkeeping. Mutated only
    by :class:`FleetBalancer` under its lock."""

    __slots__ = (
        "rid", "base", "queue_depth", "lanes_free", "lanes_total",
        "breaker", "draining", "pool_pages_free", "pool_parked_pages",
        "retry_until", "dead", "scrape_ok", "routed", "role",
    )

    def __init__(self, base: str, rid: str | None = None):
        self.base = str(base)  # "host:port"
        self.rid = str(rid or base)
        self.role = "mixed"  # "prefill" | "decode" | "mixed", from /load
        self.queue_depth = 0
        self.lanes_free = 0
        self.lanes_total = 0
        self.breaker = "closed"
        self.draining = False
        self.pool_pages_free = None
        self.pool_parked_pages = None
        self.retry_until = 0.0  # monotonic: honored Retry-After horizon
        self.dead = False  # connect refused / scrape failed
        self.scrape_ok = False  # at least one successful /load scrape
        self.routed = 0  # requests this router sent here

    def host_port(self) -> tuple[str, int]:
        host, _, port = self.base.rpartition(":")
        return host, int(port)


class FleetBalancer:
    """The replica table + consistent-hash ring + eligibility rules.

    Thread-safe: picks come from router request threads, load updates
    from the scrape thread, shed/death marks from both.
    """

    # dlint guarded-by declaration (analysis/lock_check.py): the replica
    # table, ring and counters move only under _lock — written by the
    # scrape thread and request threads, read by every pick and by the
    # router's /stats.
    _dlint_guarded_by = {
        ("_lock",): (
            "_fb_replicas", "_fb_ring", "_fb_affinity_routes",
            "_fb_affinity_hits", "_fb_load_routes", "_fb_sheds_honored",
        ),
    }

    def __init__(self, replicas: list[str] | dict[str, str],
                 vnodes: int = DEFAULT_VNODES,
                 dead_backoff_s: float = DEFAULT_DEAD_BACKOFF_S):
        """``replicas``: ``host:port`` list (each replica's id defaults
        to its address, matching the replica's own ``--replica-id``
        default) or an ``{id: host:port}`` mapping."""
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.vnodes = max(1, int(vnodes))
        self.dead_backoff_s = float(dead_backoff_s)
        self._lock = make_lock("FleetBalancer._lock")
        items = (
            replicas.items() if isinstance(replicas, dict)
            else ((None, base) for base in replicas)
        )
        self._fb_replicas: dict[str, ReplicaState] = {}
        for rid, base in items:
            state = ReplicaState(base, rid)
            if state.rid in self._fb_replicas:
                raise ValueError(f"duplicate replica id {state.rid!r}")
            self._fb_replicas[state.rid] = state
        # the ring: sorted (point, rid) pairs, vnodes points per replica.
        # Built once — membership is config; a dead replica stays ON the
        # ring and is walked past (that is what keeps the other replicas'
        # key ownership stable while it is gone).
        ring = []
        for rid in self._fb_replicas:
            for v in range(self.vnodes):
                ring.append((stable_hash(rid.encode(), seed=v), rid))
        ring.sort()
        self._fb_ring: list[tuple[int, str]] = ring
        self._fb_affinity_routes = 0  # picks that had an affinity key
        self._fb_affinity_hits = 0  # ...that landed on the ring owner
        self._fb_load_routes = 0  # keyless least-loaded picks
        self._fb_sheds_honored = 0  # Retry-After horizons recorded

    # -- eligibility ---------------------------------------------------------

    def _eligible_locked(self, state: ReplicaState, now: float,
                         exclude) -> bool:
        if state.rid in exclude:
            return False
        if now < state.retry_until:
            # dead-backoff or an honored Retry-After; past the horizon a
            # dead replica becomes eligible again for ONE inline probe —
            # a failure re-arms the backoff, a success clears dead
            return False
        if state.draining:
            return False
        if state.breaker != "closed":
            return False
        return True

    def ring_owner(self, key: int) -> str:
        """The key's ring placement ignoring eligibility — the replica
        that WOULD serve it in a healthy fleet (the affinity-hit-rate
        denominator, and the 1/N-movement property's subject)."""
        with self._lock:
            return self._ring_walk_locked(key, lambda s: True)

    def _ring_walk_locked(self, key: int, ok) -> str | None:
        ring = self._fb_ring
        i = bisect.bisect_left(ring, (key & 0xFFFFFFFFFFFFFFFF, ""))
        for step in range(len(ring)):
            point, rid = ring[(i + step) % len(ring)]
            if ok(self._fb_replicas[rid]):
                return rid
        return None

    # -- picks ---------------------------------------------------------------

    def pick(self, key: int | None = None,
             exclude: set[str] | frozenset = frozenset(),
             role: str | None = None) -> ReplicaState | None:
        """Choose a replica: by affinity ring when ``key`` is given (walk
        past ineligible replicas — consistent-hash failover), else least
        loaded. ``exclude`` holds replicas already tried this request.
        ``role`` restricts the pick to replicas advertising that role on
        their ``/load`` surface (disagg routing: long prompts ask for
        ``"prefill"``; the caller falls back to a role-free pick when
        no such replica is eligible — the monolithic path). ``None``
        when no replica is eligible (the router gives up with the
        aggregate 503 + the smallest Retry-After hint)."""
        now = time.monotonic()

        def ok(s: ReplicaState) -> bool:
            if role is not None and s.role != role:
                return False
            return self._eligible_locked(s, now, exclude)

        with self._lock:
            if key is not None:
                self._fb_affinity_routes += 1
                owner = self._ring_walk_locked(key, lambda s: True)
                rid = self._ring_walk_locked(key, ok)
                if rid is None:
                    return None
                if rid == owner:
                    self._fb_affinity_hits += 1
                state = self._fb_replicas[rid]
            else:
                candidates = [
                    s for s in self._fb_replicas.values() if ok(s)
                ]
                if not candidates:
                    return None
                self._fb_load_routes += 1
                state = min(
                    candidates,
                    key=lambda s: (
                        s.queue_depth, -s.lanes_free, s.routed, s.rid
                    ),
                )
            state.routed += 1
            return state

    def any_eligible(self) -> bool:
        """Non-mutating readiness probe (the router's /health): is at
        least one replica currently routable?"""
        now = time.monotonic()
        with self._lock:
            return any(
                self._eligible_locked(s, now, frozenset())
                for s in self._fb_replicas.values()
            )

    def min_retry_after_s(self) -> float:
        """The smallest outstanding backoff horizon across the fleet —
        the Retry-After hint a total give-up hands the client."""
        now = time.monotonic()
        with self._lock:
            horizons = [
                s.retry_until - now
                for s in self._fb_replicas.values()
                if s.retry_until > now
            ]
        return max(1.0, min(horizons)) if horizons else 1.0

    # -- signals -------------------------------------------------------------

    def update_load(self, rid: str, load: dict) -> None:
        """Fold one ``GET /load`` scrape into the table. A successful
        scrape clears the dead flag — the replica is reachable again."""
        with self._lock:
            state = self._fb_replicas.get(rid)
            if state is None:
                return
            state.queue_depth = int(load.get("queue_depth", 0) or 0)
            state.lanes_free = int(load.get("lanes_free", 0) or 0)
            state.lanes_total = int(load.get("lanes_total", 0) or 0)
            state.breaker = str(load.get("breaker", "closed"))
            state.draining = bool(load.get("draining", False))
            state.pool_pages_free = load.get("pool_pages_free")
            state.pool_parked_pages = load.get("pool_parked_pages")
            state.role = str(load.get("role", "mixed") or "mixed")
            state.dead = False
            state.scrape_ok = True

    def note_shed(self, rid: str, retry_after_s: float,
                  draining: bool = False) -> None:
        """A replica answered with a typed 429/503: honor its hint — no
        request routes there until the horizon passes (or a scrape says
        it recovered)."""
        until = time.monotonic() + max(0.05, float(retry_after_s))
        with self._lock:
            state = self._fb_replicas.get(rid)
            if state is None:
                return
            state.retry_until = max(state.retry_until, until)
            if draining:
                state.draining = True
            self._fb_sheds_honored += 1

    def note_dead(self, rid: str, backoff_s: float | None = None) -> None:
        """Connect refused / socket died mid-exchange: mark unreachable.
        The next successful scrape (or an inline probe after the
        backoff) brings it back."""
        until = time.monotonic() + (
            self.dead_backoff_s if backoff_s is None else float(backoff_s)
        )
        with self._lock:
            state = self._fb_replicas.get(rid)
            if state is None:
                return
            state.dead = True
            state.retry_until = max(state.retry_until, until)

    def note_scrape_failed(self, rid: str) -> None:
        """A /load scrape failed: treat like a connect failure (the
        scrape IS the liveness probe), but only once the replica ever
        scraped — a fleet booting up should not mark replicas dead
        before they finish binding."""
        with self._lock:
            state = self._fb_replicas.get(rid)
            if state is None or not state.scrape_ok:
                return
            state.dead = True
            state.retry_until = max(
                state.retry_until, time.monotonic() + self.dead_backoff_s
            )

    # -- introspection -------------------------------------------------------

    def replicas(self) -> list[ReplicaState]:
        with self._lock:
            return list(self._fb_replicas.values())

    def get(self, rid: str) -> ReplicaState | None:
        with self._lock:
            return self._fb_replicas.get(rid)

    def stats(self) -> dict:
        """Routing counters + the per-replica table for the router's
        /stats (bridged to its /metrics like the replica surfaces)."""
        now = time.monotonic()
        with self._lock:
            return {
                "fleet_replicas": len(self._fb_replicas),
                "fleet_affinity_routes": self._fb_affinity_routes,
                "fleet_affinity_hits": self._fb_affinity_hits,
                "fleet_load_routes": self._fb_load_routes,
                "fleet_sheds_honored": self._fb_sheds_honored,
                "fleet_replica_table": {
                    s.rid: {
                        "base": s.base,
                        "role": s.role,
                        "queue_depth": s.queue_depth,
                        "lanes_free": s.lanes_free,
                        "lanes_total": s.lanes_total,
                        "breaker": s.breaker,
                        "draining": s.draining,
                        "dead": s.dead,
                        "backing_off": s.retry_until > now,
                        "routed": s.routed,
                    }
                    for s in self._fb_replicas.values()
                },
            }
